"""Headline benchmark — prints ONE JSON line for the driver.

Measures tokens/sec/chip for a GPT-2 125M training step under the
amp-O2-equivalent policy (bf16 compute, fp32 master weights) + fused Adam —
BASELINE.json config 1's model under the north-star's optimizer/precision
recipe.

``vs_baseline``: the reference publishes no numbers (BASELINE.md); the
comparator is a literature proxy for a single A100 running a 124M GPT-2
with torch+apex-class mixed precision: ~1.5e5 tokens/sec. vs_baseline =
measured / proxy, so >1.0 means beating the A100-class number per chip.
"""

import json
import math
import time

import jax
import jax.numpy as jnp
import numpy as np

A100_PROXY_TOKENS_PER_SEC = 150_000.0


def main():
    from apex1_tpu.amp import Amp
    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.gpt2 import GPT2, GPT2Config, gpt2_loss_fn
    from apex1_tpu.optim.fused_adam import fused_adam

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    if on_accel:
        B, S = 8, 1024
        cfg = GPT2Config(policy=get_policy("O2"))  # full 125M
        iters = 10  # warmup = one identical (cached) run of the same loop
    else:  # CPU smoke mode: tiny model, same code path
        B, S = 2, 128
        cfg = GPT2Config.tiny(policy=get_policy("O2"))
        iters = 3

    model = GPT2(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (B, S)),
        jnp.int32)
    params = jax.jit(model.init)(jax.random.key(0), tokens)["params"]

    amp = Amp(tx=fused_adam(1e-4, weight_decay=0.01), opt_level="O2")
    state = amp.init(params)
    del params
    train_step = amp.make_train_step(gpt2_loss_fn(model))

    # The whole measured run is ONE dispatch: iters steps ride a
    # lax.fori_loop on-device, so host→device dispatch latency (large and
    # variable on tunneled backends) cannot pollute the steady-state
    # number; the final sync is a host readback of the last loss.
    def many_steps(state, n):
        def body(_, carry):
            st, _m = carry
            return train_step(st, tokens)
        return jax.lax.fori_loop(0, n, body,
                                 train_step(state, tokens))

    many = jax.jit(many_steps, static_argnums=1, donate_argnums=0)

    @jax.jit
    def _reduce_all(tree):
        # one scalar whose dataflow covers EVERY output leaf: on the axon
        # tunnel backend, reading back a single output does not imply the
        # whole program ran
        return sum(jnp.sum(leaf.astype(jnp.float32))
                   for leaf in jax.tree.leaves(tree))

    # warmup with the SAME static n so the timed call hits the jit cache
    state, metrics = many(state, iters - 1)
    float(_reduce_all((state, metrics)))       # compiles the sync too

    t0 = time.perf_counter()
    state, metrics = many(state, iters - 1)    # n loop iters + 1 leading
    float(_reduce_all((state, metrics)))       # hard sync, full tree
    dt = time.perf_counter() - t0
    loss = float(metrics["loss"])
    if not math.isfinite(loss):
        raise SystemExit(f"benchmark loss is not finite: {loss}")

    tokens_per_sec = B * S * iters / dt
    print(json.dumps({
        "metric": f"tokens/sec/chip GPT-2-125M amp-O2 fused_adam "
                  f"[{backend}]" if on_accel else
                  f"tokens/sec/chip GPT-2(tiny smoke) amp-O2 fused_adam "
                  f"[{backend}]",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tokens_per_sec / A100_PROXY_TOKENS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
