"""Headline benchmark — prints ONE JSON line for the driver.

Default (``--config gpt2``, what the driver runs): tokens/sec/chip for a
GPT-2 125M training step under the amp-O2-equivalent policy (bf16 compute,
fp32 master weights) + fused Adam — BASELINE.json config 1's model under
the north-star's optimizer/precision recipe.

Other BASELINE configs are measurable with ``--config``:
  bert           config 2: BERT-base pretrain (MLM+NSP), fused LN + Adam
  bert_large     the north-star model size (BERT-large, 340M) at B=4
  resnet         config 3: ResNet-50 train step (BN; SyncBN's collective
                 parity is covered by tests — single-chip bench has dp=1)
  llama_longctx  config 5: long-context decoder, Pallas flash attention +
                 fused RoPE + remat, S=16k. Width is TinyLlama-class
                 (2048 hidden, 16 layers, ~0.8B) because Llama-3-8B +
                 Adam state does not fit one 16 GB chip (sizes verified
                 by tools/aot_check.py AOT memory analysis) — the
                 per-token attention/kernel work is the benchmarked path.

``vs_baseline``: the reference publishes no numbers (BASELINE.md); the
denominator is the PINNED A100 comparator from BASELINE.md "Pinned A100
comparator" — stated-assumption arithmetic (40%-MFU A100 for training,
0.6x HBM roofline for decode, NGC-class figure for ResNet). >= 1.0 is
the north-star "match A100" inequality; on the v5e bench chip, 0.63
(training) / 0.40 (decode) is already per-spec parity (see BASELINE.md
chip-context note).

Timing methodology: the measured run is ONE dispatch — iters steps ride a
``lax.fori_loop`` on device, so host→device dispatch latency (large and
variable on tunneled backends) cannot pollute the steady state; warmup is
an identical (jit-cached) call; the sync is a full-tree readback-bearing
reduction.
"""

import argparse
import functools
import json
import math
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def probe_backend(timeout_s=180.0, retries=3, backoff=20.0):
    """Initialize the backend in a SUBPROCESS first: on a dead axon tunnel,
    in-process init blocks uninterruptibly (BENCH_r01 died rc=1 with no
    output), while a subprocess can be killed and retried with backoff.
    Returns (backend_name_or_None, last_stderr_tail)."""
    # honor JAX_PLATFORMS through jax.config: the container sitecustomize
    # pins jax_platforms=axon,cpu, which silently overrides the env var
    code = ("import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
            "p and jax.config.update('jax_platforms', p); "
            "d = jax.devices(); print('BACKEND=' + jax.default_backend())")
    stderr_tail = ""
    for attempt in range(retries):
        try:
            out = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True,
                                 timeout=timeout_s)
            stderr_tail = (out.stderr or "")[-400:]
            for line in out.stdout.splitlines():
                if line.startswith("BACKEND="):
                    return line.split("=", 1)[1], stderr_tail
        except subprocess.TimeoutExpired as e:
            stderr_tail = ((e.stderr or b"").decode("utf-8", "replace")
                           if isinstance(e.stderr, bytes)
                           else (e.stderr or ""))[-400:] or "probe timeout"
            # a KILLED probe can leave a stale libtpu lockfile that wedges
            # the next probe (and any AOT client) — but only remove it if
            # no live client still holds the flock
            _remove_stale_libtpu_lockfile()
        if attempt < retries - 1:
            time.sleep(backoff * (2 ** attempt))
    return None, stderr_tail


def _remove_stale_libtpu_lockfile(path="/tmp/libtpu_lockfile"):
    """Remove the libtpu multi-client lockfile only when it is STALE —
    i.e. no live process holds the flock (a live holder means another
    client owns the chip; deleting its lockfile would let two libtpu
    clients collide on one device)."""
    import fcntl
    try:
        fd = os.open(path, os.O_RDWR)
    except OSError:
        return
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)  # held? -> OSError
        os.remove(path)
    except OSError:
        pass
    finally:
        os.close(fd)


def timed_steps(train_step, state, batch, iters, *, profile_dir=None):
    """(seconds/step, flops/step, final metrics, final state) with the
    loop in one dispatch.

    The many-step loop is AOT-lowered so ``cost_analysis`` can price one
    dispatch (→ MFU) without a second compile; the sync reduction covers
    every output leaf because on the tunneled backend reading back one
    output does not imply the whole program ran.

    ``profile_dir``: capture ONE extra (untimed) dispatch under
    ``jax.profiler.trace`` into this directory after the measured run —
    the ROADMAP-5 flywheel's trace-banking hook (a hardware window
    leaves a per-op breakdown artifact next to every record instead of
    a number alone). Profiling failure is swallowed: a trace must never
    cost the measurement."""

    def many_steps(state):
        def body(_, carry):
            st, _m = carry
            return train_step(st, *batch)
        return jax.lax.fori_loop(0, iters - 1, body,
                                 train_step(state, *batch))

    compiled = jax.jit(many_steps, donate_argnums=0).lower(state).compile()
    flops_per_step = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops_per_step = float(cost["flops"]) / iters
    except Exception:
        pass  # cost model unavailable on some backends — MFU omitted

    @jax.jit
    def _reduce_all(tree):
        return sum(jnp.sum(leaf.astype(jnp.float32))
                   for leaf in jax.tree.leaves(tree))

    state, metrics = compiled(state)           # warmup (same executable)
    float(_reduce_all((state, metrics)))       # compiles the sync too

    # the spine StopWatch is the repo's ONE host-side timing primitive
    # (same machinery as utils.observability.Timers and the serving
    # clock); the full-tree float() reduction above IS the hard sync,
    # so no sync tree is passed here
    from apex1_tpu.obs import spine
    sw = spine.StopWatch().start()
    state, metrics = compiled(state)           # n loop iters + 1 leading
    float(_reduce_all((state, metrics)))       # hard sync, full tree
    dt = sw.stop()
    spine.emit("span", "bench.timed_steps", dur_s=round(dt, 6),
               iters=iters, step_s=round(dt / iters, 6))
    loss = float(metrics["loss"])
    if not math.isfinite(loss):
        raise RuntimeError(f"benchmark loss is not finite: {loss}")
    if profile_dir:
        try:
            os.makedirs(profile_dir, exist_ok=True)
            # the profiled dispatch runs on a COPY (donate_argnums=0
            # would otherwise eat the state we return) and its outputs
            # are discarded — the returned metrics/state and any banked
            # checkpoint stay exactly the measured run's, profiled or
            # not
            state_copy = jax.tree_util.tree_map(jnp.copy, state)
            with jax.profiler.trace(profile_dir):
                prof_out = compiled(state_copy)
                float(_reduce_all(prof_out))
            del prof_out
        except Exception as e:
            print(f"WARNING: profile capture failed ({e}); record will "
                  f"carry no artifact", file=sys.stderr, flush=True)
    # final metrics + state ride along so configs can surface state
    # evidence (fp16 O1: skipped_steps + final loss_scale) and bank a
    # resume checkpoint of the trained state (--ckpt-dir)
    return dt / iters, flops_per_step, metrics, state


def _amp_state_step(model_loss_fn, params, lr=1e-4, opt_level="O2"):
    from apex1_tpu.amp import Amp
    from apex1_tpu.optim.fused_adam import fused_adam

    amp = Amp(tx=fused_adam(lr, weight_decay=0.01), opt_level=opt_level)
    return amp.init(params), amp.make_train_step(model_loss_fn)


def bench_gpt2(on_accel, batch=None, seq=None, fp16=False):
    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.gpt2 import GPT2, GPT2Config, gpt2_loss_fn

    # fp16=True: the O1_fp16 policy — fp16 compute, fp32 fragile ops,
    # DYNAMIC loss scaling with skip-on-overflow (half the reference's
    # reason to exist; VERDICT Weak #8 wanted hardware evidence with the
    # skip-step count and final loss-scale in the record)
    level = "O1_fp16" if fp16 else "O2"
    if on_accel:
        # B=16 AOT-verified on v5e (8.2 GiB incl. donated args; B=8 left
        # the MXU underfed — tools/aot_check.py sized both)
        B, S, iters = batch or 16, seq or 1024, 10
        cfg = GPT2Config(policy=get_policy(level),
                         max_seq_len=max(S, 1024))
    else:
        B, S, iters = batch or 2, seq or 128, 3
        cfg = GPT2Config.tiny(policy=get_policy(level),
                              max_seq_len=max(S, 128))
    model = GPT2(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (B, S)),
        jnp.int32)
    params = jax.jit(model.init)(jax.random.key(0), tokens)["params"]
    state, step = _amp_state_step(gpt2_loss_fn(model), params,
                                  opt_level=level)
    name = "GPT-2-125M" if on_accel else "GPT-2(tiny smoke)"
    return (state, step, (tokens,), B * S, iters,
            f"tokens/sec/chip {name} amp-{level} fused_adam",
            "tokens/sec/chip",
            145_000.0)   # BASELINE.md pinned A100 row: gpt2


def bench_bert(on_accel, large=False, dropout=0.0):
    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.bert import (BertConfig, BertPretrain,
                                       bert_pretrain_loss_fn)

    if on_accel:
        B, S, iters = (4, 512, 8) if large else (8, 512, 10)
        mk = BertConfig.bert_large if large else BertConfig.bert_base
        cfg = mk(policy=get_policy("O2"), dropout=dropout)
    else:
        B, S, iters = 2, 64, 3
        cfg = BertConfig.tiny(policy=get_policy("O2"), dropout=dropout)
    model = BertPretrain(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S)), jnp.int32)
    mlm_labels = jnp.asarray(
        np.where(rng.random((B, S)) < 0.15,
                 rng.integers(0, cfg.vocab_size, (B, S)), -1), jnp.int32)
    batch = {"tokens": tokens, "mlm_labels": mlm_labels,
             "nsp_labels": jnp.asarray(rng.integers(0, 2, (B,)), jnp.int32)}
    if dropout > 0.0:
        # presence of the key ACTIVATES the in-kernel dropout paths
        # (flash attention-probability dropout + fused dropout-add-LN
        # epilogues). One fixed key per run: every timed step draws the
        # same masks — the PRNG work is identical per step, which is
        # what the throughput number prices; training would thread a
        # fresh key per step.
        batch["dropout_rng"] = jax.random.key(1234)
    params = jax.jit(model.init)(jax.random.key(0), tokens)["params"]
    state, step = _amp_state_step(bert_pretrain_loss_fn(model), params)
    name = (("BERT-large-pretrain" if large else "BERT-base-pretrain")
            if on_accel else "BERT(tiny smoke)")
    if dropout > 0.0:
        name += f"-dropout{dropout}"
    # BASELINE.md pinned A100 rows: bert_large / bert
    proxy = 57_500.0 if large else 173_000.0
    return (state, step, (batch,), B * S, iters,
            f"tokens/sec/chip {name} amp-O2 fused_adam", "tokens/sec/chip",
            proxy)


def bench_resnet(on_accel):
    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.resnet import ResNet, ResNetConfig
    from apex1_tpu.ops import softmax_cross_entropy_loss

    if on_accel:
        B, HW, iters = 64, 224, 10
        cfg = ResNetConfig.resnet50(policy=get_policy("O2"))
    else:
        B, HW, iters = 2, 32, 3
        cfg = ResNetConfig.tiny(policy=get_policy("O2"))
    model = ResNet(cfg)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(B, HW, HW, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, cfg.num_classes, (B,)), jnp.int32)
    variables = jax.jit(model.init)(jax.random.key(0), images)
    bn0 = variables.get("batch_stats", {})

    def loss_fn(params, images, labels, bn):
        logits, upd = model.apply(
            {"params": params, "batch_stats": bn}, images,
            mutable=["batch_stats"])
        loss = jnp.mean(softmax_cross_entropy_loss(
            logits.astype(jnp.float32), labels))
        return loss, upd["batch_stats"]

    from apex1_tpu.amp import Amp
    from apex1_tpu.optim.fused_sgd import fused_sgd

    amp = Amp(tx=fused_sgd(0.1, momentum=0.9, weight_decay=1e-4),
              opt_level="O2")
    state = amp.init(variables["params"])
    inner = amp.make_train_step(loss_fn, has_aux=True)

    def step(carry, images, labels):
        st, bn = carry
        st, metrics = inner(st, images, labels, bn)
        return (st, metrics["aux"]), metrics

    name = "ResNet-50" if on_accel else "ResNet(tiny smoke)"
    return ((state, bn0), step, (images, labels), B, iters,
            f"images/sec/chip {name} amp-O2 fused_sgd", "images/sec/chip",
            2_900.0)   # BASELINE.md pinned A100 row: resnet (NGC-class)


def _bench_llama(on_accel, *, accel_cfg, accel_bsi, tiny_seq, name, proxy):
    """Shared scaffolding for the Llama-family configs below."""
    import dataclasses

    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.llama import Llama, LlamaConfig, llama_loss_fn

    if on_accel:
        B, S, iters = accel_bsi
        cfg = accel_cfg(get_policy("O2"), S)
    else:
        B, S, iters = 1, tiny_seq, 2
        cfg = dataclasses.replace(
            LlamaConfig.tiny(policy=get_policy("O2")), max_seq_len=S,
            remat=True)
        name = "Llama(tiny smoke)"
    model = Llama(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (B, S)),
        jnp.int32)
    params = jax.jit(model.init)(jax.random.key(0), tokens)["params"]
    state, step = _amp_state_step(llama_loss_fn(model), params)
    return (state, step, (tokens,), B * S, iters,
            f"tokens/sec/chip {name} amp-O2 remat", "tokens/sec/chip",
            proxy)


def bench_llama_longctx(on_accel):
    from apex1_tpu.models.llama import LlamaConfig

    # 16 layers: AOT memory analysis (tools/aot_check.py) showed the
    # 22-layer variant needs 18.7 GiB on a 15.75 GiB v5e (Adam state
    # dominates); 16 layers compiles at ~14.4 GiB with margin
    return _bench_llama(
        on_accel,
        accel_cfg=lambda pol, S: LlamaConfig(
            vocab_size=32000, max_seq_len=S, num_layers=16,
            num_heads=32, num_kv_heads=4, hidden_size=2048,
            ffn_size=5632, remat=True, policy=pol),
        accel_bsi=(1, 16384, 4), tiny_seq=512,
        name="Llama-0.8B-16k-flash",
        proxy=11_100.0)   # BASELINE.md pinned A100 row: llama_longctx


def bench_llama_block(on_accel):
    """BASELINE config 4's single-chip proxy (VERDICT r2 item 6): a
    Llama-3-8B-WIDTH decoder stack (hidden 4096, ffn 14336, 32 heads /
    8 KV, full flash + fused RoPE/RMSNorm/CE path) at the depth that fits
    one chip with full Adam state — tp=pp=1, remat. Times the exact
    per-layer fused stack the dp2×pp2×tp4 flagship runs per stage, so
    tokens/sec here × (depth ratio) bounds the full-model per-chip rate.
    3 layers + 32k-vocab embedding/head ≈ 0.9B params ≈ 11 GiB Adam
    state on a 16 GiB v5e."""
    from apex1_tpu.models.llama import LlamaConfig

    return _bench_llama(
        on_accel,
        accel_cfg=lambda pol, S: LlamaConfig(
            vocab_size=32000, max_seq_len=S, num_layers=3,
            num_heads=32, num_kv_heads=8, hidden_size=4096,
            ffn_size=14336, remat=True, policy=pol),
        accel_bsi=(2, 4096, 6), tiny_seq=256,
        name="Llama-8B-width-3L",
        proxy=20_800.0)   # BASELINE.md pinned A100 row: llama_block


def bench_t5(on_accel):
    """Beyond-BASELINE: T5-large-class encoder-decoder (the enc-dec family
    the reference's variable-shape pipeline machinery serves) — rel-pos
    bias on the Pallas fused-softmax path + flash cross-attention + fused
    tied-head CE. Sized to fit one v5e with full Adam state (12 enc + 12
    dec layers at d_model 1024 ≈ 0.4B params)."""
    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.t5 import T5, T5Config, t5_loss_fn

    if on_accel:
        B, S_enc, S_dec, iters = 8, 512, 512, 8
        cfg = T5Config.t5_large(policy=get_policy("O2"),
                                num_encoder_layers=12,
                                num_decoder_layers=12, remat=True)
    else:
        B, S_enc, S_dec, iters = 2, 32, 32, 3
        cfg = T5Config.tiny(policy=get_policy("O2"))
    model = T5(cfg)
    rng = np.random.default_rng(0)
    enc = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S_enc)),
                      jnp.int32)
    dec = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S_dec)),
                      jnp.int32)
    params = jax.jit(model.init)(jax.random.key(0), enc, dec)["params"]
    state, step = _amp_state_step(t5_loss_fn(model), params)
    name = "T5-0.4B-encdec" if on_accel else "T5(tiny smoke)"
    return (state, step, (enc, dec), B * (S_enc + S_dec), iters,
            f"tokens/sec/chip {name} amp-O2 fused_adam", "tokens/sec/chip",
            48_000.0)   # BASELINE.md pinned A100 row: t5


def bench_decode(on_accel, quant=False):
    """Serving-path decode throughput (beyond-BASELINE; the reference is
    training-only): KV-cached autoregressive generation through
    `models.generate` — prefill + a fixed number of single-dispatch
    decode steps per measured "step". ``quant=True`` times the int8
    weight-only path (`models.quant_decode`): decode is HBM-bound, so
    int8 weights should approach 2x the bf16 tokens/sec at small batch.

    Comparator: BASELINE.md pinned A100 decode rows — the 0.8B model's
    weight-streaming HBM roofline at B=8 x 0.6 achieved bandwidth
    (bf16 6.1k tok/s, int8 12.2k). Not a measured A100 run; the
    assumptions are stated in BASELINE.md and the int8 row credits the
    comparator with its own int8 path.
    """
    import functools as ft

    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.generate import generate, llama_decoder
    from apex1_tpu.models.llama import Llama, LlamaConfig
    from apex1_tpu.models.quant_decode import llama_quant_decoder

    if on_accel:
        B, S0, N, iters = 8, 128, 128, 3
        cfg = LlamaConfig(vocab_size=32000, max_seq_len=S0 + N + 8,
                          num_layers=16, num_heads=32, num_kv_heads=4,
                          hidden_size=2048, ffn_size=5632,
                          policy=get_policy("O2"))
        name = "Llama-0.8B-decode"
    else:
        B, S0, N, iters = 2, 8, 8, 2
        cfg = LlamaConfig.tiny(policy=get_policy("O2"), max_seq_len=32)
        name = "Llama(tiny smoke)-decode"
    model = Llama(cfg)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S0)),
                         jnp.int32)
    params = jax.jit(model.init)(jax.random.key(0), prompt)["params"]
    if quant:
        apply_fn, make_cache, decode_params = llama_quant_decoder(
            model, params)
        name += "-int8"
    else:
        apply_fn, make_cache = llama_decoder(model)
        decode_params = params

    gen = ft.partial(generate, apply_fn, max_new_tokens=N,
                     vocab_size=cfg.vocab_size)

    def step(state, prompt):
        (decode_params,) = state
        toks = gen(decode_params, prompt,
                   cache=make_cache(B, S0 + N + 1))
        # a finite scalar for the harness's loss check / full-tree sync
        metrics = {"loss": jnp.mean(toks.astype(jnp.float32))}
        return state, metrics

    # BASELINE.md pinned A100 rows: decode / decode_int8
    proxy = 12_200.0 if quant else 6_100.0
    return ((decode_params,), step, (prompt,), B * N, iters,
            f"decode tokens/sec/chip {name}", "tokens/sec/chip",
            proxy)


def bench_llama_3d(on_accel, plan=None):
    """The planner-driven 3D config: layout chosen by
    `apex1_tpu.planner` for THIS process's device count (or replayed
    from a banked plan via --plan), then the full
    `models.llama_3d.make_train_step` composition driven end-to-end
    from the emitted spec. On one CPU device the planner degenerates
    to the all-ones layout — the smoke proves the plan->mesh->specs->
    step path, the multi-chip number is the hardware queue's
    (`planner_ab`)."""
    import dataclasses

    from apex1_tpu import planner
    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.llama import LlamaConfig
    from apex1_tpu.models.llama_3d import make_train_step

    n = jax.device_count()
    if on_accel:
        # the llama_longctx-class 0.8B at trainable depth; global
        # batch sized so every dp split up to n stays feasible
        mcfg = LlamaConfig(vocab_size=32000, max_seq_len=2048,
                           num_layers=8, num_heads=32, num_kv_heads=4,
                           hidden_size=2048, ffn_size=5632, remat=True,
                           policy=get_policy("O2"))
        global_batch, iters = 4 * n, 6
    else:
        mcfg = dataclasses.replace(
            LlamaConfig.tiny(policy=get_policy("O2")), max_seq_len=128,
            remat=True)
        global_batch, iters = 4 * n, 2
    shape = planner.ModelShape.from_llama(mcfg, name="llama_3d",
                                          global_batch=global_batch)
    gen = None
    if on_accel:
        from apex1_tpu.core.capability import get_capability
        gen = get_capability().generation
    if plan is None:
        plan = planner.make_plan(shape, n, generation=gen,
                                 allow_zero=False)
    else:
        plan = planner.load_plan(plan)
        # a replayed plan must price THIS model and cover THIS mesh —
        # and the record's tokens/step must follow the PLAN's
        # schedule, not the live-derived default batch
        mismatch = planner.check_plan_model(plan, shape)
        if plan["n_devices"] != n:
            mismatch.append(f"n_devices: plan={plan['n_devices']} "
                            f"live={n}")
        if mismatch:
            raise ValueError(
                "--plan was searched for a different model/mesh than "
                "this bench builds: " + "; ".join(mismatch))
        shape = dataclasses.replace(
            shape, global_batch=plan["model"]["global_batch"])
    m = plan["mesh"]
    print(f"planner pick: dp={m['dp']} pp={m['pp']} cp={m['cp']} "
          f"ep={m['ep']} tp={m['tp']} "
          f"M={plan['schedule']['num_microbatches']} — "
          f"{plan['predicted']['calibrated_step_ms']:.2f} ms/step "
          f"calibrated", flush=True)
    cfg = planner.llama3d_config_from_plan(plan, mcfg)
    step, state, _ = make_train_step(cfg)
    rng = np.random.default_rng(0)
    dshape = (cfg.num_microbatches, mcfg.max_seq_len,
              cfg.microbatch_size * cfg.dp * cfg.ep)
    tokens = jnp.asarray(rng.integers(0, mcfg.vocab_size, dshape),
                         jnp.int32)
    labels = jnp.roll(tokens, -1, axis=1)

    def loss_step(state, tokens, labels):
        state, loss = step(state, tokens, labels)
        return state, {"loss": loss}

    tokens_per_step = shape.tokens_per_step
    return (state, loss_step, (tokens, labels), tokens_per_step // n,
            iters,
            f"tokens/sec/chip Llama-3D(planned x{n}) amp-O2 remat",
            "tokens/sec/chip",
            11_100.0)   # vs the pinned llama_longctx A100 row: the
    #                     nearest hand-tuned comparator until the
    #                     planner A/B banks its own


BENCHES = {
    "gpt2": bench_gpt2,
    "gpt2_fp16": functools.partial(bench_gpt2, fp16=True),
    "bert": bench_bert,
    "bert_dropout": functools.partial(bench_bert, dropout=0.1),
    "bert_large": functools.partial(bench_bert, large=True),
    "resnet": bench_resnet,
    "llama_longctx": bench_llama_longctx,
    "llama_block": bench_llama_block,
    "llama_3d": bench_llama_3d,
    "t5": bench_t5,
    "decode": bench_decode,
    "decode_int8": functools.partial(bench_decode, quant=True),
}

#: configs whose mesh comes from the planner + the LIVE device count:
#: excluded from tools/predict_perf.py's single-chip AOT table (the
#: planner's own cost engine prices them) so the banked
#: predicted_*.json rows stay byte-stable
PLANNED_BENCHES = {"llama_3d"}


def _emit(record, out_path=None):
    """The ONE JSON line the driver parses — also on partial failure.

    ``out_path``: crash-safe partial banking for sweeps — the record is
    ALSO written to this file via temp-file + atomic rename, so a sweep
    killed between configs still banks every completed record (a
    half-written JSON file can never exist at ``out_path``). Inline
    copy of `resilience.manifest.atomic_write_text` on purpose: this
    fallback path must not depend on importing the package it may be
    reporting a failure of."""
    print(json.dumps(record), flush=True)
    if not out_path:
        return
    try:
        out_path = os.path.abspath(out_path)
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        tmp = f"{out_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(record, f)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, out_path)
    except OSError as e:   # banking is best-effort; stdout already has it
        print(f"WARNING: could not bank record to {out_path}: {e}",
              file=sys.stderr, flush=True)


# perf_results/ log names per config (tools/tpu_watch.sh queue names;
# a config with several queue entries lists every log it lands in)
_BANKED_LOGS = {
    "bert": ["bench_bert.log"],
    "bert_dropout": ["bench_bert_drop.log"],
    "bert_large": ["bench_bert_lg.log"],
    "decode": ["bench_decode.log"],
    "decode_int8": ["bench_dec_int8.log"],
    "gpt2": ["bench_gpt2.log", "bench_gpt2_b24.log"],
    "gpt2_fp16": ["bench_gpt2_fp16.log"],
    "llama_3d": ["bench_llama3d.log"],
    "llama_block": ["bench_llama_blk.log"],
    "llama_longctx": ["bench_llama16k.log"],
    "resnet": ["bench_resnet.log"],
    "t5": ["bench_t5.log"],
}


def _last_banked(config, results_dir=None):
    """Best on-silicon JSON record for ``config`` across the tee'd
    queue logs in perf_results/, or None. Only records that carry a
    real measurement (nonzero value from a tpu backend) qualify; among
    qualifying records the highest value wins (the headline contract —
    the queue logs carry no timestamps to order by)."""
    if results_dir is None:
        results_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "perf_results")
    best = None
    for name in _BANKED_LOGS.get(config, ()):
        path = os.path.join(results_dir, name)
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not (line.startswith("{") and line.endswith("}")):
                        continue
                    try:
                        cand = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    val = cand.get("value")
                    if isinstance(val, bool) \
                            or not isinstance(val, (int, float)) \
                            or not math.isfinite(val) or not val:
                        continue
                    if "[tpu]" not in cand.get("metric", ""):
                        continue
                    if best is None or cand["value"] > best["value"]:
                        cand["source_log"] = f"perf_results/{name}"
                        best = cand
        except OSError:
            continue
    if best is not None:
        # the record states its own selection rule: it is the BEST value
        # across every banked log for the config (any shape), not the
        # most recent run at the standard shape (ADVICE r5)
        best["selection"] = "max across queue logs"
    return best


def _predicted_row(config, results_dir=None):
    """The ``config`` step row of the newest banked prediction table
    (perf_results/predicted_*.json, written by tools/predict_perf.py),
    or None (never raises — the always-emit contract must not depend on
    this)."""
    import glob

    if results_dir is None:
        results_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "perf_results")
    paths = glob.glob(os.path.join(results_dir, "predicted_*.json"))
    if not paths:
        return None
    try:
        # newest by mtime — lexicographic order breaks at r10 vs r9
        path = max(paths, key=os.path.getmtime)
        with open(path) as f:
            doc = json.load(f)
        return next(r for r in doc.get("steps", [])
                    if r.get("name") == config and "flops" in r)
    except (StopIteration, OSError, KeyError, ValueError,
            json.JSONDecodeError):
        return None


def _predicted_rate(config, results_dir=None):
    """Roofline-predicted units/sec for ``config`` from the newest banked
    prediction table, priced at the CURRENT chip's capability row. The
    comms term rides along: a row carrying ``ici_exposed_bytes`` (ICI
    traffic NOT hidden behind compute — tools/predict_perf.py's overlap
    model) ADDS that exposed transfer time, so `roofline_ratio` prices
    a serialized-collective program honestly instead of crediting the
    transfer as free. None when no prediction is banked."""
    row = _predicted_row(config, results_dir)
    if row is None:
        return None
    try:
        from apex1_tpu.core.capability import get_capability, ici_link_gbps
        cap = get_capability()
        t_pred = max(row["flops"] / (cap.bf16_tflops * 1e12),
                     row["bytes"] / (cap.hbm_gbps * 1e9))
        exposed = row.get("ici_exposed_bytes", 0.0)
        if exposed:
            link = ici_link_gbps()
            if link:
                t_pred += exposed / (link * 1e9)
        if t_pred <= 0:
            return None
        return row["units_per_step"] / t_pred
    except (OSError, KeyError, ValueError, TypeError):
        return None


def _attach_roofline(record, config, results_dir=None):
    """Add ``predicted`` (roofline units/sec) + ``roofline_ratio``
    (value / predicted — the localizer metric: < ~0.5 means a kernel or
    schedule is leaving real performance on the floor, see
    tools/predict_perf.py) to a record with a nonzero value. ON-SILICON
    records only: a cpu smoke run measures tiny auto-shrunk shapes, so
    a ratio against the accelerator-shape prediction would be noise
    dressed as a score.

    When a banked calibration table exists (``apex1_tpu.obs.calibrate``
    — perf_results/calibration.json, TPU-backed factors only), the
    record ALSO carries ``calibrated_predicted`` (the analytic rate
    corrected by the config's fitted slowdown) and
    ``calibrated_ratio`` (value / calibrated_predicted — ≈1.0 means
    "performing as banked silicon history says"; a drop below ~0.9 is
    a REGRESSION signal even when the raw ratio looks normal). The raw
    ``roofline_ratio`` keeps its absolute-localizer meaning."""
    try:
        metric = record.get("metric", "")
        if "[cpu]" in metric or "[unreachable]" in metric:
            return record
        pred = _predicted_rate(config, results_dir)
        val = record.get("value")
        if pred and isinstance(val, (int, float)) and val > 0 \
                and math.isfinite(val):
            record["predicted"] = round(pred, 1)
            record["roofline_ratio"] = round(val / pred, 4)
            try:
                from apex1_tpu.obs.calibrate import step_slowdown
                cal = step_slowdown(config, results_dir)
                if cal:
                    cal_pred = pred / cal["slowdown"]
                    record["calibrated_predicted"] = round(cal_pred, 1)
                    record["calibrated_ratio"] = round(val / cal_pred, 4)
                    record["calibration"] = {
                        "slowdown": cal["slowdown"], "n": cal["n"]}
            except Exception:
                pass  # calibration is metadata on metadata
    except Exception:
        pass  # metadata only — never break the always-emit contract
    return record


def _try_resume(ckpt_dir, template):
    """--resume auto: restore the newest VALID checkpoint under
    ``ckpt_dir`` (integrity-verified, scans past corrupt ones). Returns
    ``(state, "step_N")`` or ``(template, None)`` when nothing usable is
    banked — a bench must measure, not die, on a stale/foreign dir."""
    try:
        from apex1_tpu.resilience import ResilientCheckpointer

        with ResilientCheckpointer(ckpt_dir) as ck:
            state, man = ck.restore(template=template)
        return state, f"step_{man.step}"
    except Exception as e:
        print(f"WARNING: --resume auto: no usable checkpoint under "
              f"{ckpt_dir} ({e}); starting fresh", file=sys.stderr,
              flush=True)
        return template, None


def _bank_ckpt(ckpt_dir, state, fallback_step):
    """Bank the trained bench state (synchronously) so the next
    ``--resume auto`` run continues from it."""
    from apex1_tpu.resilience import ResilientCheckpointer

    step_no = getattr(state, "step", None)
    if step_no is None and isinstance(state, tuple) and state:
        step_no = getattr(state[0], "step", None)
    step_no = (int(np.asarray(step_no)) if step_no is not None
               else int(fallback_step))
    with ResilientCheckpointer(ckpt_dir) as ck:
        ck.save_sync(step_no, state, meta={"source": "bench.py"})
    return step_no


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="gpt2", choices=sorted(BENCHES))
    ap.add_argument("--batch", type=int, default=None,
                    help="override batch size (gpt2 config only)")
    ap.add_argument("--seq", type=int, default=None,
                    help="override sequence length (gpt2 config only)")
    ap.add_argument("--plan", default=None,
                    help="banked plan.json for --config llama_3d "
                    "(default: the planner searches the live device "
                    "count)")
    ap.add_argument("--timeout", type=float, default=1500.0,
                    help="watchdog for build+compile+measure (seconds)")
    ap.add_argument("--probe-timeout", type=float, default=180.0)
    ap.add_argument("--probe-retries", type=int, default=3)
    ap.add_argument("--out", default=None,
                    help="also bank the record to this file (temp-file + "
                    "atomic rename): an interrupted sweep keeps every "
                    "completed config's record")
    ap.add_argument("--ckpt-dir", default=None,
                    help="resilient checkpoint dir: the trained bench "
                    "state is banked here after measuring, and --resume "
                    "auto continues from the newest valid checkpoint")
    ap.add_argument("--resume", default="never", choices=("auto", "never"),
                    help="auto: restore the bench state from the newest "
                    "VALID checkpoint under --ckpt-dir (resilience."
                    "find_restorable) and stamp the record with "
                    "`resumed_from` provenance")
    args = ap.parse_args()

    unit = "images/sec/chip" if args.config == "resnet" else "tokens/sec/chip"
    fallback = {"metric": f"{unit} {args.config} [unreachable]",
                "value": 0.0, "unit": unit, "vs_baseline": 0.0}

    backend, probe_stderr = probe_backend(args.probe_timeout,
                                          args.probe_retries)
    if backend is None:
        fallback["error"] = (
            f"backend init unreachable after {args.probe_retries} probes "
            f"x {args.probe_timeout:.0f}s"
            + (f"; last stderr: {probe_stderr}" if probe_stderr else ""))
        # an unreachable tunnel does not erase history: point at the best
        # ON-SILICON number banked in perf_results/ for this config
        # (value stays 0.0 — this run measured nothing; the pointer is
        # metadata so the record isn't mistaken for "never measured").
        # Never let the pointer lookup break the always-emit contract.
        try:
            prior = _last_banked(args.config)
        except Exception:
            prior = None
        if prior is not None:
            # ratio for the banked on-silicon number: the measured
            # record should carry its own roofline score (value /
            # predicted) so the 0.36x-class localizer reads off the line
            fallback["best_banked"] = _attach_roofline(prior, args.config)
        _emit(fallback, args.out)
        return

    def _alarm(signum, frame):
        raise TimeoutError(f"watchdog: exceeded {args.timeout:.0f}s")

    signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(int(args.timeout))
    try:
        # honor JAX_PLATFORMS despite the sitecustomize jax_platforms pin
        # — only now, AFTER the subprocess probe succeeded and UNDER the
        # watchdog (the helper's verification initializes the in-process
        # backend, which blocks uninterruptibly on a dead tunnel)
        from apex1_tpu.testing import (enable_persistent_compilation_cache,
                                       honor_jax_platforms_env)

        honor_jax_platforms_env()
        # compile-once economics: the measured loop is timed AFTER warmup,
        # so a persistent cache only cuts re-run latency, never the number
        enable_persistent_compilation_cache()
        on_accel = backend not in ("cpu",)
        # headline auto-tune: with no explicit --batch, measure the
        # AOT-verified batch candidates and report the best (B=16 fits
        # at 8.2 GiB on v5e; 24 fits with margin — both sized by
        # tools/aot_check.py). A candidate that fails (OOM on a
        # smaller-memory pool chip) is skipped, not fatal.
        if args.config in ("gpt2", "gpt2_fp16") and on_accel \
                and args.batch is None:
            cand_batches = [16, 24]
        else:
            cand_batches = [args.batch]

        best = None
        best_rate = -1.0
        last_err = None
        bank_state = None
        bank_iters = 0
        resume_cache = None   # restore + digest-verify once per run,
        for b in cand_batches:  # not per candidate (batch-independent)
            try:
                kw = {}
                if args.config in ("gpt2", "gpt2_fp16"):
                    kw = dict(batch=b, seq=args.seq)
                elif args.config == "llama_3d":
                    kw = dict(plan=args.plan)
                (state, step, batch, units_per_step, iters, metric, unit,
                 proxy) = BENCHES[args.config](on_accel, **kw)
                resumed_from = None
                if args.ckpt_dir and args.resume == "auto":
                    if resume_cache is None:
                        restored, rf = _try_resume(args.ckpt_dir, state)
                        if rf is not None:
                            # hold the restored state as HOST arrays:
                            # timed_steps donates its input buffers, so
                            # each candidate needs fresh device copies
                            restored = jax.device_get(restored)
                        resume_cache = (restored, rf)
                    host_restored, resumed_from = resume_cache
                    if resumed_from is not None:
                        state = jax.tree_util.tree_map(jnp.asarray,
                                                       host_restored)
                # on-silicon runs bank a profiler trace as a PRODUCT of
                # the window (ROADMAP item 5): one untimed dispatch
                # under jax.profiler.trace, its directory stamped on the
                # record as `profile_artifact`. APEX1_BENCH_PROFILE=0
                # opts out; CPU smoke runs never profile.
                pdir = None
                if on_accel and os.environ.get(
                        "APEX1_BENCH_PROFILE", "1") != "0":
                    pdir = os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "perf_results", "profiles",
                        f"{args.config}_b{b}_{int(time.time())}")
                (per_step, flops_per_step, final_metrics,
                 final_state) = timed_steps(step, state, batch, iters,
                                            profile_dir=pdir)
                rate = units_per_step / per_step
                if rate > best_rate:   # unrounded comparison
                    best_rate = rate
                    bank_state, bank_iters = final_state, iters
                    best = {
                        "metric": f"{metric} [{backend}]",
                        "value": round(rate, 1),
                        "unit": unit,
                        "vs_baseline": round(rate / proxy, 4),
                    }
                    if pdir is not None and os.path.isdir(pdir) \
                            and os.listdir(pdir):
                        best["profile_artifact"] = os.path.relpath(
                            pdir, os.path.dirname(
                                os.path.abspath(__file__)))
                    if resumed_from:
                        # provenance: this number continued from a banked
                        # checkpoint, not a fresh init
                        best["resumed_from"] = resumed_from
                    if len(cand_batches) > 1:
                        best["batch"] = b
                    # dynamic-loss-scaling evidence (fp16 O1): the
                    # record carries the skip count and where the scale
                    # settled — zero skips after warmup and a stable
                    # scale is the pass signal
                    for mk_ in ("loss_scale", "skipped_steps"):
                        if mk_ in final_metrics:
                            best[mk_] = float(
                                np.asarray(final_metrics[mk_]))
                    if flops_per_step is not None and on_accel:
                        from apex1_tpu.core.capability import (
                            get_capability)
                        peak = get_capability().bf16_tflops * 1e12
                        # cost_analysis is blind inside tpu_custom_call,
                        # so its number under-reports true utilization by
                        # the kernels' flop share (~8.5x on decode_int8)
                        # — name it what it is, and emit the REAL `mfu`
                        # from logical flops: visible x the banked
                        # mfu_correction (logical/visible flop ratio from
                        # perf_results/predicted_*.json — a ratio, so it
                        # survives batch overrides that change absolute
                        # flops)
                        vis = flops_per_step / per_step / peak
                        best["xla_visible_mfu"] = round(vis, 4)
                        best["step_ms"] = round(per_step * 1e3, 2)
                        try:
                            row = _predicted_row(args.config)
                            corr = (row or {}).get("mfu_correction")
                            if corr:
                                best["mfu"] = round(vis * corr, 4)
                        except Exception:
                            pass  # metadata only — never break emit
            except TimeoutError:
                # the watchdog fired mid-candidate; a finished earlier
                # candidate is still a valid headline — emit it rather
                # than discarding a good number
                break
            except Exception as e:  # try the remaining candidates
                last_err = e
        signal.alarm(0)
        if best is None:
            raise last_err if last_err is not None else RuntimeError(
                "no benchmark candidate ran")
        if args.ckpt_dir and bank_state is not None:
            try:
                _bank_ckpt(args.ckpt_dir, bank_state, bank_iters)
            except Exception as e:  # banking must not eat the record
                print(f"WARNING: checkpoint banking failed: {e}",
                      file=sys.stderr, flush=True)
        best = _attach_roofline(best, args.config)
        try:   # mirror the headline record into the telemetry spine
            from apex1_tpu.obs import spine
            spine.emit("event", "bench.record", config=args.config,
                       **best)
        except Exception:
            pass
        _emit(best, args.out)
    except Exception as e:  # the line must still print on any failure
        signal.alarm(0)
        fallback["metric"] = f"{unit} {args.config} [{backend}]"
        fallback["error"] = f"{type(e).__name__}: {e}"
        _emit(fallback, args.out)


if __name__ == "__main__":
    main()
