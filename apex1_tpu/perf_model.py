"""The ONE analytic performance-pricing library — rooflines, per-kernel
cost formulas, and ICI comms exposure models.

History: these formulas grew up inside ``tools/predict_perf.py``
(`_roofline`, `_kernel_cases`, `predict_comms`, `predict_comms_fused`)
where only the CLI could reach them. ROADMAP item 1's planner must price
thousands of candidate layouts per search — shelling out to a CLI per
layout, or re-implementing the roofline, would either be absurd or
guarantee formula drift (exactly the divergence ``vmem_model`` exists
to prevent for the VMEM formulas). This module is the same
deduplication for TIME: ``tools/predict_perf.py`` now imports every
pricing ingredient from here (its CLI behavior and banked
``predicted_*.json`` output are byte-stable across the refactor —
pinned by the planner test suite re-deriving its table rows), and
``apex1_tpu.planner.cost`` prices candidate layouts through the same
functions.

Everything here is jax-free at import (``core.capability`` is too):
the planner's legality/pricing path must run in light tools and the
``tools/lint.py``-style stub environments. The honesty contract on
every number is ``tools/predict_perf.py``'s module docstring — these
are UPPER bounds on throughput (no bandwidth derating, no scheduler
gaps); calibration (``obs.calibrate``) is what corrects them against
banked silicon history.
"""

from __future__ import annotations

from typing import Optional

# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------


def roofline(flops, nbytes, cap, ici_exposed_bytes=0.0):
    """Predicted seconds + binding side for one program on one chip.

    ``ici_exposed_bytes``: ICI traffic NOT hidden behind compute — it
    ADDS to the roofline time (an overlapped transfer costs nothing
    here; an exposed one serializes). Priced at the conservative
    per-neighbor link rate (`core.capability.ici_link_gbps`). 0 for
    the single-chip bench rows."""
    from apex1_tpu.core.capability import ici_link_gbps

    t_mxu = flops / (cap.bf16_tflops * 1e12)
    t_hbm = nbytes / (cap.hbm_gbps * 1e9)
    t = max(t_mxu, t_hbm)
    bound = "MXU" if t_mxu >= t_hbm else "HBM"
    if ici_exposed_bytes:
        link = ici_link_gbps(cap.generation)
        t_ici = ici_exposed_bytes / (link * 1e9) if link else 0.0
        t = t + t_ici
        if t_ici > max(t_mxu, t_hbm):
            bound = "ICI"
    mfu = flops / (t * cap.bf16_tflops * 1e12) if t > 0 else 0.0
    return t, bound, mfu


# ---------------------------------------------------------------------------
# per-kernel analytic cases (the Pallas blind-spot table)
# ---------------------------------------------------------------------------


def flash_flops_bytes(B, Hq, Hkv, S, D, causal=True, grad=False):
    """Analytic (flops, min HBM bytes) for one flash-attention call —
    the formula block shared by `kernel_cases` and the planner's
    attention pricing (docstring of the factors: predict_perf
    "_kernel_cases")."""
    f = 4 * B * Hq * S * S * D * (0.5 if causal else 1.0)
    if grad:
        # fwd (2 matmuls) + the SHIPPED two-pass backward: dq pass
        # recomputes p and dP then dq (3 matmuls), dkv pass
        # recomputes them again then dk, dv (4) — 7 bwd matmuls
        # total, NOT the fused-backward 5 an analytic count
        # assumes (Mosaic's output-revisiting rule forces the two
        # passes; see ops/attention.py and measured_r5.md). A
        # perfect kernel measured against the 5-matmul roofline
        # would read as ~0.78 and be mis-flagged as a tuning
        # target.
        f *= 4.5          # (2 + 7) / 2
    qb = B * Hq * S * D * 2
    kvb = 2 * B * Hkv * S * D * 2
    byt = qb + kvb + qb   # q, k, v in; o out
    if grad:
        byt += 2 * qb + kvb + qb   # dq out, dk/dv out, do in
    return f, byt


def elemwise_flops_bytes(n_elem, passes, itemsize, fpe):
    """Bandwidth-bound row kernels: flops-per-element x count, passes x
    element traffic."""
    return fpe * n_elem, passes * n_elem * itemsize


def kernel_cases():
    """ANALYTIC (flops, min HBM bytes) per Pallas kernel at its bench
    shape — shapes mirror tools/aot_check.py's kernel gate, so each row
    lines up with what tools/bench_kernels.py measures on silicon.

    Formulas (all counts: multiply-add = 2 flops; bytes = each operand
    and result crossing HBM once — the kernels are designed to touch
    operands once, so this IS the target):
    - flash attention fwd: 4*B*H*S^2*D matmul flops (QK^T + PV), x0.5
      causal skip; bwd = 2.5x fwd (dV/dP/dS/dQ/dK matmuls + the
      recomputed P the memory-efficient backward pays for). GQA K/V
      bytes scale by Hkv/Hq.
    - linear_xent f+b: 6*T*Hd*V (fwd logits + dX + dW); bytes 3 reads
      of W (fwd + recompute-bwd + dW stream) + x/dx/dw.
    - LN / RMS / softmax / rope / xentropy: bandwidth-bound, flops ~
      a few per element (counted as 5/elem fwd, 8/elem f+b — they
      never bind the roofline); bytes = per-pass element traffic
      (softmax f+b: x in, y out, then y + dy in, dx out; LN f+b: 2
      reads + 2 writes of x-sized arrays + stats).
    - int8 GEMM: 2*M*N*K flops; bytes dominated by the int8 weight
      (N*K) + scales + activations.
    """
    flash = flash_flops_bytes
    elemwise = elemwise_flops_bytes

    T, Hd, V = 16 * 1023, 768, 50432
    lx_f = linear_xent_flops(T, Hd, V)
    lx_b = 2 * (3 * V * Hd + 2 * T * Hd + V * Hd)  # W x3, x/dx, dW

    return [
        ("flash gpt2 (16,12,1024,64) fwd", *flash(16, 12, 12, 1024, 64)),
        ("flash gpt2 (16,12,1024,64) f+b",
         *flash(16, 12, 12, 1024, 64, grad=True)),
        ("flash longctx (1,32,16384,64) f+b",
         *flash(1, 32, 32, 16384, 64, grad=True)),
        ("flash GQA (Hq32/Hkv4,16k,64) f+b",
         *flash(1, 32, 4, 16384, 64, grad=True)),
        ("linear_xent gpt2 (16k,768,50k) f+b", lx_f, lx_b),
        ("layer_norm (16384,768) f+b",
         *elemwise(16384 * 768, 4, 2, 8)),
        ("rms_norm (16384,2048) f+b",
         *elemwise(16384 * 2048, 4, 2, 8)),
        ("causal softmax (16,12,1024,1024) f+b",
         *elemwise(16 * 12 * 1024 * 1024 // 2, 4, 4, 8)),
        ("xentropy (16368,50432) f+b",
         *elemwise(16368 * 50432, 3, 4, 8)),   # recompute-bwd: x, x, dx
        ("rope llama (1,16384,32,64) f+b",
         *elemwise(16384 * 32 * 64, 4, 2, 6)),
        ("int8 GEMM decode (8,4096)x(32000,4096)",
         2 * 8 * 32000 * 4096,
         32000 * 4096 * 1 + 32000 * 4 + 2 * 8 * (4096 + 32000) * 2),
    ]


def linear_xent_flops(T, Hd, V):
    """Fused LM-head CE fwd+bwd flops (logits + dX + dW) — the chunked
    kernel's arithmetic is the dense one's."""
    return 6 * T * Hd * V


# ---------------------------------------------------------------------------
# ICI comms exposure models
# ---------------------------------------------------------------------------


def ring_attention_comms(generation: str, n: int, *,
                         B: int = 1, Hq: int = 32, Hkv: int = 4,
                         S: int = 16384, D: int = 64
                         ) -> Optional[dict]:
    """Exposure model for the ring-attention CP path: per ring step the
    K/V shard transfer either serializes against the attend (the
    pre-overlap schedule) or hides behind it (the double-buffered
    schedule, hlo_probe-pinned). Returns None when the capability row
    carries no ICI figure. Values in the dict are exactly what
    predict_perf's comms table prints; the planner prices candidate cp
    degrees through the same math at its model's shape."""
    from apex1_tpu.core.capability import get_capability, ici_link_gbps

    cap = get_capability(generation)
    link = ici_link_gbps(generation)
    if not link:
        return None
    S_l = S // n
    kv_hop = 2 * B * Hkv * S_l * D * 2          # K+V bf16
    dkv_hop = 2 * B * Hkv * S_l * D * 4         # dK+dV fp32
    att = 4 * B * Hq * S_l * S_l * D * 0.5      # causal attend
    bwd = 2.5 * att
    t_hop_f = kv_hop / (link * 1e9)
    t_hop_b = (kv_hop + dkv_hop) / (link * 1e9)
    t_att = att / (cap.bf16_tflops * 1e12)
    t_bwd = bwd / (cap.bf16_tflops * 1e12)
    fwd_bytes = (n - 1) * kv_hop
    bwd_bytes = n * (kv_hop + dkv_hop)
    exp_f_overlap = (n - 1) * max(0.0, t_hop_f - t_att) * (link * 1e9)
    exp_b_overlap = n * max(0.0, t_hop_b - t_bwd) * (link * 1e9)
    return dict(
        generation=generation, cp=n, link_gbps=link,
        kv_hop=kv_hop, dkv_hop=dkv_hop,
        t_hop_f=t_hop_f, t_hop_b=t_hop_b, t_att=t_att, t_bwd=t_bwd,
        fwd_bytes=fwd_bytes, bwd_bytes=bwd_bytes,
        exp_f_overlap=exp_f_overlap, exp_b_overlap=exp_b_overlap)


def sp_boundary_comms(generation: str, n: int, *,
                      rows: int = 8192, local_k: Optional[int] = None,
                      out_width: int = 4096, ffn: int = 14336,
                      acc_bytes: int = 4,
                      hop_width: Optional[int] = None
                      ) -> Optional[dict]:
    """Exposure model for ONE Megatron-SP boundary matmul+collective
    (chunk-pipelined ppermute ring; docs/parallel.md "Fused
    comm-kernels"), across the three shipped schedules:

    - ``serial``   — every byte exposed (monolithic collective /
      rotate-then-dot negative control);
    - ``overlap``  — PR 4's ppermute ring AND the fused ppermute form:
      exposed = the per-hop residual the chunk dot cannot cover
      (BEST-case: assumes the scheduler hoists every permute);
    - ``fused``    — the single-kernel RDMA form: STRUCTURAL bound,
      exposed ≈ prologue hop (pipeline fill) + the same residual.

    Defaults are the llama-8B MLP row-parallel boundary
    (``predict_comms_fused``'s shape); the planner calls this per
    candidate layout with its own (rows, local_k, out_width).

    ``hop_width``: width of the TRAVELLING chunk. Default (None) =
    ``out_width`` — correct for matmul→reduce-scatter, where the fp32
    partial-result accumulator hops. For the all-gather→matmul dual
    the travelling data is the INPUT activation (width = the model
    dim, NOT the dot's output shard), so pass ``hop_width=E`` with
    ``acc_bytes`` = the activation dtype size.
    Returns None when the capability row carries no ICI figure."""
    from apex1_tpu.core.capability import get_capability, ici_link_gbps

    cap = get_capability(generation)
    link = ici_link_gbps(generation)
    if not link:
        return None
    if local_k is None:
        local_k = ffn // n
    chunk_rows = rows // n
    if hop_width is None:
        hop_width = out_width
    hop = chunk_rows * hop_width * acc_bytes      # travelling chunk
    dot = 2 * chunk_rows * local_k * out_width    # per-step MXU
    t_hop = hop / (link * 1e9)
    t_dot = dot / (cap.bf16_tflops * 1e12)
    total = n * hop
    resid = n * max(0.0, t_hop - t_dot) * (link * 1e9)
    fused_exposed = hop + resid                   # prologue hop
    return dict(
        generation=generation, tp=n, link_gbps=link,
        hop=hop, dot=dot, t_hop=t_hop, t_dot=t_dot,
        total=float(total),
        exposed_serial=float(total),
        exposed_overlap=float(resid),
        exposed_fused=float(fused_exposed))


def allreduce_bytes(nbytes: float, n: int) -> float:
    """Per-device ring all-reduce traffic for an ``nbytes`` buffer over
    ``n`` participants: reduce-scatter + all-gather, each moving
    (n-1)/n of the buffer through every device. The ZeRO split
    (reduce-scatter grads, all-gather updated params —
    `parallel.distributed_optimizer`) moves the same total, so one
    formula prices both the plain-dp and the zero layouts' gradient
    sync."""
    if n <= 1:
        return 0.0
    return 2.0 * nbytes * (n - 1) / n


# ---------------------------------------------------------------------------
# serving-config pricing (ISSUE 15: the goodput-multiplier arithmetic)
# ---------------------------------------------------------------------------


def kv_cache_bytes(num_layers: int, num_kv_heads: int, head_dim: int,
                   positions: int, batch: int = 1,
                   bytes_per_el: int = 2) -> int:
    """HBM bytes of a K/V cache pytree (`models.generate.init_cache`
    layout: K + V per layer, ``(batch, Hkv, positions, D)`` each) — the
    analytic mirror of `serving.KVPool.pool_bytes`, jax-free so the
    planner/bench can size pools without building one. ``bytes_per_el``
    2 = bf16 (the default compute dtype), 1 = the int8 capacity tier,
    4 = fp32 test configs."""
    return (2 * int(num_layers) * int(batch) * int(num_kv_heads)
            * int(positions) * int(head_dim) * int(bytes_per_el))


def serving_capacity(hbm_budget_bytes: float, num_layers: int,
                     num_kv_heads: int, head_dim: int, pool_len: int,
                     bytes_per_el: int = 2) -> int:
    """Resident batch (engine ``max_slots``) a KV-pool HBM budget buys:
    ``budget // bytes-per-slot``. The int8 tier's headline is this
    function at ``bytes_per_el=1`` — double the slots for the same
    budget — which is capacity, not correctness: the dtype-flip parity
    drills are what license flipping it on."""
    per_slot = kv_cache_bytes(num_layers, num_kv_heads, head_dim,
                              pool_len, 1, bytes_per_el)
    if per_slot <= 0:
        raise ValueError("per-slot KV bytes must be positive")
    return int(hbm_budget_bytes // per_slot)


def speculative_speedup(accept_rate: float, num_draft: int,
                        verify_cost: float = 1.0,
                        draft_cost: float = 0.0) -> float:
    """Expected decode-dispatch speedup of the engine's speculative
    mode: tokens emitted per verify round over its relative cost.

    Per-position independent accept probability ``r`` gives
    ``E[tokens/round] = 1 + r + r^2 + ... + r^K`` (the accepted prefix
    is geometric, truncated at K drafts, plus the always-emitted
    correction/bonus token). ``verify_cost`` is one (K+1)-wide chunk
    verify relative to one plain decode step (~1 on TPU decode, which
    is weight-streaming-bound: the same weights stream either way);
    ``draft_cost`` is the per-draft-token proposal cost (0 for the
    host-side n-gram default). An UPPER bound, like every number in
    this module — the banked accept rates (`bench_serving`) are what
    calibrate it."""
    if not 0.0 <= accept_rate <= 1.0:
        raise ValueError(f"accept_rate must be in [0, 1], "
                         f"got {accept_rate}")
    if num_draft < 1:
        raise ValueError(f"num_draft must be >= 1, got {num_draft}")
    tokens = sum(accept_rate ** j for j in range(num_draft + 1))
    cost = float(verify_cost) + num_draft * float(draft_cost)
    if cost <= 0:
        raise ValueError("round cost must be positive")
    return tokens / cost
