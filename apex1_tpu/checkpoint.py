"""Sharded checkpoint / resume — SURVEY.md §5.4.

Reference capabilities covered:
- ``amp.state_dict()/load_state_dict()`` (loss-scaler state) — here the
  loss-scale state lives INSIDE `AmpState`, so one checkpoint round-trips
  the whole (params, opt_state, loss_scale, step) tuple — the triple the
  reference README tells users to save by hand.
- ``DistributedFusedAdam.state_dict()`` gather-to-rank0 / sharded-save —
  orbax writes each host's shards of a ``jax.sharding``-annotated array
  directly (sharded-save is the default, gather never materializes).
- resume onto a DIFFERENT mesh: restore takes a target sharding tree, so a
  checkpoint written on one topology restores onto another (the reference
  cannot do this — NCCL-rank-file checkpoints are topology-bound).

Backend: orbax ``StandardCheckpointer`` (async-capable, atomic renames).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def _checkpointer() -> ocp.StandardCheckpointer:
    return ocp.StandardCheckpointer()


def save_checkpoint(path: str | os.PathLike, state: Any, *,
                    force: bool = True) -> None:
    """Write ``state`` (any pytree of arrays, e.g. `AmpState`) to ``path``.
    Sharded arrays are written shard-wise by their current sharding."""
    path = os.fspath(os.path.abspath(path))
    with _checkpointer() as ckptr:
        ckptr.save(path, state, force=force)


def restore_checkpoint(path: str | os.PathLike, template: Any = None, *,
                       mesh: Optional[Mesh] = None,
                       spec_tree: Any = None) -> Any:
    """Restore a checkpoint.

    ``template``: a pytree of arrays or ShapeDtypeStructs matching the
    saved structure (e.g. ``jax.eval_shape(make_state)``); with ``mesh`` +
    ``spec_tree`` (PartitionSpecs), arrays restore directly onto the mesh
    with those shardings — resume on a different topology than the save.
    """
    path = os.fspath(os.path.abspath(path))
    with _checkpointer() as ckptr:
        if template is None:
            return ckptr.restore(path)
        return ckptr.restore(path, _abstract(template, mesh, spec_tree))


def _abstract(template, mesh, spec_tree):
    """ShapeDtypeStruct tree for restore; with ``mesh``, each leaf carries
    a NamedSharding so orbax places shards directly on the target mesh."""
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(jnp_shape(x), x.dtype), template)
    if mesh is None:
        return abstract
    specs = (spec_tree if spec_tree is not None
             else jax.tree.map(lambda _: PartitionSpec(), abstract))
    return jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=NamedSharding(mesh, s)),
        abstract, specs)


def jnp_shape(x) -> tuple:
    return tuple(np.shape(x)) if not hasattr(x, "shape") else tuple(x.shape)


class CheckpointManager:
    """Rotating step-numbered checkpoints with resume — the
    train-loop-facing API (``save(step, state)`` / ``latest()`` /
    ``restore(template)``). ≙ the reference examples' epoch checkpointing
    plus DistributedFusedAdam's sharded-state handling, unified."""

    def __init__(self, directory: str | os.PathLike, *,
                 max_to_keep: int = 3, save_interval_steps: int = 1):
        self._mgr = ocp.CheckpointManager(
            os.fspath(os.path.abspath(directory)),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force)
        return bool(saved)

    def latest(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, template: Any, *, step: Optional[int] = None,
                mesh: Optional[Mesh] = None, spec_tree: Any = None) -> Any:
        step = self.latest() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        return self._mgr.restore(
            step,
            args=ocp.args.StandardRestore(_abstract(template, mesh,
                                                    spec_tree)))

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def to_global(tree, mesh: Mesh, spec_tree: Any = None):
    """Host-local pytree → globally-addressable arrays on ``mesh``
    (replicated by default). Required before `save_checkpoint` in
    multi-controller jobs — orbax refuses host-local arrays
    (≙ the reference's rank-0 state_dict gather, without the gather)."""
    from jax.experimental import multihost_utils

    specs = (spec_tree if spec_tree is not None
             else jax.tree.map(lambda _: PartitionSpec(), tree))
    return multihost_utils.host_local_array_to_global_array(
        tree, mesh, specs)


def to_host_local(tree, mesh: Mesh, spec_tree: Any = None):
    """Inverse of `to_global` after a multi-controller restore."""
    from jax.experimental import multihost_utils

    specs = (spec_tree if spec_tree is not None
             else jax.tree.map(lambda _: PartitionSpec(), tree))
    return multihost_utils.global_array_to_host_local_array(
        tree, mesh, specs)
