"""Sharded checkpoint / resume — SURVEY.md §5.4.

Reference capabilities covered:
- ``amp.state_dict()/load_state_dict()`` (loss-scaler state) — here the
  loss-scale state lives INSIDE `AmpState`, so one checkpoint round-trips
  the whole (params, opt_state, loss_scale, step) tuple — the triple the
  reference README tells users to save by hand.
- ``DistributedFusedAdam.state_dict()`` gather-to-rank0 / sharded-save —
  orbax writes each host's shards of a ``jax.sharding``-annotated array
  directly (sharded-save is the default, gather never materializes).
- resume onto a DIFFERENT mesh: restore takes a target sharding tree, so a
  checkpoint written on one topology restores onto another (the reference
  cannot do this — NCCL-rank-file checkpoints are topology-bound).

Backend: orbax ``StandardCheckpointer`` (async-capable, atomic renames).
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# suffix marker for in-progress saves: a killed save leaves only
# `<path>.tmp-<pid>`, never a half-written `<path>` that LOOKS restorable
_TMP_MARK = ".tmp-"


class CheckpointError(RuntimeError):
    """Typed checkpoint failure naming the path and the reason — the
    orbax/tensorstore stack traces (missing dir, truncated array file,
    structure mismatch) all surface through this so callers
    (`resilience.find_restorable`, resume loops) can catch ONE type and
    decide, instead of pattern-matching backend internals."""

    def __init__(self, path: str | os.PathLike, reason: str):
        self.path = os.fspath(path)
        self.reason = reason
        super().__init__(f"checkpoint {self.path}: {reason}")


def _checkpointer() -> ocp.StandardCheckpointer:
    return ocp.StandardCheckpointer()


def save_checkpoint(path: str | os.PathLike, state: Any, *,
                    force: bool = True) -> None:
    """Write ``state`` (any pytree of arrays, e.g. `AmpState`) to ``path``.
    Sharded arrays are written shard-wise by their current sharding.

    Atomicity: the write lands in ``<path>.tmp-<pid>`` and is renamed to
    ``path`` only after the backend finished and synced — a save killed
    mid-write leaves the temp dir (ignored by restore and
    `resilience.find_restorable`), never a truncated ``path``."""
    path = os.fspath(os.path.abspath(path))
    if os.path.exists(path) and not force:
        raise CheckpointError(path, "exists and force=False")
    tmp = f"{path}{_TMP_MARK}{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    old = None
    try:
        with _checkpointer() as ckptr:
            ckptr.save(tmp, state, force=True)
        # overwrite via move-aside, never delete-then-rename: a kill
        # between the two renames leaves EITHER the old checkpoint at
        # `path` or the new one — at no instant zero committed copies
        if os.path.exists(path):
            old = f"{path}.old-{os.getpid()}"
            shutil.rmtree(old, ignore_errors=True)
            os.rename(path, old)
        os.rename(tmp, path)
    except CheckpointError:
        raise
    except Exception as e:
        if old is not None and not os.path.exists(path):
            os.rename(old, path)        # put the old checkpoint back
            old = None
        shutil.rmtree(tmp, ignore_errors=True)
        raise CheckpointError(path, f"save failed: {e}") from e
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)


def restore_checkpoint(path: str | os.PathLike, template: Any = None, *,
                       mesh: Optional[Mesh] = None,
                       spec_tree: Any = None) -> Any:
    """Restore a checkpoint.

    ``template``: a pytree of arrays or ShapeDtypeStructs matching the
    saved structure (e.g. ``jax.eval_shape(make_state)``); with ``mesh`` +
    ``spec_tree`` (PartitionSpecs), arrays restore directly onto the mesh
    with those shardings — resume on a different topology than the save.

    Raises `CheckpointError` (never a raw orbax/tensorstore traceback)
    on a missing path, an unfinished ``.tmp-`` save, or a corrupt /
    structure-mismatched checkpoint.
    """
    path = os.fspath(os.path.abspath(path))
    if not os.path.exists(path):
        raise CheckpointError(path, "missing (no such directory)")
    if _TMP_MARK in os.path.basename(path):
        raise CheckpointError(
            path, "partial write (unfinished save temp dir)")
    try:
        with _checkpointer() as ckptr:
            if template is None:
                return ckptr.restore(path)
            return ckptr.restore(path, _abstract(template, mesh, spec_tree))
    except Exception as e:
        raise CheckpointError(path, f"restore failed: {e}") from e


def _abstract(template, mesh, spec_tree):
    """ShapeDtypeStruct tree for restore; with ``mesh``, each leaf carries
    a NamedSharding so orbax places shards directly on the target mesh."""
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(jnp_shape(x), x.dtype), template)
    if mesh is None:
        return abstract
    specs = (spec_tree if spec_tree is not None
             else jax.tree.map(lambda _: PartitionSpec(), abstract))
    return jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=NamedSharding(mesh, s)),
        abstract, specs)


def jnp_shape(x) -> tuple:
    return tuple(np.shape(x)) if not hasattr(x, "shape") else tuple(x.shape)


class CheckpointManager:
    """Rotating step-numbered checkpoints with resume — the
    train-loop-facing API (``save(step, state)`` / ``latest()`` /
    ``restore(template)``). ≙ the reference examples' epoch checkpointing
    plus DistributedFusedAdam's sharded-state handling, unified."""

    def __init__(self, directory: str | os.PathLike, *,
                 max_to_keep: int = 3, save_interval_steps: int = 1):
        self._mgr = ocp.CheckpointManager(
            os.fspath(os.path.abspath(directory)),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force)
        return bool(saved)

    def latest(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, template: Any, *, step: Optional[int] = None,
                mesh: Optional[Mesh] = None, spec_tree: Any = None) -> Any:
        step = self.latest() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        return self._mgr.restore(
            step,
            args=ocp.args.StandardRestore(_abstract(template, mesh,
                                                    spec_tree)))

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def to_global(tree, mesh: Mesh, spec_tree: Any = None):
    """Host-local pytree → globally-addressable arrays on ``mesh``
    (replicated by default). Required before `save_checkpoint` in
    multi-controller jobs — orbax refuses host-local arrays
    (≙ the reference's rank-0 state_dict gather, without the gather)."""
    from jax.experimental import multihost_utils

    specs = (spec_tree if spec_tree is not None
             else jax.tree.map(lambda _: PartitionSpec(), tree))
    return multihost_utils.host_local_array_to_global_array(
        tree, mesh, specs)


def to_host_local(tree, mesh: Mesh, spec_tree: Any = None):
    """Inverse of `to_global` after a multi-controller restore."""
    from jax.experimental import multihost_utils

    specs = (spec_tree if spec_tree is not None
             else jax.tree.map(lambda _: PartitionSpec(), tree))
    return multihost_utils.global_array_to_host_local_array(
        tree, mesh, specs)
