"""RNN modules — reference ``apex/RNN/{RNNBackend,cells,models}.py``
(deprecated upstream, kept for surface parity).

TPU-native: the input projection for ALL timesteps is one big MXU matmul
hoisted out of the loop; the recurrence is a ``jax.lax.scan`` over the
(small) hidden-to-hidden matmul + gates — there is no cuDNN-RNN analogue
to bind. Layout (T, B, F) seq-first, reference convention.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def _proj_params(mod, name, fan_in, fan_out, bias):
    k = nn.initializers.lecun_normal()
    w = mod.param(f"{name}_w", k, (fan_in, fan_out), jnp.float32)
    b = (mod.param(f"{name}_b", nn.initializers.zeros, (fan_out,),
                   jnp.float32) if bias else None)
    return w, b


def _apply(x, w, b):
    y = x @ w.astype(x.dtype)
    return y if b is None else y + b.astype(x.dtype)


class LSTM(nn.Module):
    """``apex.RNN.LSTM`` equivalent. Input (T, B, input_size); returns
    (outputs (T, B, hidden), (h_n, c_n) each (layers, B, hidden))."""

    input_size: int
    hidden_size: int
    num_layers: int = 1
    bias: bool = True

    @nn.compact
    def __call__(self, xs, state=None):
        B, H = xs.shape[1], self.hidden_size
        outs = xs
        finals = []
        for layer in range(self.num_layers):
            fan_in = self.input_size if layer == 0 else H
            wi, bi = _proj_params(self, f"l{layer}_ih", fan_in, 4 * H,
                                  self.bias)
            wh, _ = _proj_params(self, f"l{layer}_hh", H, 4 * H, False)
            x_gates = _apply(outs, wi, bi)       # (T, B, 4H), one matmul

            def cell(carry, xg, wh=wh):
                h, c = carry
                gates = xg + _apply(h, wh, None)
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
                h = jax.nn.sigmoid(o) * jnp.tanh(c)
                return (h, c), h

            if state is None:
                h0 = jnp.zeros((B, H), xs.dtype)
                c0 = jnp.zeros((B, H), xs.dtype)
            else:
                h0, c0 = state[0][layer], state[1][layer]
            (h_n, c_n), outs = jax.lax.scan(cell, (h0, c0), x_gates)
            finals.append((h_n, c_n))
        return outs, (jnp.stack([f[0] for f in finals]),
                      jnp.stack([f[1] for f in finals]))


class GRU(nn.Module):
    """``apex.RNN.GRU`` equivalent."""

    input_size: int
    hidden_size: int
    num_layers: int = 1
    bias: bool = True

    @nn.compact
    def __call__(self, xs, state=None):
        B, H = xs.shape[1], self.hidden_size
        outs = xs
        finals = []
        for layer in range(self.num_layers):
            fan_in = self.input_size if layer == 0 else H
            wi, bi = _proj_params(self, f"l{layer}_ih", fan_in, 3 * H,
                                  self.bias)
            wh, _ = _proj_params(self, f"l{layer}_hh", H, 3 * H, False)
            x_gates = _apply(outs, wi, bi)

            def cell(h, xg, wh=wh):
                hg = _apply(h, wh, None)
                xr, xz, xn = jnp.split(xg, 3, axis=-1)
                hr, hz, hn = jnp.split(hg, 3, axis=-1)
                r = jax.nn.sigmoid(xr + hr)
                z = jax.nn.sigmoid(xz + hz)
                n = jnp.tanh(xn + r * hn)
                return (1.0 - z) * n + z * h, (1.0 - z) * n + z * h

            h0 = (jnp.zeros((B, H), xs.dtype) if state is None
                  else state[layer])
            h_n, outs = jax.lax.scan(cell, h0, x_gates)
            finals.append(h_n)
        return outs, jnp.stack(finals)


class RNNReLU(nn.Module):
    """``apex.RNN.RNNReLU`` — vanilla RNN, ReLU nonlinearity."""

    input_size: int
    hidden_size: int
    num_layers: int = 1
    bias: bool = True
    activation: str = "relu"

    @nn.compact
    def __call__(self, xs, state=None):
        act = jax.nn.relu if self.activation == "relu" else jnp.tanh
        B, H = xs.shape[1], self.hidden_size
        outs = xs
        finals = []
        for layer in range(self.num_layers):
            fan_in = self.input_size if layer == 0 else H
            wi, bi = _proj_params(self, f"l{layer}_ih", fan_in, H,
                                  self.bias)
            wh, _ = _proj_params(self, f"l{layer}_hh", H, H, False)
            x_gates = _apply(outs, wi, bi)

            def cell(h, xg, wh=wh):
                h = act(xg + _apply(h, wh, None))
                return h, h

            h0 = (jnp.zeros((B, H), xs.dtype) if state is None
                  else state[layer])
            h_n, outs = jax.lax.scan(cell, h0, x_gates)
            finals.append(h_n)
        return outs, jnp.stack(finals)


class RNNTanh(RNNReLU):
    activation: str = "tanh"
