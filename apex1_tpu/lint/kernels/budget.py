"""VMEM/BlockSpec budget pass (APX208) + kernel-binding sanity (APX209).

APX208 prices every ``pallas_call``'s statically evaluable frame —
VMEM ``scratch_shapes`` with literal shapes/dtypes, plus BlockSpec
block shapes (double-buffered, floored at 1 byte/element when the
operand dtype is unknowable from the AST) — against the **conservative
v5e planning budget**, the same ``core.capability.vmem_budget`` figure
the block planners and ``tuning.registry`` gate with, through the ONE
shared sizing module ``apex1_tpu.vmem_model``. Everything unpriceable
contributes zero, so the estimate is a LOWER bound: a finding is a
proof the kernel cannot fit, never a heuristic. (The registry's
per-kernel formulas stay the richer model for tuned kernels; this pass
is the backstop for the kernels nothing registered — exactly the ones
a planner or sweep will emit unreviewed.)

APX209 checks the wiring between a ``pallas_call`` and its kernel
function, the part Mosaic only diagnoses with a cryptic arity error at
compile time on real hardware:

- kernel positional-parameter count == num_scalar_prefetch + inputs +
  outputs + scratch entries (when all four are statically countable);
- each BlockSpec ``index_map`` arity == grid rank + num_scalar_prefetch;
- scratch roles respected inside the kernel body: a ``SemaphoreType``
  scratch param must never be subscript-read/written or used as a DMA
  data buffer, and a ``VMEM`` scratch param must never be passed to
  ``semaphore_signal``/``semaphore_wait`` or a DMA semaphore position —
  cross-wired semaphores are precisely how a protocol kernel corrupts
  its own flow control.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from apex1_tpu.lint.core import Finding
from apex1_tpu.lint.project import Project
from apex1_tpu.lint.kernels.extract import (PLTPU, PallasSite,
                                            pallas_sites)

#: the static gate prices against the conservative off-TPU planning
#: target — the same default ``core.capability.get_capability`` serves
#: the heuristics (passing the generation EXPLICITLY keeps this import
#: path jax-free: detection would touch jax.devices()).
PLANNING_GENERATION = "v5e"


def _budget() -> int:
    from apex1_tpu.vmem_model import budget_bytes
    return budget_bytes(PLANNING_GENERATION)


def check(project: Project,
          sites: Optional[List[PallasSite]] = None) -> List[Finding]:
    findings: List[Finding] = []
    if sites is None:
        sites = pallas_sites(project)
    for site in sites:
        findings.extend(_check_budget(site))
        findings.extend(_check_binding(project, site))
    return findings


# ---------------------------------------------------------------------------
# APX208: static VMEM lower bound vs the planning budget
# ---------------------------------------------------------------------------

def _block_elems(shape) -> Optional[int]:
    if shape is None:
        return None
    total = 1
    for d in shape:
        if not isinstance(d, int):
            return None
        total *= d
    return total


def _check_budget(site: PallasSite) -> List[Finding]:
    scratch_bytes = 0
    for entry in site.scratch:
        b = entry.static_bytes()
        if b:
            scratch_bytes += b
    operand_bytes = 0
    for spec in site.in_specs + site.out_specs:
        elems = _block_elems(spec.shape)
        if elems:
            operand_bytes += elems  # 1 byte/element floor: dtype unknown
    from apex1_tpu.vmem_model import static_frame_bytes
    est = static_frame_bytes(operand_bytes=operand_bytes,
                             scratch_bytes=scratch_bytes)
    if est == 0:
        return []
    budget = _budget()
    if est <= budget:
        return []
    return [Finding(
        "APX208", site.mod.path, site.line, site.call.col_offset,
        f"statically provable VMEM frame lower bound "
        f"{est / 2**20:.1f} MiB (scratch {scratch_bytes / 2**20:.1f} "
        f"MiB + double-buffered blocks, 1 B/elem floor) exceeds the "
        f"{PLANNING_GENERATION} planning budget "
        f"{budget / 2**20:.1f} MiB (apex1_tpu.vmem_model) — this "
        f"kernel cannot compile on the planning target")]


# ---------------------------------------------------------------------------
# APX209: pallas_call <-> kernel wiring
# ---------------------------------------------------------------------------

def _kernel_positional_params(node) -> Optional[List[str]]:
    a = node.args
    if a.vararg or a.kwarg:
        return None
    return [p.arg for p in a.posonlyargs + a.args]


_SEM_KINDS = ("sem_dma", "sem_regular", "sem_barrier")


def _check_binding(project: Project, site: PallasSite) -> List[Finding]:
    findings: List[Finding] = []
    if site.kernel is None:
        return findings
    all_params = _kernel_positional_params(site.kernel.node)
    params = None
    if all_params is not None:
        # functools.partial consumes leading positionals and kw-bound
        # names before the pallas machinery binds refs
        params = [p for p in all_params[site.n_bound_pos:]
                  if p not in site.kernel_bindings]
    mod = site.mod

    # arity: prefetch + inputs + outputs + scratch
    if params is not None and site.n_inputs is not None and \
            site.n_outputs is not None:
        expected = (site.num_scalar_prefetch + site.n_inputs
                    + site.n_outputs + len(site.scratch))
        if len(params) != expected:
            findings.append(Finding(
                "APX209", mod.path, site.line, site.call.col_offset,
                f"kernel {site.kernel.name!r} takes {len(params)} "
                f"unbound positional ref(s) but the pallas_call "
                f"supplies {expected} ({site.num_scalar_prefetch} "
                f"prefetch + {site.n_inputs} in + {site.n_outputs} "
                f"out + {len(site.scratch)} scratch) — Mosaic reports "
                f"this as an opaque arity error at compile time"))
            return findings   # role mapping below would misalign

    # index_map arity: grid rank + prefetch
    if site.grid_len is not None:
        want = site.grid_len + site.num_scalar_prefetch
        for spec in site.in_specs + site.out_specs:
            if spec.index_map_arity is not None and \
                    spec.index_map_arity != want:
                findings.append(Finding(
                    "APX209", mod.path, spec.line, 0,
                    f"BlockSpec index_map takes "
                    f"{spec.index_map_arity} argument(s) but the grid "
                    f"supplies {want} ({site.grid_len} grid + "
                    f"{site.num_scalar_prefetch} scalar-prefetch)"))

    # scratch roles
    if params is None or site.n_inputs is None or \
            site.n_outputs is None or not site.scratch:
        return findings
    scratch_params = params[len(params) - len(site.scratch):]
    roles = {p: e for p, e in zip(scratch_params, site.scratch)}
    sem_use, buf_use = _usage(project, site)
    for p, entry in roles.items():
        if entry.kind in _SEM_KINDS and p in buf_use:
            findings.append(Finding(
                "APX209", mod.path, buf_use[p], 0,
                f"semaphore scratch {p!r} is used as a data buffer "
                f"(subscript access / DMA data operand) inside kernel "
                f"{site.kernel.name!r}"))
        if entry.kind == "vmem" and p in sem_use:
            findings.append(Finding(
                "APX209", mod.path, sem_use[p], 0,
                f"VMEM scratch {p!r} is used as a semaphore inside "
                f"kernel {site.kernel.name!r}"))
    return findings


def _base_ref_name(node) -> Optional[str]:
    """``name``, ``name.at[..]`` or ``name[..]`` -> ``name``."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr == "at":
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _usage(project: Project, site: PallasSite):
    """(sem_use, buf_use): kernel param name -> first line used in a
    semaphore position / a buffer position."""
    sem_use: Dict[str, int] = {}
    buf_use: Dict[str, int] = {}
    mod = site.kernel.mod
    for node in ast.walk(site.kernel.node):
        if isinstance(node, ast.Call):
            dotted = project.resolve_dotted(mod, node.func) or ""
            if dotted in (f"{PLTPU}.semaphore_signal",
                          f"{PLTPU}.semaphore_wait") and node.args:
                name = _base_ref_name(node.args[0])
                if name:
                    sem_use.setdefault(name, node.lineno)
            elif dotted == f"{PLTPU}.make_async_remote_copy":
                for i, arg in enumerate(node.args[:4]):
                    name = _base_ref_name(arg)
                    if not name:
                        continue
                    if i < 2:
                        buf_use.setdefault(name, node.lineno)
                    else:
                        sem_use.setdefault(name, node.lineno)
            elif dotted == f"{PLTPU}.make_async_copy":
                for i, arg in enumerate(node.args[:3]):
                    name = _base_ref_name(arg)
                    if not name:
                        continue
                    (buf_use if i < 2 else sem_use).setdefault(
                        name, node.lineno)
        elif isinstance(node, ast.Subscript) and isinstance(
                node.value, ast.Name):
            # direct data access only: `ref.at[slot]` slicing stays
            # role-neutral here (its role comes from the DMA/semaphore
            # call position it is passed to)
            buf_use.setdefault(node.value.id, node.lineno)
    return sem_use, buf_use
