"""Collective/mesh consistency pass (APX204–APX207).

Four checks over the shard_map surface that pass every CPU test and
fail only on a real mesh (or never fail, silently computing garbage):

- **APX204 ring-guard** — a function that dispatches a ``pallas_call``
  whose kernel performs inter-chip DMA must guard the degenerate ring
  first (``if n < 2: raise/return``): on one device the RDMA drain
  waits a never-started DMA — an in-kernel HANG, not an error message
  (PR 9 round-2 review). Guarded kernels are also what licenses the
  protocol checker to skip its n == 1 simulation.
- **APX205 ppermute-perm** — a statically evaluable ``ppermute``
  permutation must be injective in both coordinates with indices in
  ``[0, n)`` (duplicated sources/destinations are undefined; partial
  permutations are legal — halo's edge shifts use them — so coverage
  is NOT required).
- **APX206 axis-binding** — a collective's axis name must come from a
  function contract (parameter), a named constant (``AXIS_TP``), or a
  string literal the module visibly binds (a mesh axis name in
  ``make_mesh``/``Mesh``/``shard_map``/``PartitionSpec``). A bare
  string literal bound nowhere in sight is a typo'd or never-mounted
  axis waiting for an ``unbound axis name`` crash at dispatch time.
- **APX207 exclusive-knobs** — ``overlap=`` and ``fused=`` are
  mutually exclusive by design (docs/parallel.md): a def taking both
  must carry the both-set guard raise, and a call site passing both
  non-False values is an error today or after the next default flip.

All checks underclaim: anything not statically resolvable is skipped,
never guessed.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from apex1_tpu.lint.core import Finding
from apex1_tpu.lint.project import (FunctionInfo, ModuleSource, Project,
                                    own_body_walk)
from apex1_tpu.lint.kernels.extract import (PallasSite, pallas_sites,
                                            uses_remote_dma)

#: named-axis collectives -> index of the axis argument
AXIS_OPS: Dict[str, int] = {
    "jax.lax.psum": 1, "jax.lax.pmax": 1, "jax.lax.pmin": 1,
    "jax.lax.pmean": 1, "jax.lax.ppermute": 1,
    "jax.lax.psum_scatter": 1, "jax.lax.all_gather": 1,
    "jax.lax.pbroadcast": 1, "jax.lax.all_to_all": 1,
    "jax.lax.axis_index": 0, "jax.lax.axis_size": 0,
}

#: calls whose string arguments / kw names visibly bind mesh axis names
_BINDING_CALLS = (
    "jax.sharding.PartitionSpec", "jax.sharding.Mesh",
    "jax.sharding.NamedSharding", "jax.shard_map",
    "jax.experimental.shard_map.shard_map", "jax.make_mesh",
    "apex1_tpu.core.mesh.make_mesh",
    "apex1_tpu.core.mesh.make_hybrid_mesh",
    "apex1_tpu.core.mesh.local_mesh",
)

_AXIS_SIZE_OPS = ("jax.lax.axis_size", "jax.lax.psum")

_TRIAL_NS = (2, 3, 4, 5, 6)


def check(project: Project,
          sites: Optional[List[PallasSite]] = None) -> List[Finding]:
    if sites is None:
        sites = pallas_sites(project)
    findings: List[Finding] = []
    findings.extend(_ring_guard(project, sites))
    by_mod: Dict[int, List[FunctionInfo]] = {}
    for info in project.functions.values():
        by_mod.setdefault(id(info.mod), []).append(info)
    for mod in project.modules:
        if mod.tree is None:
            continue
        infos = by_mod.get(id(mod), [])
        bound = _bound_axis_literals(project, mod)
        for info in infos:
            findings.extend(_check_function(project, mod, info, bound))
        findings.extend(_exclusive_defs(infos))
    return findings


# ---------------------------------------------------------------------------
# APX204: ring-size guard before remote-DMA dispatch
# ---------------------------------------------------------------------------

def remote_dma_kernels(project: Project,
                       sites: List[PallasSite]) -> List[PallasSite]:
    return [s for s in sites if s.kernel is not None
            and uses_remote_dma(project, s.kernel)]


def _axis_size_names(project: Project, mod: ModuleSource,
                     info: FunctionInfo) -> Set[str]:
    """Names in ``info`` assigned from an axis-size source:
    ``jax.lax.axis_size(...)``, a module-local wrapper of it, or
    ``psum(1, axis)``."""
    out: Set[str] = set()
    for node in own_body_walk(info.node):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        if _is_axis_size_call(project, mod, info, node.value):
            out.add(node.targets[0].id)
    return out


def _is_axis_size_call(project, mod, info, call: ast.Call) -> bool:
    dotted = project.resolve_dotted(mod, call.func)
    if dotted == "jax.lax.axis_size":
        return True
    if dotted == "jax.lax.psum" and call.args and \
            isinstance(call.args[0], ast.Constant) and \
            call.args[0].value == 1:
        return True
    if isinstance(call.func, ast.Name):
        target = project.lookup_function(mod, info.scope, call.func.id)
        if target is not None and isinstance(
                target.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            body = [st for st in target.node.body
                    if not isinstance(st, ast.Expr)
                    or not isinstance(st.value, ast.Constant)]
            if len(body) == 1 and isinstance(body[0], ast.Return) and \
                    isinstance(body[0].value, ast.Call):
                return (project.resolve_dotted(
                    target.mod, body[0].value.func)
                    in ("jax.lax.axis_size",))
    return False


def _has_ring_guard(project, mod, info, before_line: int) -> bool:
    """An ``if`` comparing an axis-size-derived name against an int
    constant, raising or returning, lexically before the dispatch."""
    size_names = _axis_size_names(project, mod, info)
    if not size_names:
        return False
    for node in own_body_walk(info.node):
        if not isinstance(node, ast.If) or node.lineno >= before_line:
            continue
        test = node.test
        if not isinstance(test, ast.Compare):
            continue
        names = {sub.id for sub in ast.walk(test)
                 if isinstance(sub, ast.Name)}
        consts = [sub for sub in ast.walk(test)
                  if isinstance(sub, ast.Constant)
                  and isinstance(sub.value, int)]
        if not (names & size_names) or not consts:
            continue
        for st in node.body:
            if isinstance(st, (ast.Raise, ast.Return)):
                return True
    return False


def ring_guarded(project: Project, site: PallasSite) -> bool:
    if site.enclosing is None:
        return False
    return _has_ring_guard(project, site.mod, site.enclosing, site.line)


def _ring_guard(project: Project,
                sites: List[PallasSite]) -> List[Finding]:
    findings = []
    for site in remote_dma_kernels(project, sites):
        if not ring_guarded(project, site):
            findings.append(Finding(
                "APX204", site.mod.path, site.line, site.call.col_offset,
                f"remote-DMA kernel "
                f"{site.kernel.name if site.kernel else '?'!r} is "
                f"dispatched without a ring-size guard: at axis size 1 "
                f"the in-kernel drain waits a DMA that never starts (a "
                f"hang, not an error) — guard with `if n < 2: raise` "
                f"before the pallas_call"))
    return findings


def guarded_kernel_nodes(project: Project,
                         sites: List[PallasSite]) -> Set[int]:
    """Kernel nodes every dispatch of which carries a ring-size guard
    (the protocol checker's license to skip n == 1)."""
    by_kernel: Dict[int, List[PallasSite]] = {}
    for site in remote_dma_kernels(project, sites):
        by_kernel.setdefault(id(site.kernel.node), []).append(site)
    return {k for k, ss in by_kernel.items()
            if all(ring_guarded(project, s) for s in ss)}


# ---------------------------------------------------------------------------
# per-function checks: APX205 ppermute, APX206 axis binding, APX207 calls
# ---------------------------------------------------------------------------

def _check_function(project, mod, info, bound) -> List[Finding]:
    findings: List[Finding] = []
    size_names = _axis_size_names(project, mod, info)
    assigns: Dict[str, List[ast.Assign]] = {}
    for node in own_body_walk(info.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            assigns.setdefault(node.targets[0].id, []).append(node)
    for node in own_body_walk(info.node):
        if not isinstance(node, ast.Call):
            continue
        dotted = project.resolve_dotted(mod, node.func)
        if dotted == "jax.lax.ppermute":
            findings.extend(_check_perm(project, mod, info, node,
                                        size_names, assigns))
        if dotted in AXIS_OPS:
            findings.extend(_check_axis(project, mod, info, node,
                                        dotted, bound))
        findings.extend(_exclusive_call(mod, node))
    return findings


def _perm_expr(node: ast.Call, assigns) -> Optional[ast.AST]:
    perm = None
    for kw in node.keywords:
        if kw.arg == "perm":
            perm = kw.value
    if perm is None and len(node.args) > 2:
        perm = node.args[2]
    if isinstance(perm, ast.Name):
        cands = [a for a in assigns.get(perm.id, ())
                 if a.lineno < node.lineno]
        if len(cands) != 1:
            return None
        return cands[0].value
    return perm


class _PermEval(ast.NodeVisitor):
    """Tiny closed-form evaluator for permutation expressions: list
    comprehensions / literals over int arithmetic, ``range``, and the
    axis size bound to a trial n."""

    def __init__(self, env: Dict[str, int]):
        self.env = env

    def ev(self, node):
        if isinstance(node, ast.Constant) and isinstance(
                node.value, int) and not isinstance(node.value, bool):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in self.env:
                return self.env[node.id]
            raise ValueError(f"free name {node.id}")
        if isinstance(node, ast.Tuple):
            return tuple(self.ev(el) for el in node.elts)
        if isinstance(node, ast.List):
            return [self.ev(el) for el in node.elts]
        if isinstance(node, ast.BinOp):
            a, b = self.ev(node.left), self.ev(node.right)
            op = type(node.op)
            if op is ast.Add:
                return a + b
            if op is ast.Sub:
                return a - b
            if op is ast.Mult:
                return a * b
            if op is ast.Mod:
                return a % b
            if op is ast.FloorDiv:
                return a // b
            raise ValueError("op")
        if isinstance(node, ast.UnaryOp) and isinstance(
                node.op, ast.USub):
            return -self.ev(node.operand)
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Name) and node.func.id == "range":
            return range(*[self.ev(a) for a in node.args])
        if isinstance(node, ast.ListComp) and len(
                node.generators) == 1 and not node.generators[0].ifs:
            gen = node.generators[0]
            if not isinstance(gen.target, ast.Name):
                raise ValueError("target")
            out = []
            for v in self.ev(gen.iter):
                sub = _PermEval({**self.env, gen.target.id: v})
                out.append(sub.ev(node.elt))
            return out
        raise ValueError(type(node).__name__)


def _check_perm(project, mod, info, node, size_names,
                assigns) -> List[Finding]:
    expr = _perm_expr(node, assigns)
    if expr is None:
        return []
    free = {sub.id for sub in ast.walk(expr)
            if isinstance(sub, ast.Name)}
    comp_vars = {g.target.id for sub in ast.walk(expr)
                 if isinstance(sub, (ast.ListComp, ast.GeneratorExp))
                 for g in sub.generators
                 if isinstance(g.target, ast.Name)}
    unresolved = free - comp_vars - size_names - {"range"}
    if unresolved:
        return []     # underclaim: only axis-sized perms are provable
    for n in _TRIAL_NS:
        env = {name: n for name in size_names}
        try:
            perm = _PermEval(env).ev(expr)
        except ValueError:
            return []
        if not isinstance(perm, list) or not all(
                isinstance(p, tuple) and len(p) == 2
                and all(isinstance(v, int) for v in p) for p in perm):
            return []
        srcs = [p[0] for p in perm]
        dsts = [p[1] for p in perm]
        bad = None
        if len(set(srcs)) != len(srcs):
            bad = "duplicate source indices"
        elif len(set(dsts)) != len(dsts):
            bad = "duplicate destination indices"
        elif any(v < 0 or v >= n for v in srcs + dsts):
            bad = f"indices outside [0, {n})"
        if bad:
            return [Finding(
                "APX205", mod.path, node.lineno, node.col_offset,
                f"ppermute permutation is not a bijection over the "
                f"axis at size n={n}: {bad} in {perm!r}")]
    return []


def _bound_axis_literals(project, mod) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = project.resolve_dotted(mod, node.func) or ""
        if dotted in _BINDING_CALLS or dotted.endswith(
                (".PartitionSpec", ".NamedSharding", ".Mesh",
                 ".shard_map", ".make_mesh", ".make_hybrid_mesh")):
            for kw in node.keywords:
                if kw.arg:
                    out.add(kw.arg)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str):
                    out.add(sub.value)
    # module-level string constants are contracts, not literals
    for st in mod.tree.body:
        if isinstance(st, ast.Assign) and isinstance(
                st.value, ast.Constant) and isinstance(
                    st.value.value, str):
            out.add(st.value.value)
    return out


def _axis_arg(node: ast.Call, pos: int) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg in ("axis_name", "axis"):
            return kw.value
    if len(node.args) > pos:
        return node.args[pos]
    return None


def _enclosing_params(project, info: FunctionInfo) -> Set[str]:
    """Parameters of ``info`` and every lexically enclosing function."""
    out: Set[str] = set(info.params)
    key = info.mod.modname or info.mod.path
    scope = info.scope
    for k in range(len(scope) - 1, 0, -1):
        outer = project.functions.get((key, scope[:k]))
        if outer is not None:
            out |= set(outer.params)
    return out


def _check_axis(project, mod, info, node, dotted, bound) -> List[Finding]:
    arg = _axis_arg(node, AXIS_OPS[dotted])
    if arg is None:
        return []
    out: List[Finding] = []
    for expr in ([arg] if not isinstance(arg, (ast.Tuple, ast.List))
                 else list(arg.elts)):
        if not isinstance(expr, ast.Constant) or not isinstance(
                expr.value, str):
            continue  # params, constants, computed names: underclaim
        if expr.value in bound:
            continue
        out.append(Finding(
            "APX206", mod.path, node.lineno, node.col_offset,
            f"axis name {expr.value!r} in "
            f"{dotted.rsplit('.', 1)[-1]} is a bare string literal "
            f"bound by no visible mesh/shard_map/PartitionSpec in "
            f"this module and no function contract — a typo'd or "
            f"never-mounted axis fails only at dispatch time"))
    return out


# ---------------------------------------------------------------------------
# APX207: overlap= / fused= exclusivity
# ---------------------------------------------------------------------------

def _is_falsy_literal(node) -> bool:
    return isinstance(node, ast.Constant) and (
        node.value is False or node.value is None)


def _exclusive_call(mod: ModuleSource, node: ast.Call) -> List[Finding]:
    kw = {k.arg: k.value for k in node.keywords if k.arg}
    if "overlap" in kw and "fused" in kw:
        # both must be PROVABLY non-False: a variable on either side
        # (`overlap=opt, fused=True`) is a legal plumb-one-knob-through
        # pattern guarded at runtime — underclaim
        if isinstance(kw["overlap"], ast.Constant) and \
                isinstance(kw["fused"], ast.Constant) and \
                not _is_falsy_literal(kw["overlap"]) and \
                not _is_falsy_literal(kw["fused"]):
            return [Finding(
                "APX207", mod.path, node.lineno, node.col_offset,
                "overlap= and fused= passed together as non-False "
                "literals: the knobs are mutually exclusive (fused "
                "IS the overlap)")]
    return []


def _exclusive_defs(infos: List[FunctionInfo]) -> List[Finding]:
    findings = []
    for info in infos:
        mod = info.mod
        params = set(info.params)
        if not {"overlap", "fused"} <= params:
            continue
        guarded = False
        for node in own_body_walk(info.node):
            if not isinstance(node, ast.If):
                continue
            names = {sub.id for sub in ast.walk(node.test)
                     if isinstance(sub, ast.Name)}
            if {"overlap", "fused"} <= names and any(
                    isinstance(st, ast.Raise) for st in node.body):
                guarded = True
                break
        if not guarded:
            findings.append(Finding(
                "APX207", mod.path, info.line, 0,
                f"{info.name}() takes both overlap= and fused= but "
                f"never raises on the both-set combination — the "
                f"mutually-exclusive knobs are silently combinable"))
    return findings
