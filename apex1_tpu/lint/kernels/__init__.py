"""graftlint kernel analyzer — the APX2xx rule family.

The APX1xx rules gate the *host-side* JAX hazards tier-1 can at least
partially execute. This package gates the compiled-TPU-only surface
tier-1 can NEVER execute: Pallas kernel bodies and the shard_map
collective layer. Three cooperating analyses (all stdlib-``ast``, no
jax, no device):

- **protocol** (APX201–203): a micro-model-checker over each kernel's
  ``semaphore_signal``/``semaphore_wait``/``make_async_remote_copy``
  schedule, exhaustively simulated for ring sizes n=1..6 — the machine
  version of the manual "recount it for n=2..5" proof PR 9's review
  performed on the RDMA reduce-scatter (both of that review's races
  are regression fixtures in tests/test_lint_kernels.py);
- **mesh** (APX204–207): ppermute bijections, axis-name binding,
  ``overlap=``/``fused=`` exclusivity, ring-size guards before
  remote-DMA dispatch;
- **budget** (APX208–209): static VMEM lower bounds against the
  ``apex1_tpu.vmem_model`` planning budget (the ONE sizing model
  shared with ``tuning.registry`` and ``tools/aot_check.py``) and
  pallas_call<->kernel wiring sanity.

Entry points: ``tools/lint.py --kernels`` (the ``== graftlint kernels
==`` check_all step), ``lint_paths(..., kernels=True)``, and the
tier-1 repo self-check. The APX1xx suppression grammar and exit-code
contract apply unchanged: ``# graftlint: allow(APX202) -- reason``.
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple

from apex1_tpu.lint.core import Finding
from apex1_tpu.lint.project import Project
from apex1_tpu.lint.kernels import budget as _budget
from apex1_tpu.lint.kernels import mesh as _mesh
from apex1_tpu.lint.kernels.extract import (ExtractError,
                                            extract_schedule,
                                            is_protocol_kernel,
                                            pallas_sites)
from apex1_tpu.lint.kernels.protocol import (RING_SIZES,
                                             check_schedules)

__all__ = ["KERNEL_RULES", "KernelRule", "check_kernels"]


class KernelRule(NamedTuple):
    code: str
    slug: str
    summary: str


#: catalogue (the check functions are pass-level, not per-rule —
#: docs/lint.md documents each)
KERNEL_RULES = [
    KernelRule("APX201", "sem-protocol",
               "semaphore/DMA protocol defect: unpaired signal/wait, "
               "semaphore nonzero at kernel exit, or an unmodelable "
               "protocol kernel"),
    KernelRule("APX202", "dma-race",
               "DMA data race: a slot write not ordered after the "
               "wait licensing it, or a read observing "
               "schedule-dependent payloads"),
    KernelRule("APX203", "kernel-hang",
               "kernel can deadlock at some ring size n=1..6 "
               "(all devices blocked, nothing in flight)"),
    KernelRule("APX204", "ring-guard",
               "remote-DMA kernel dispatched without a ring-size "
               "guard (n==1 is an in-kernel hang)"),
    KernelRule("APX205", "ppermute-perm",
               "ppermute permutation is not a bijection over the "
               "named axis"),
    KernelRule("APX206", "axis-binding",
               "collective axis name bound by no mesh, shard_map, or "
               "function contract"),
    KernelRule("APX207", "exclusive-knobs",
               "overlap=/fused= both reachable (mutually exclusive "
               "by design)"),
    KernelRule("APX208", "vmem-budget",
               "statically provable VMEM frame exceeds the planning "
               "budget (shared apex1_tpu.vmem_model)"),
    KernelRule("APX209", "kernel-binding",
               "pallas_call<->kernel wiring mismatch: ref arity, "
               "index_map arity, or semaphore/buffer role confusion"),
]


def _protocol_findings(project: Project, sites) -> List[Finding]:
    guarded = _mesh.guarded_kernel_nodes(project, sites)
    findings: List[Finding] = []
    protocol_infos = [info for info in project.functions.values()
                      if is_protocol_kernel(project, info)]

    def mkey(info):
        return info.mod.modname or info.mod.path

    # Selection: `is_protocol_kernel` uses ast.walk, so a DISPATCH
    # function with a nested kernel def satisfies it too — but the
    # kernel, not its wrapper, is what must be simulated. Any protocol
    # function that strictly ENCLOSES a pallas_call-referenced kernel
    # is a wrapper and is excluded; of the rest, only the outermost are
    # kernels (their nested `pl.when` closures and helpers are
    # interpreted inline as part of the enclosing schedule).
    site_kernel_scopes = {
        (mkey(s.kernel), s.kernel.scope) for s in sites
        if s.kernel is not None
        and is_protocol_kernel(project, s.kernel)}
    wrappers = set()
    for info in protocol_infos:
        m = mkey(info)
        if any(ms == m and len(info.scope) < len(sc)
               and sc[:len(info.scope)] == info.scope
               for ms, sc in site_kernel_scopes):
            wrappers.add((m, info.scope))
    scopes = {(mkey(info), info.scope)
              for info in protocol_infos} - wrappers
    seen = set()
    for info in protocol_infos:
        m = mkey(info)
        if (m, info.scope) in wrappers:
            continue
        if any((m, info.scope[:k]) in scopes
               for k in range(1, len(info.scope))):
            continue
        if id(info.node) in seen:
            continue
        seen.add(id(info.node))
        # a ring-size-guarded kernel is unreachable at n == 1 by
        # construction: simulating it there would only re-prove the
        # guard's reason
        sizes = tuple(n for n in RING_SIZES
                      if n > 1 or id(info.node) not in guarded)
        schedules = {}
        try:
            for n in sizes:
                schedules[n] = extract_schedule(project, info.mod,
                                                info, n)
        except ExtractError as e:
            findings.append(Finding(
                "APX201", info.mod.path, e.line or info.line, 0,
                f"protocol kernel {info.name!r} cannot be "
                f"model-checked: {e} — keep semaphore/DMA kernels "
                f"inside the modelable fragment (docs/lint.md) or "
                f"suppress with a reason"))
            continue
        for issue in check_schedules(schedules):
            findings.append(Finding(
                issue.code, info.mod.path,
                issue.line or info.line, 0,
                f"[{info.name}, ring n={_fmt_ns(issue.ns)}] "
                f"{issue.msg}"))
    return findings


def _fmt_ns(ns) -> str:
    return ",".join(str(n) for n in sorted(ns))


def check_kernels(project: Project) -> List[Finding]:
    """All APX2xx findings for a built project."""
    sites = pallas_sites(project)
    findings = _protocol_findings(project, sites)
    findings.extend(_mesh.check(project, sites))
    findings.extend(_budget.check(project, sites))
    return findings
