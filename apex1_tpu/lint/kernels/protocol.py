"""The semaphore/DMA protocol micro-model-checker (APX201–APX203).

Automates the proof PR 9's review did by hand ("recounting for
n=2..5"): for each protocol kernel and each ring size n, build the
SPMD-symmetric transition system — n devices each running the
schedule :mod:`extract` produced, semaphores as counters, RDMA
transfers as in-flight items that *deliver nondeterministically* at any
point between their start and the wait that licenses consuming them —
and explore EVERY interleaving (DFS with memoized states). Checked
properties:

- **liveness** — no reachable state where all devices are blocked and
  nothing is in flight (APX203; ``n == 1`` turns the RDMA drain into a
  wait on a never-started DMA — the hang class the ring-size guard
  rule exists for);
- **torn sends** — a local write to a buffer slot while a DMA that
  reads that slot is still in flight: delivery observes content that
  differs from the content at start (APX202; PR 9 race #1,
  write-before-credit-wait);
- **read determinism** — every read of a DMA-fed buffer slot must
  observe the SAME payload in every interleaving; two reachable
  payloads mean the read is not ordered after the wait that completes
  its DMA / the credit protecting it (APX202; PR 9 race #2,
  credit-signal-before-read);
- **conservation** — per semaphore, increments arriving at a device
  (neighbor signals + DMA completions) must equal the wait decrements
  it performs, and every semaphore must be zero in every terminal
  state (APX201: unpaired signals, non-draining semaphores).

Payload identity is structural: each write event has a deterministic
tag ``(device, program_index)``; deliveries copy tags. Two schedules
disagreeing about which tag a read sees IS the race — no algorithm
knowledge needed, so the checker is generic over kernels.

What this does NOT prove (docs/lint.md has the full list): anything
beyond the modeled ring sizes (n=1..6), Mosaic lowering/DMA-engine
bugs, numerics, or performance. It is a protocol checker, not a
compiler.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from apex1_tpu.lint.kernels.extract import Event

#: ring sizes the checker simulates — covers the degenerate pair ring
#: (n=2, both neighbors one device), the first size with slot reuse
#: (n=4) and two sizes beyond it.
RING_SIZES = (1, 2, 3, 4, 5, 6)

#: memoized-state budget per (kernel, n). The shipped RDMA kernel
#: explores a few thousand states at n=6; the cap exists for runaway
#: (buggy, unthrottled) protocols and surfaces as an APX201 finding
#: when hit — an unexplorable protocol is not a verified protocol.
STATE_CAP = 120_000


@dataclasses.dataclass
class Issue:
    code: str          # "APX201" | "APX202" | "APX203"
    line: int
    key: str           # dedup key (issue class + anchor)
    msg: str
    ns: Set[int] = dataclasses.field(default_factory=set)


# compact event encoding for the simulator ---------------------------------
# ('r',  buf, slot, line, t)
# ('w',  buf, slot, line, t, idx)        idx = program index (tag id)
# ('sig', sem, slot, inc, off, line, t)
# ('wai', sem, slot, cnt, line, t)
# ('dma', src_buf, src_slot, dst_buf, dst_slot, send_sem, s_slot,
#         recv_sem, r_slot, off, line, t)


def _encode(schedule: Sequence[Sequence[Event]]
            ) -> Tuple[Tuple, FrozenSet[str]]:
    """Flatten per-step events into one device program; returns the
    program and the set of DMA-touched buffers (only their reads and
    writes are simulated — everything else is local arithmetic).
    A whole-ref access on a slotted DMA buffer (``buf[...]``) aliases
    EVERY slot the program ever addresses on that buffer, so it is
    expanded into one event per slot — collapsing it to slot 0 would
    certify torn sends on slots 1+ as clean."""
    dma_bufs: Set[str] = set()
    slots: Dict[str, Set[int]] = {}
    for evs in schedule:
        for e in evs:
            if e.kind == "dma":
                for sr in (e.desc.src, e.desc.dst):
                    dma_bufs.add(sr.ref)
                    slots.setdefault(sr.ref, set()).add(sr.key()[1])
            elif e.kind in ("read", "write") and e.ref.slot is not None:
                slots.setdefault(e.ref.ref, set()).add(e.ref.slot)
    prog = []
    for evs in schedule:
        for e in evs:
            if e.kind in ("read", "write"):
                if e.ref.ref not in dma_bufs:
                    continue
                kind = "r" if e.kind == "read" else "w"
                expand = (sorted(slots.get(e.ref.ref, {0})) or [0]) \
                    if e.ref.slot is None else [e.ref.slot]
                for slot in expand:
                    k = (kind, e.ref.ref, slot, e.line, e.t)
                    if kind == "w":
                        k = k + (len(prog),)
                    prog.append(k)
            elif e.kind == "signal":
                prog.append(("sig", e.ref.ref, e.ref.key()[1], e.count,
                             e.off, e.line, e.t))
            elif e.kind == "wait":
                prog.append(("wai", e.ref.ref, e.ref.key()[1], e.count,
                             e.line, e.t))
            elif e.kind == "dma":
                d = e.desc
                prog.append(("dma", d.src.ref, d.src.key()[1],
                             d.dst.ref, d.dst.key()[1],
                             d.send_sem.ref, d.send_sem.key()[1],
                             d.recv_sem.ref, d.recv_sem.key()[1],
                             d.off, e.line, e.t))
    return tuple(prog), frozenset(dma_bufs)


def _conservation(prog: Tuple, n: int) -> List[Issue]:
    """Static signal/wait pairing: by SPMD symmetry every device
    receives exactly what every device sends, so per (sem, slot) the
    arriving increments must equal the wait decrements."""
    inc: Dict[Tuple[str, int], int] = {}
    dec: Dict[Tuple[str, int], int] = {}
    first_line: Dict[Tuple[str, int], int] = {}
    for ev in prog:
        if ev[0] == "sig":
            k = (ev[1], ev[2])
            inc[k] = inc.get(k, 0) + ev[3]
            first_line.setdefault(k, ev[5])
        elif ev[0] == "wai":
            k = (ev[1], ev[2])
            dec[k] = dec.get(k, 0) + ev[3]
            first_line.setdefault(k, ev[4])
        elif ev[0] == "dma":
            ks = (ev[5], ev[6])
            kr = (ev[7], ev[8])
            inc[ks] = inc.get(ks, 0) + 1
            inc[kr] = inc.get(kr, 0) + 1
            first_line.setdefault(ks, ev[10])
            first_line.setdefault(kr, ev[10])
    issues = []
    for k in sorted(set(inc) | set(dec)):
        i, d = inc.get(k, 0), dec.get(k, 0)
        if i != d:
            sem, slot = k
            issues.append(Issue(
                "APX201", first_line.get(k, 0),
                f"conservation:{sem}:{slot}:{i - d}",
                f"semaphore {sem!r} slot {slot} receives {i} "
                f"increment(s) but waits consume {d} per device — "
                f"{'unconsumed signals leave it' if i > d else 'waits block forever; it ends'}"
                f" nonzero at kernel exit", {n}))
    return issues


class _Checker:
    def __init__(self, prog: Tuple, n: int, state_cap: int):
        self.prog = prog
        self.n = n
        self.cap = state_cap
        self.issues: List[Issue] = []
        self._seen_keys: Set[str] = set()
        # (grid step, line, slot) -> observed payload tags, banked
        # rotation-invariantly (provenance relative to the reader)
        self.reads: Dict[Tuple[int, int, int], Set] = {}
        self.cap_hit = False
        self.deadlocks: Set[Tuple] = set()
        self.bad_exit: Set[Tuple[str, int, int]] = set()
        self.torn: Set[Tuple[int, int, int]] = set()

    def _issue(self, code, line, dedup, msg):
        if dedup not in self._seen_keys:
            self._seen_keys.add(dedup)
            self.issues.append(Issue(code, line, dedup, msg, {self.n}))

    # state: (pcs, sems, bufs, inflight) — all hashable-canonical
    #   sems:     sorted tuple of ((dev, sem, slot), value>0)
    #   bufs:     sorted tuple of ((dev, buf, slot), tag)
    #   inflight: frozenset of (src_dev, dst_dev, src_buf, src_slot,
    #             dst_buf, dst_slot, send_sem, s_slot, recv_sem,
    #             r_slot, tag_at_start, line, t)
    #
    # Partial-order reduction (Lipton-style movers — what keeps n=6
    # exhaustively checkable): with per-device semaphores there is
    # exactly ONE consumer per semaphore instance, so a signal only
    # monotonically enables its single remote consumer, an enabled wait
    # only lowers a counter nobody else reads, and a DMA start whose
    # source buffer is never a delivery TARGET captures content no
    # concurrent transition can change. All three commute with every
    # other device's transitions, so executing the first enabled one
    # deterministically loses no reachable observation (reads, torn
    # sends, deadlocks, exit counts). Branching remains only where
    # interleavings genuinely differ: buffer reads/writes on DMA-fed
    # slots versus in-flight delivery timing.

    def run(self) -> None:
        n = self.n
        plen = len(self.prog)
        self._dst_bufs = {ev[3] for ev in self.prog if ev[0] == "dma"}
        # recv semaphores that plain signals also touch lose the
        # "only this delivery can unblock the consumer" eagerness
        self._signalled_sems = {ev[1] for ev in self.prog
                                if ev[0] == "sig"}
        init = self._settle(([0] * n, {}, {}, set()))
        stack = [init]
        visited = {self._rot_canonical(init)}
        while stack:
            if len(visited) > self.cap:
                self.cap_hit = True
                break
            state = stack.pop()
            pcs_t, sems_t, bufs_t, inflight = state
            moves = []
            for d in range(n):
                if pcs_t[d] < plen and self.prog[pcs_t[d]][0] in (
                        "r", "w", "dma"):
                    moves.append(("ev", d))
            for dma in inflight:
                moves.append(("del", dma))
            if not moves:
                if all(pc >= plen for pc in pcs_t):
                    for (d, sem, slot), v in sems_t:
                        if v:
                            self.bad_exit.add((sem, slot, v))
                else:
                    self._deadlock(pcs_t, dict(sems_t))
                continue
            for mv in moves:
                work = (list(pcs_t), dict(sems_t), dict(bufs_t),
                        set(inflight))
                self._apply(work, mv)
                nxt = self._settle(work)
                canon = self._rot_canonical(nxt)
                if canon not in visited:
                    visited.add(canon)
                    stack.append(nxt)

    def _rot_canonical(self, state) -> Tuple:
        """The ring is SPMD-symmetric: relabeling devices by a rotation
        maps reachable states to reachable states and preserves every
        recorded observation (reads are banked rotation-invariantly —
        payload provenance relative to the reading device). Memoizing
        the lexicographically-least rotation cuts the explored set by
        up to a factor of n."""
        pcs, sems, bufs, inflight = state
        n = self.n
        if n == 1:
            return state
        # cheap pre-filter: only rotations minimizing the pcs tuple can
        # be the canonical representative (ties are rare mid-run)
        rots = [tuple(pcs[(d + r) % n] for d in range(n))
                for r in range(n)]
        m = min(rots)
        best = None
        for r in range(n):
            if rots[r] != m:
                continue
            s = tuple(sorted((((k[0] - r) % n, k[1], k[2]), v)
                             for k, v in sems))
            b = tuple(sorted((((k[0] - r) % n, k[1], k[2]),
                              _rot_tag(t, r, n)) for k, t in bufs))
            f = tuple(sorted(
                ((i[0] - r) % n, (i[1] - r) % n) + i[2:10]
                + (_rot_tag(i[10], r, n),) + i[11:]
                for i in inflight))
            cand = (m, s, b, f)
            if best is None or cand < best:
                best = cand
        return best

    def _settle(self, work) -> Tuple:
        """Fast-forward every deterministic (mover) transition in place,
        then freeze the state: only genuine branch points are memoized.
        A settled state's pending device events are exactly the
        conflict-prone kinds ("r"/"w"/"dma" with a possible delivery
        race) plus blocked waits."""
        pcs, sems, bufs, inflight = work
        n = self.n
        plen = len(self.prog)
        dst_bufs = self._dst_bufs
        progressed = True
        while progressed:
            progressed = False
            for d in range(n):
                while pcs[d] < plen:
                    ev = self.prog[pcs[d]]
                    kind = ev[0]
                    if kind == "wai":
                        if sems.get((d, ev[1], ev[2]), 0) >= ev[3]:
                            self._apply(work, ("ev", d))
                            progressed = True
                            continue
                        break
                    if kind == "sig":
                        self._apply(work, ("ev", d))
                        progressed = True
                        continue
                    if kind == "dma" and ev[1] not in dst_bufs:
                        # start whose source no delivery can mutate:
                        # captures content nothing concurrent changes
                        self._apply(work, ("ev", d))
                        progressed = True
                        continue
                    if kind == "w" and ev[1] not in dst_bufs and \
                            not any(dma[0] == d and dma[2] == ev[1]
                                    and dma[3] == ev[2]
                                    for dma in inflight):
                        # a write to a slot that is never a delivery
                        # target conflicts only with SAME-device DMAs
                        # reading it; none in flight -> any future
                        # conflicting DMA is program-ordered after it
                        self._apply(work, ("ev", d))
                        progressed = True
                        continue
                    break
            for dma in list(inflight):
                dd = dma[1]
                key = (dd, dma[7], dma[8])
                if dma[7] in self._signalled_sems or any(
                        o is not dma and (o[1], o[7], o[8]) == key
                        for o in inflight):
                    continue
                if pcs[dd] >= plen:
                    # consumer finished: no read can ever conflict
                    self._apply(work, ("del", dma))
                    progressed = True
                    continue
                nxt = self.prog[pcs[dd]]
                if nxt[0] == "wai" and (nxt[1], nxt[2]) == (
                        dma[7], dma[8]) and \
                        sems.get(key, 0) < nxt[3]:
                    # consumer is blocked on THIS delivery's recv
                    # semaphore and nothing else can unblock it: no
                    # conflicting read/write can precede the delivery
                    # in any schedule — deliver now
                    self._apply(work, ("del", dma))
                    progressed = True
        return (tuple(pcs), _canon(sems), _canon_b(bufs),
                frozenset(inflight))

    def _apply(self, work, mv) -> None:
        pcs, sems, bufs, inflight = work
        if mv[0] == "del":
            dma = mv[1]
            (src_dev, dst_dev, src_buf, src_slot, dst_buf, dst_slot,
             send_sem, s_slot, recv_sem, r_slot, tag0, line, t) = dma
            cur = bufs.get((src_dev, src_buf, src_slot))
            if cur != tag0:
                # the slot was overwritten while the DMA was reading it
                wline = (self.prog[cur[1]][3]
                         if isinstance(cur, tuple) else line)
                self.torn.add((wline, line, t))
            bufs[(dst_dev, dst_buf, dst_slot)] = cur
            k = (dst_dev, recv_sem, r_slot)
            sems[k] = sems.get(k, 0) + 1
            k = (src_dev, send_sem, s_slot)
            sems[k] = sems.get(k, 0) + 1
            inflight.discard(dma)
            return
        d = mv[1]
        ev = self.prog[pcs[d]]
        pcs[d] += 1
        kind = ev[0]
        if kind == "r":
            tag = bufs.get((d, ev[1], ev[2]))
            # bank the observation rotation-invariantly: payload
            # provenance RELATIVE to the reading device. Keyed per
            # SLOT — a whole-ref read expands to one event per slot,
            # and distinct slots legitimately hold distinct payloads.
            rel = (((tag[0] - d) % self.n, tag[1])
                   if isinstance(tag, tuple) else None)
            self.reads.setdefault((ev[4], ev[3], ev[2]),
                                  set()).add(rel)
        elif kind == "w":
            bufs[(d, ev[1], ev[2])] = (d, ev[5])
        elif kind == "sig":
            tgt = ((d + ev[4]) % self.n, ev[1], ev[2])
            sems[tgt] = sems.get(tgt, 0) + ev[3]
        elif kind == "wai":
            k = (d, ev[1], ev[2])
            sems[k] = sems.get(k, 0) - ev[3]
            if sems[k] == 0:
                del sems[k]
        elif kind == "dma":
            tgt = (d + ev[9]) % self.n
            tag0 = bufs.get((d, ev[1], ev[2]))
            inflight.add((d, tgt, ev[1], ev[2], ev[3], ev[4], ev[5],
                          ev[6], ev[7], ev[8], tag0, ev[10], ev[11]))

    def _deadlock(self, pcs, sems) -> None:
        blocked = []
        for d in range(self.n):
            pc = pcs[d]
            if pc < len(self.prog):
                ev = self.prog[pc]
                if ev[0] == "wai":
                    blocked.append((ev[4], ev[1], ev[2], ev[5]))
        blocked.sort()
        self.deadlocks.add(tuple(sorted(set(blocked))))

    def collect(self) -> List[Issue]:
        for b in sorted(self.deadlocks):
            if not b:
                continue
            line, sem, slot, t = b[0]
            waits = ", ".join(
                f"line {ln} (sem {s!r} slot {sl}, grid step {tt})"
                for ln, s, sl, tt in b)
            hint = (" — on a single device the DMA the drain waits for "
                    "is never started (ring-size guard missing?)"
                    if self.n == 1 else "")
            self._issue(
                "APX203", line, f"deadlock:{b}",
                f"kernel can hang at ring size n={self.n}: every "
                f"device blocks at {waits} with nothing in "
                f"flight{hint}")
        for sem, slot, v in sorted(self.bad_exit):
            self._issue(
                "APX201", 0, f"exit:{sem}:{slot}",
                f"semaphore {sem!r} slot {slot} is {v} (not zero) at "
                f"kernel exit at ring size n={self.n}")
        for wline, dline, t in sorted(self.torn):
            self._issue(
                "APX202", wline, f"torn:{wline}:{dline}",
                f"write at line {wline} can overwrite a buffer slot "
                f"while the DMA started at line {dline} (grid step "
                f"{t}) is still reading it — the write is not ordered "
                f"after the send-wait/credit that licenses the slot "
                f"reuse (n={self.n})")
        for (t, line, slot), tags in sorted(self.reads.items()):
            if len(tags) > 1:
                # dedup on the LINE only (like the torn-send key): one
                # racy read is one defect; check_schedules' ns merge
                # then aggregates the ring sizes/steps it reproduces at
                self._issue(
                    "APX202", line, f"nondet:{line}",
                    f"read at line {line} (first at grid step {t}, "
                    f"slot {slot}) can observe different in-flight "
                    f"payloads depending on the schedule (n={self.n}) "
                    f"— the read is not ordered after the DMA-wait "
                    f"that completes it, or its slot's credit is "
                    f"returned before the read")
        if self.cap_hit:
            self._issue(
                "APX201", 0, "cap",
                f"state space exceeds {self.cap} states at n={self.n} "
                f"— the protocol is not flow-controlled enough to "
                f"verify (missing credit waits let devices drift "
                f"unboundedly)")
        return self.issues


def _rot_tag(tag, r: int, n: int) -> Tuple[int, int]:
    """Payload tag under a device rotation; the never-written sentinel
    sorts uniformly as (-1, -1)."""
    if isinstance(tag, tuple):
        return ((tag[0] - r) % n, tag[1])
    return (-1, -1)


def _canon(sems: Dict) -> Tuple:
    return tuple(sorted((k, v) for k, v in sems.items() if v))


def _canon_b(bufs: Dict) -> Tuple:
    return tuple(sorted(bufs.items()))


def check_schedules(schedules_by_n: Dict[int, Sequence[Sequence[Event]]],
                    state_cap: int = STATE_CAP) -> List[Issue]:
    """Model-check one kernel over all extracted ring sizes; issues are
    deduplicated across sizes (the ``ns`` field collects every ring
    size an issue reproduces at)."""
    merged: Dict[str, Issue] = {}
    for n, schedule in sorted(schedules_by_n.items()):
        prog, _bufs = _encode(schedule)
        issues = _conservation(prog, n)
        chk = _Checker(prog, n, state_cap)
        chk.run()
        issues.extend(chk.collect())
        for iss in issues:
            prev = merged.get(iss.key + iss.code)
            if prev is None:
                merged[iss.key + iss.code] = iss
            else:
                prev.ns |= iss.ns
    out = list(merged.values())
    out.sort(key=lambda i: (i.line, i.code, i.key))
    return out
