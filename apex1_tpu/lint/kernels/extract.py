"""Kernel-body extraction: from AST to analyzable structures.

Two extractors live here:

1. :func:`pallas_sites` — every ``pallas_call`` call site in a module,
   with its kernel function resolved (through ``functools.partial``),
   its grid / ``num_scalar_prefetch`` / in_specs / out_shape /
   scratch_shapes parsed as far as they are static. The budget and
   binding passes (APX208/APX209) consume these.

2. :class:`ScheduleExtractor` — a micro-interpreter over a kernel
   function's body that, for a CONCRETE ring size ``n`` and grid step
   ``t``, evaluates ``pl.when`` predicates and slot arithmetic and
   emits the kernel's semaphore/DMA **event schedule**: buffer
   reads/writes, ``semaphore_signal``/``semaphore_wait``,
   ``make_async_remote_copy`` starts and their send/recv waits. The
   protocol model checker (APX201–203) simulates these schedules
   exhaustively.

The modelable fragment (documented in docs/lint.md): a protocol kernel
must take its ring size as a kw-only parameter named ``n`` (or
``ring_size``/``n_devices``) and its ring axis as ``axis_name``/
``axis``; slot indices and ``pl.when`` predicates must be arithmetic
over ``pl.program_id``, that ``n``, and integer constants. Everything
data-dependent is abstracted: an unsupported construct raises
:class:`ExtractError` and surfaces as an APX201 "unmodelable" finding —
a protocol kernel that cannot be machine-checked must be simplified or
suppressed with a reason, never silently passed.

Like the rest of graftlint this is stdlib-``ast`` only: no jax import,
runs on the no-TPU CI image in ~milliseconds per (kernel, n).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple

from apex1_tpu.lint.project import FunctionInfo, ModuleSource, Project

PALLAS_CALL = "jax.experimental.pallas.pallas_call"
PL = "jax.experimental.pallas"
PLTPU = "jax.experimental.pallas.tpu"

#: kw-only kernel params the checker binds to the trial ring size
RING_PARAMS = ("n", "ring_size", "n_devices")
#: kw-only kernel params bound to an (inert) axis token
AXIS_PARAMS = ("axis_name", "axis")

#: callables that make a kernel a "protocol kernel"
_PROTOCOL_OPS = (
    f"{PLTPU}.semaphore_signal",
    f"{PLTPU}.semaphore_wait",
    f"{PLTPU}.make_async_remote_copy",
)


# ---------------------------------------------------------------------------
# pallas_call site parsing (budget / binding passes)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ScratchEntry:
    """One ``scratch_shapes`` element, as static as the AST allows."""

    kind: str                 # "vmem" | "sem_dma" | "sem_regular" |
    #                           "sem_barrier" | "unknown"
    shape: Optional[Tuple]    # ints where static, None elsewhere
    dtype: Optional[str]      # "float32", ... when written literally
    line: int

    def static_bytes(self) -> Optional[int]:
        if self.kind != "vmem" or self.shape is None:
            return None
        total = 1
        for d in self.shape:
            if not isinstance(d, int):
                return None
            total *= d
        es = _DTYPE_BYTES.get(self.dtype or "", None)
        return None if es is None else total * es


_DTYPE_BYTES = {
    "float32": 4, "int32": 4, "uint32": 4, "bfloat16": 2, "float16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "float64": 8, "int64": 8,
}


@dataclasses.dataclass
class BlockSpecInfo:
    shape: Optional[Tuple]        # block shape, ints where static
    index_map_arity: Optional[int]
    line: int


@dataclasses.dataclass
class PallasSite:
    mod: ModuleSource
    call: ast.Call
    enclosing: Optional[FunctionInfo]   # the dispatch function
    kernel: Optional[FunctionInfo]      # resolved kernel body
    kernel_bindings: Dict[str, ast.AST]  # partial(...) kw bindings
    n_bound_pos: int                     # partial(...) positional args
    grid_len: Optional[int]
    num_scalar_prefetch: int
    n_inputs: Optional[int]
    n_outputs: Optional[int]
    scratch: List[ScratchEntry]
    in_specs: List[BlockSpecInfo]
    out_specs: List[BlockSpecInfo]

    @property
    def line(self) -> int:
        return self.call.lineno


def _static_int(node) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _static_int(node.operand)
        return None if inner is None else -inner
    return None


def _static_shape(node) -> Optional[Tuple]:
    """A tuple/list literal -> tuple with ints where static and None
    placeholders elsewhere; non-sequence -> None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    return tuple(_static_int(el) for el in node.elts)


def _dtype_name(project: Project, mod: ModuleSource,
                node) -> Optional[str]:
    dotted = project.resolve_dotted(mod, node)
    if dotted:
        tail = dotted.rsplit(".", 1)[-1]
        if tail in _DTYPE_BYTES:
            return tail
    return None


def _parse_scratch(project: Project, mod: ModuleSource,
                   node) -> List[ScratchEntry]:
    out: List[ScratchEntry] = []
    if not isinstance(node, (ast.List, ast.Tuple)):
        return out
    for el in node.elts:
        line = el.lineno
        if isinstance(el, ast.Call):
            dotted = project.resolve_dotted(mod, el.func) or ""
            if dotted == f"{PLTPU}.VMEM":
                shape = _static_shape(el.args[0]) if el.args else None
                dt = (_dtype_name(project, mod, el.args[1])
                      if len(el.args) > 1 else None)
                out.append(ScratchEntry("vmem", shape, dt, line))
                continue
            if dotted == f"{PLTPU}.SemaphoreType.DMA":
                out.append(ScratchEntry("sem_dma", None, None, line))
                continue
            if dotted == f"{PLTPU}.SemaphoreType.BARRIER":
                out.append(ScratchEntry("sem_barrier", None, None, line))
                continue
        else:
            dotted = project.resolve_dotted(mod, el) or ""
            if dotted == f"{PLTPU}.SemaphoreType.REGULAR":
                out.append(ScratchEntry("sem_regular", None, None, line))
                continue
            if dotted == f"{PLTPU}.SemaphoreType.DMA":
                out.append(ScratchEntry("sem_dma", None, None, line))
                continue
            if dotted == f"{PLTPU}.SemaphoreType.BARRIER":
                out.append(ScratchEntry("sem_barrier", None, None, line))
                continue
        out.append(ScratchEntry("unknown", None, None, line))
    return out


def _parse_blockspec(project: Project, mod: ModuleSource,
                     node) -> Optional[BlockSpecInfo]:
    if not isinstance(node, ast.Call):
        return None
    dotted = project.resolve_dotted(mod, node.func) or ""
    if not dotted.endswith(".BlockSpec"):
        return None
    shape = _static_shape(node.args[0]) if node.args else None
    arity = None
    imap = node.args[1] if len(node.args) > 1 else None
    for kw in node.keywords:
        if kw.arg == "index_map":
            imap = kw.value
    if isinstance(imap, ast.Lambda):
        a = imap.args
        arity = len(a.posonlyargs) + len(a.args)
    return BlockSpecInfo(shape, arity, node.lineno)


def _parse_specs(project, mod, node) -> List[BlockSpecInfo]:
    out: List[BlockSpecInfo] = []
    if isinstance(node, (ast.List, ast.Tuple)):
        for el in node.elts:
            bs = _parse_blockspec(project, mod, el)
            if bs is not None:
                out.append(bs)
    else:
        bs = _parse_blockspec(project, mod, node)
        if bs is not None:
            out.append(bs)
    return out


def _count_out_shape(node) -> Optional[int]:
    """Number of outputs when the out_shape expression is statically a
    list/tuple (each element one output) or a single struct call."""
    if isinstance(node, (ast.List, ast.Tuple)):
        return len(node.elts)
    if isinstance(node, ast.Call):
        return 1
    return None


def _resolve_kernel(project: Project, mod: ModuleSource,
                    scope: Tuple[str, ...], node
                    ) -> Tuple[Optional[FunctionInfo],
                               Dict[str, ast.AST], int]:
    """First positional arg of pallas_call -> (kernel FunctionInfo,
    partial KW bindings, count of partial-bound POSITIONAL args)."""
    bindings: Dict[str, ast.AST] = {}
    if isinstance(node, ast.Call):
        dotted = project.resolve_dotted(mod, node.func) or ""
        is_partial = dotted == "functools.partial" or (
            isinstance(node.func, ast.Name)
            and node.func.id == "partial")
        if is_partial and node.args:
            for kw in node.keywords:
                if kw.arg:
                    bindings[kw.arg] = kw.value
            inner, more, n_pos = _resolve_kernel(project, mod, scope,
                                                 node.args[0])
            bindings.update(more)
            return inner, bindings, n_pos + len(node.args) - 1
        return None, bindings, 0
    if isinstance(node, ast.Name):
        return project.lookup_function(mod, scope, node.id), bindings, 0
    return None, bindings, 0


def pallas_sites(project: Project) -> List[PallasSite]:
    # innermost enclosing function per call node: a call inside a
    # nested def is reached by ast.walk of every enclosing function,
    # so keep the deepest scope only
    best: Dict[int, Tuple[int, ModuleSource, FunctionInfo, ast.Call]] = {}
    for info in project.functions.values():
        mod = info.mod
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call) and (
                    project.resolve_dotted(mod, node.func)
                    == PALLAS_CALL):
                prev = best.get(id(node))
                if prev is None or len(info.scope) > prev[0]:
                    best[id(node)] = (len(info.scope), mod, info, node)
    return [_parse_site(project, mod, info, node)
            for _, mod, info, node in best.values()]


def _parse_site(project: Project, mod: ModuleSource,
                enclosing: Optional[FunctionInfo],
                call: ast.Call) -> PallasSite:
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    kernel, bindings, n_bound_pos = _resolve_kernel(
        project, mod, enclosing.scope if enclosing else (),
        call.args[0] if call.args else None)

    grid_len = None
    prefetch = 0
    in_specs: List[BlockSpecInfo] = []
    out_specs: List[BlockSpecInfo] = []
    scratch: List[ScratchEntry] = []

    grid_node = kw.get("grid")
    gs = kw.get("grid_spec")
    if isinstance(gs, ast.Call):
        gdotted = project.resolve_dotted(mod, gs.func) or ""
        if gdotted.endswith("PrefetchScalarGridSpec") or \
                gdotted.endswith("GridSpec"):
            gkw = {k.arg: k.value for k in gs.keywords if k.arg}
            grid_node = gkw.get("grid", grid_node)
            pf = _static_int(gkw.get("num_scalar_prefetch"))
            prefetch = pf if pf is not None else 0
            if "in_specs" in gkw:
                in_specs = _parse_specs(project, mod, gkw["in_specs"])
                kw.setdefault("in_specs", gkw["in_specs"])
            if "out_specs" in gkw:
                out_specs = _parse_specs(project, mod, gkw["out_specs"])
            if "scratch_shapes" in gkw:
                scratch = _parse_scratch(project, mod,
                                         gkw["scratch_shapes"])
    if isinstance(grid_node, (ast.Tuple, ast.List)):
        grid_len = len(grid_node.elts)
    elif _static_int(grid_node) is not None:
        grid_len = 1

    n_inputs = None
    if "in_specs" in kw:
        if not in_specs:
            in_specs = _parse_specs(project, mod, kw["in_specs"])
        if isinstance(kw["in_specs"], (ast.List, ast.Tuple)):
            n_inputs = len(kw["in_specs"].elts)
    if "out_specs" in kw and not out_specs:
        out_specs = _parse_specs(project, mod, kw["out_specs"])
    if "scratch_shapes" in kw and not scratch:
        scratch = _parse_scratch(project, mod, kw["scratch_shapes"])
    n_outputs = _count_out_shape(kw.get("out_shape"))

    return PallasSite(mod=mod, call=call, enclosing=enclosing,
                      kernel=kernel, kernel_bindings=bindings,
                      n_bound_pos=n_bound_pos,
                      grid_len=grid_len, num_scalar_prefetch=prefetch,
                      n_inputs=n_inputs, n_outputs=n_outputs,
                      scratch=scratch, in_specs=in_specs,
                      out_specs=out_specs)


def is_protocol_kernel(project: Project, info: FunctionInfo) -> bool:
    """Does this function body (incl. nested ``pl.when`` defs) touch the
    semaphore/DMA layer?"""
    for node in ast.walk(info.node):
        if isinstance(node, ast.Call):
            dotted = project.resolve_dotted(info.mod, node.func)
            if dotted in _PROTOCOL_OPS:
                return True
    return False


def uses_remote_dma(project: Project, info: FunctionInfo) -> bool:
    for node in ast.walk(info.node):
        if isinstance(node, ast.Call):
            dotted = project.resolve_dotted(info.mod, node.func)
            if dotted == f"{PLTPU}.make_async_remote_copy":
                return True
    return False


# ---------------------------------------------------------------------------
# schedule extraction: the micro-interpreter
# ---------------------------------------------------------------------------

class ExtractError(Exception):
    """Kernel falls outside the modelable fragment."""

    def __init__(self, msg: str, line: int = 0):
        super().__init__(msg)
        self.line = line


@dataclasses.dataclass(frozen=True)
class SlotRef:
    ref: str
    slot: Optional[int]       # None = the whole (unsliced) ref

    def key(self) -> Tuple[str, int]:
        return (self.ref, 0 if self.slot is None else self.slot)


@dataclasses.dataclass(frozen=True)
class Desc:
    src: SlotRef
    dst: SlotRef
    send_sem: SlotRef
    recv_sem: SlotRef
    off: int                  # ring offset of the target device
    line: int


@dataclasses.dataclass
class Event:
    kind: str                 # "read" | "write" | "signal" | "wait" |
    #                           "dma"
    line: int
    t: int = 0
    ref: Optional[SlotRef] = None      # read/write/signal/wait subject
    count: int = 1                     # signal inc / wait count
    off: int = 0                       # signal target ring offset
    desc: Optional[Desc] = None        # dma


class _Ref:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name


class _RefAt:
    __slots__ = ("ref",)

    def __init__(self, ref):
        self.ref = ref


class _Data:
    """Opaque traced value; ``derived`` carries the concrete ints it was
    built from (the ``dev(i)`` provenance trick)."""

    __slots__ = ("derived",)

    def __init__(self, derived=frozenset()):
        self.derived = frozenset(derived)


class _Closure:
    __slots__ = ("node", "env")

    def __init__(self, node, env):
        self.node = node
        self.env = env


class _Method:
    __slots__ = ("desc", "op")

    def __init__(self, desc, op):
        self.desc = desc
        self.op = op


class _Axis:
    __slots__ = ()


_UNSET = object()


class ScheduleExtractor:
    """Interpret one kernel body for concrete (n, t); ``events`` is the
    program-order schedule of that grid step on any device (the ring is
    SPMD-symmetric; the interpreter runs as device 0, neighbor targets
    become signed ring offsets)."""

    def __init__(self, project: Project, mod: ModuleSource,
                 info: FunctionInfo, n: int, t: int):
        self.project = project
        self.mod = mod
        self.info = info
        self.n = n
        self.t = t
        self.events: List[Event] = []
        self._barrier = _Ref("<barrier>")

    # -- entry ------------------------------------------------------------

    def run(self) -> List[Event]:
        env: Dict[str, object] = {}
        node = self.info.node
        args = node.args
        for p in args.posonlyargs + args.args:
            env[p.arg] = _Ref(p.arg)
        for p in args.kwonlyargs:
            if p.arg in RING_PARAMS:
                env[p.arg] = self.n
            elif p.arg in AXIS_PARAMS:
                env[p.arg] = _Axis()
            else:
                raise ExtractError(
                    f"unmodelable kw-only kernel parameter {p.arg!r} "
                    f"(the checker binds only {RING_PARAMS} and "
                    f"{AXIS_PARAMS})", node.lineno)
        if args.vararg or args.kwarg:
            raise ExtractError("*args/**kwargs kernels are unmodelable",
                               node.lineno)
        self._exec_body(node.body, [env])
        for ev in self.events:
            ev.t = self.t
        return self.events

    # -- statements -------------------------------------------------------

    def _exec_body(self, body, envs) -> object:
        for st in body:
            r = self._exec_stmt(st, envs)
            if r is not _UNSET:
                return r
        return _UNSET

    def _exec_stmt(self, st, envs) -> object:
        if isinstance(st, ast.Assign):
            val = self._eval(st.value, envs)
            for tgt in st.targets:
                self._assign(tgt, val, envs)
            return _UNSET
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._assign(st.target, self._eval(st.value, envs), envs)
            return _UNSET
        if isinstance(st, ast.AugAssign):
            cur = self._eval(ast.BinOp(
                left=_load_of(st.target), op=st.op, right=st.value,
                lineno=st.lineno, col_offset=st.col_offset), envs)
            self._assign(st.target, cur, envs)
            return _UNSET
        if isinstance(st, ast.Expr):
            self._eval(st.value, envs)
            return _UNSET
        if isinstance(st, ast.FunctionDef):
            when = self._when_cond(st, envs)
            if when is None:
                envs[-1][st.name] = _Closure(st, list(envs))
            elif when:
                self._exec_body(st.body, envs + [{}])
            return _UNSET
        if isinstance(st, ast.Return):
            return (self._eval(st.value, envs)
                    if st.value is not None else None)
        if isinstance(st, (ast.Import, ast.ImportFrom)):
            for al in st.names:
                envs[-1][al.asname or al.name.split(".")[0]] = \
                    _Data()
            return _UNSET
        if isinstance(st, ast.If):
            cond = self._eval(st.test, envs)
            if isinstance(cond, _Data):
                raise ExtractError(
                    "python `if` on a traced value in a protocol "
                    "kernel", st.lineno)
            if cond:
                return self._exec_body(st.body, envs)
            return self._exec_body(st.orelse, envs)
        if isinstance(st, ast.Pass):
            return _UNSET
        raise ExtractError(
            f"unmodelable statement {type(st).__name__}", st.lineno)

    def _when_cond(self, st: ast.FunctionDef, envs) -> Optional[bool]:
        """``@pl.when(cond)`` decorator -> bool; None if not a when-def."""
        if len(st.decorator_list) != 1:
            if st.decorator_list:
                raise ExtractError(
                    "unmodelable kernel decorator", st.lineno)
            return None
        dec = st.decorator_list[0]
        if isinstance(dec, ast.Call) and (
                self.project.resolve_dotted(self.mod, dec.func)
                == f"{PL}.when"):
            cond = self._eval(dec.args[0], envs)
            if isinstance(cond, _Data):
                raise ExtractError(
                    "pl.when predicate depends on traced data "
                    "(unmodelable)", dec.lineno)
            return bool(cond)
        raise ExtractError("unmodelable kernel decorator", st.lineno)

    def _assign(self, tgt, val, envs) -> None:
        if isinstance(tgt, ast.Name):
            envs[-1][tgt.id] = val
            return
        if isinstance(tgt, ast.Tuple) and isinstance(val, tuple) \
                and len(tgt.elts) == len(val):
            for el, v in zip(tgt.elts, val):
                self._assign(el, v, envs)
            return
        if isinstance(tgt, ast.Subscript):
            obj = self._eval(tgt.value, envs)
            if isinstance(obj, _Ref):
                self.events.append(Event(
                    "write", tgt.lineno,
                    ref=SlotRef(obj.name, self._slot(tgt.slice, envs))))
                return
        raise ExtractError(
            f"unmodelable assignment target {type(tgt).__name__}",
            tgt.lineno)

    # -- expressions ------------------------------------------------------

    def _slot(self, node, envs) -> Optional[int]:
        if isinstance(node, ast.Constant) and node.value is Ellipsis:
            return None
        if isinstance(node, ast.Slice):
            return None
        v = self._eval(node, envs)
        if isinstance(v, bool):
            v = int(v)
        if isinstance(v, int):
            return v
        raise ExtractError("slot index is not statically evaluable",
                           getattr(node, "lineno", 0))

    def _eval(self, node, envs):
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            for env in reversed(envs):
                if node.id in env:
                    return env[node.id]
            const = self._module_const(node.id)
            if const is not _UNSET:
                return const
            raise ExtractError(f"unresolvable name {node.id!r}",
                              node.lineno)
        if isinstance(node, ast.Tuple):
            return tuple(self._eval(el, envs) for el in node.elts)
        if isinstance(node, ast.List):
            return [self._eval(el, envs) for el in node.elts]
        if isinstance(node, ast.BinOp):
            return self._binop(node, envs)
        if isinstance(node, ast.UnaryOp):
            v = self._eval(node.operand, envs)
            if isinstance(v, _Data):
                return _Data(v.derived)
            if isinstance(node.op, ast.USub):
                return -v
            if isinstance(node.op, ast.Not):
                return not v
            return v
        if isinstance(node, ast.Compare):
            return self._compare(node, envs)
        if isinstance(node, ast.BoolOp):
            vals = [self._eval(v, envs) for v in node.values]
            if any(isinstance(v, _Data) for v in vals):
                return _Data()
            if isinstance(node.op, ast.And):
                out = True
                for v in vals:
                    out = out and v
                return out
            out = False
            for v in vals:
                out = out or v
            return out
        if isinstance(node, ast.IfExp):
            cond = self._eval(node.test, envs)
            if isinstance(cond, _Data):
                return _Data(self._free_ints(node, envs))
            return self._eval(node.body if cond else node.orelse, envs)
        if isinstance(node, ast.Call):
            return self._call(node, envs)
        if isinstance(node, ast.Attribute):
            return self._attribute(node, envs)
        if isinstance(node, ast.Subscript):
            return self._subscript(node, envs)
        if isinstance(node, (ast.GeneratorExp, ast.ListComp,
                             ast.SetComp)):
            return self._comprehension(node, envs)
        if isinstance(node, ast.JoinedStr):
            return _Data()
        raise ExtractError(
            f"unmodelable expression {type(node).__name__}",
            getattr(node, "lineno", 0))

    def _module_const(self, name: str):
        """Module-level literal constant (``_SOME_ID = 7``)."""
        tree = self.mod.tree
        if tree is None:
            return _UNSET
        for st in tree.body:
            if isinstance(st, ast.Assign):
                for tgt in st.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == name:
                        try:
                            return ast.literal_eval(st.value)
                        except (ValueError, SyntaxError):
                            return _UNSET
        return _UNSET

    def _binop(self, node, envs):
        a = self._eval(node.left, envs)
        b = self._eval(node.right, envs)
        if isinstance(a, _Data) or isinstance(b, _Data):
            der = frozenset()
            for v in (a, b):
                if isinstance(v, _Data):
                    der |= v.derived
            return _Data(der)
        try:
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Sub):
                return a - b
            if isinstance(node.op, ast.Mult):
                return a * b
            if isinstance(node.op, ast.Mod):
                return a % b
            if isinstance(node.op, ast.FloorDiv):
                return a // b
            if isinstance(node.op, ast.Div):
                return a / b
            if isinstance(node.op, ast.Pow):
                return a ** b
        except Exception as e:
            raise ExtractError(f"arithmetic failed: {e}", node.lineno)
        raise ExtractError(
            f"unmodelable operator {type(node.op).__name__}",
            node.lineno)

    def _compare(self, node, envs):
        left = self._eval(node.left, envs)
        out = True
        for op, rhs in zip(node.ops, node.comparators):
            right = self._eval(rhs, envs)
            if isinstance(left, (_Data, _Axis)) or \
                    isinstance(right, (_Data, _Axis)):
                return _Data()
            if isinstance(op, ast.Eq):
                ok = left == right
            elif isinstance(op, ast.NotEq):
                ok = left != right
            elif isinstance(op, ast.Lt):
                ok = left < right
            elif isinstance(op, ast.LtE):
                ok = left <= right
            elif isinstance(op, ast.Gt):
                ok = left > right
            elif isinstance(op, ast.GtE):
                ok = left >= right
            elif isinstance(op, ast.Is):
                ok = left is right
            elif isinstance(op, ast.IsNot):
                ok = left is not right
            else:
                raise ExtractError("unmodelable comparison", node.lineno)
            out = out and ok
            left = right
        return out

    def _free_ints(self, node, envs) -> frozenset:
        """Concrete ints bound to names referenced under ``node`` — the
        provenance that survives abstraction (``dev(i)``'s ``i``)."""
        out = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                for env in reversed(envs):
                    if sub.id in env:
                        v = env[sub.id]
                        if isinstance(v, int) and not isinstance(v, bool):
                            out.add(v)
                        break
        return frozenset(out)

    def _comprehension(self, node, envs):
        gen = node.generators[0]
        it = self._eval(gen.iter, envs)
        if isinstance(it, _Data) or not isinstance(
                it, (list, tuple, range)):
            # abstract iteration: keep the provenance of any concrete
            # ints the element expression closes over
            return _Data(self._free_ints(node, envs))
        out = []
        for item in it:
            child = dict()
            self._assign(gen.target, item, envs + [child])
            keep = True
            for cond in gen.ifs:
                c = self._eval(cond, envs + [child])
                if isinstance(c, _Data):
                    raise ExtractError(
                        "comprehension filter on traced data",
                        node.lineno)
                keep = keep and bool(c)
            if keep:
                out.append(self._eval(node.elt, envs + [child]))
        return out

    def _subscript(self, node, envs):
        obj = self._eval(node.value, envs)
        if isinstance(obj, _Ref):
            slot = self._slot(node.slice, envs)
            self.events.append(Event(
                "read", node.lineno, ref=SlotRef(obj.name, slot)))
            return _Data()
        if isinstance(obj, _RefAt):
            return SlotRef(obj.ref.name, self._slot(node.slice, envs))
        if isinstance(obj, (list, tuple, range)):
            idx = self._eval(node.slice, envs)
            if isinstance(idx, int):
                return obj[idx]
        if isinstance(obj, _Data):
            return _Data(obj.derived)
        raise ExtractError("unmodelable subscript", node.lineno)

    def _attribute(self, node, envs):
        # dotted module names first (jnp.float32, pltpu.X, ...)
        dotted = self.project.resolve_dotted(self.mod, node)
        if dotted is not None and not dotted.startswith(("self.",
                                                         "cls.")):
            return _Data()
        obj = self._eval(node.value, envs)
        if isinstance(obj, _Ref):
            if node.attr == "at":
                return _RefAt(obj)
            if node.attr in ("ndim", "shape", "dtype", "size"):
                return _Data()
            raise ExtractError(
                f"unmodelable ref attribute .{node.attr}", node.lineno)
        if isinstance(obj, Desc):
            if node.attr in ("start", "wait", "wait_send", "wait_recv"):
                return _Method(obj, node.attr)
            raise ExtractError(
                f"unmodelable descriptor attribute .{node.attr}",
                node.lineno)
        if isinstance(obj, _Data):
            return _Data(obj.derived)
        raise ExtractError(f"unmodelable attribute .{node.attr}",
                          node.lineno)

    # -- calls ------------------------------------------------------------

    def _call(self, node: ast.Call, envs):
        dotted = self.project.resolve_dotted(self.mod, node.func)
        if dotted is not None:
            handler = self._DOTTED.get(dotted)
            if handler is not None:
                return handler(self, node, envs)
            if dotted.startswith(("jax.numpy.", "jax.nn.", "numpy.",
                                  "jax.lax.", "jax.random.")):
                # generic traced math: evaluate args for their read
                # events, return opaque data
                self._eval_args(node, envs)
                return _Data()
            # project-module helper called through an alias
            head, _, fname = dotted.rpartition(".")
            target = self.project.functions.get((head, (fname,)))
            if target is not None:
                return self._call_value(_Closure(target.node, [{}]),
                                        node, envs)
            raise ExtractError(f"unmodelable call to {dotted}",
                              node.lineno)
        if isinstance(node.func, ast.Name):
            name = node.func.id
            fn = None
            for env in reversed(envs):
                if name in env:
                    fn = env[name]
                    break
            if fn is None:
                if name in self._BUILTINS:
                    args, _ = self._eval_args(node, envs)
                    return self._builtin(name, args, node.lineno)
                target = self.project.lookup_function(
                    self.mod, self.info.scope, name)
                if target is not None:
                    fn = _Closure(target.node, [{}])
            if fn is None:
                raise ExtractError(f"unmodelable call to {name!r}",
                                  node.lineno)
            return self._call_value(fn, node, envs)
        fnval = self._eval(node.func, envs)
        return self._call_value(fnval, node, envs)

    _BUILTINS = frozenset({"tuple", "list", "range", "len", "min",
                           "max", "int", "abs", "sum", "sorted",
                           "float", "bool"})

    def _builtin(self, name, args, line):
        if any(isinstance(a, _Data) for a in args):
            der = frozenset()
            for a in args:
                if isinstance(a, _Data):
                    der |= a.derived
            return _Data(der)
        try:
            return {"tuple": tuple, "list": list, "range": range,
                    "len": len, "min": min, "max": max, "int": int,
                    "abs": abs, "sum": sum, "sorted": sorted,
                    "float": float, "bool": bool}[name](*args)
        except Exception as e:
            raise ExtractError(f"builtin {name} failed: {e}", line)

    def _eval_args(self, node, envs):
        args = [self._eval(a, envs) for a in node.args]
        kwargs = {k.arg: self._eval(k.value, envs)
                  for k in node.keywords if k.arg}
        return args, kwargs

    def _call_value(self, fn, node, envs):
        args, kwargs = self._eval_args(node, envs)
        if isinstance(fn, _Closure):
            return self._invoke(fn, args, kwargs, node)
        if isinstance(fn, _Method):
            return self._dma_method(fn, node)
        raise ExtractError("unmodelable callable", node.lineno)

    def _invoke(self, clo: _Closure, args, kwargs, node):
        fnode = clo.node
        a = fnode.args
        local: Dict[str, object] = {}
        params = [p.arg for p in a.posonlyargs + a.args]
        for name, val in zip(params, args):
            local[name] = val
        if len(args) > len(params):
            raise ExtractError("too many call args", node.lineno)
        defaults = a.defaults
        if defaults:
            for p, d in zip(params[-len(defaults):], defaults):
                if p not in local:
                    local[p] = self._eval(d, clo.env + [local])
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if p.arg in kwargs:
                local[p.arg] = kwargs[p.arg]
            elif d is not None:
                local[p.arg] = self._eval(d, clo.env + [local])
        for k, v in kwargs.items():
            if k in params:
                local[k] = v
        missing = [p for p in params if p not in local]
        if missing:
            raise ExtractError(
                f"call leaves parameters unbound: {missing}",
                node.lineno)
        r = self._exec_body(fnode.body, clo.env + [local])
        return None if r is _UNSET else r

    def _dma_method(self, m: _Method, node):
        d = m.desc
        if m.op == "start":
            self.events.append(Event("dma", node.lineno, desc=d))
        elif m.op == "wait_send":
            self.events.append(Event("wait", node.lineno,
                                     ref=d.send_sem, count=1))
        elif m.op == "wait_recv":
            self.events.append(Event("wait", node.lineno,
                                     ref=d.recv_sem, count=1))
        elif m.op == "wait":
            self.events.append(Event("wait", node.lineno,
                                     ref=d.send_sem, count=1))
            self.events.append(Event("wait", node.lineno,
                                     ref=d.recv_sem, count=1))
        return None

    def _ring_offset(self, val, line) -> int:
        """device_id value -> signed ring offset (interpreter runs as
        device 0)."""
        cands = set()
        if isinstance(val, int) and not isinstance(val, bool):
            cands = {val}
        elif isinstance(val, _Data):
            cands = set(val.derived)
        elif isinstance(val, tuple):
            for v in val:
                if isinstance(v, int) and not isinstance(v, bool) \
                        and v != 0:
                    cands.add(v)
                elif isinstance(v, _Data):
                    cands |= {x for x in v.derived if x != 0}
        cands = {c % self.n for c in cands if 0 <= c % self.n}
        cands.discard(0)
        if not cands:
            return 0
        if len(cands) > 1:
            raise ExtractError(
                f"ambiguous device_id (candidates {sorted(cands)})",
                line)
        v = cands.pop()
        return v if v <= self.n // 2 else v - self.n

    def _slotref(self, val, line) -> SlotRef:
        if isinstance(val, SlotRef):
            return val
        if isinstance(val, _Ref):
            return SlotRef(val.name, None)
        raise ExtractError("expected a ref or ref.at[slot]", line)

    # dotted-name handlers -------------------------------------------------

    def _h_program_id(self, node, envs):
        return self.t

    def _h_num_programs(self, node, envs):
        return self.n

    def _h_axis_index(self, node, envs):
        return 0

    def _h_axis_size(self, node, envs):
        return self.n

    def _h_rem(self, node, envs):
        a = self._eval(node.args[0], envs)
        b = self._eval(node.args[1], envs)
        if isinstance(a, _Data) or isinstance(b, _Data):
            return _Data()
        # non-negative operands in the modelable fragment: % == rem
        return a % b

    def _h_when(self, node, envs):
        raise ExtractError(
            "pl.when(...) used outside a decorator (unmodelable)",
            node.lineno)

    def _h_barrier(self, node, envs):
        return self._barrier

    def _h_signal(self, node, envs):
        args, kwargs = self._eval_args(node, envs)
        sem = self._slotref(args[0], node.lineno)
        inc = kwargs.get("inc", args[1] if len(args) > 1 else 1)
        if not isinstance(inc, int):
            raise ExtractError("non-static semaphore inc", node.lineno)
        off = self._ring_offset(kwargs.get("device_id", 0), node.lineno)
        self.events.append(Event("signal", node.lineno, ref=sem,
                                 count=inc, off=off))
        return None

    def _h_sem_wait(self, node, envs):
        args, _ = self._eval_args(node, envs)
        sem = self._slotref(args[0], node.lineno)
        count = args[1] if len(args) > 1 else 1
        if not isinstance(count, int):
            raise ExtractError("non-static semaphore count",
                              node.lineno)
        self.events.append(Event("wait", node.lineno, ref=sem,
                                 count=count))
        return None

    def _h_remote_copy(self, node, envs):
        args, kwargs = self._eval_args(node, envs)
        if len(args) < 4:
            raise ExtractError(
                "make_async_remote_copy needs (src, dst, send_sem, "
                "recv_sem)", node.lineno)
        off = self._ring_offset(kwargs.get("device_id", 0), node.lineno)
        return Desc(src=self._slotref(args[0], node.lineno),
                    dst=self._slotref(args[1], node.lineno),
                    send_sem=self._slotref(args[2], node.lineno),
                    recv_sem=self._slotref(args[3], node.lineno),
                    off=off, line=node.lineno)

    def _h_local_copy(self, node, envs):
        # local async copy: same descriptor, no ring hop
        args, _ = self._eval_args(node, envs)
        if len(args) < 3:
            raise ExtractError(
                "make_async_copy needs (src, dst, sem)", node.lineno)
        sem = self._slotref(args[2], node.lineno)
        return Desc(src=self._slotref(args[0], node.lineno),
                    dst=self._slotref(args[1], node.lineno),
                    send_sem=sem, recv_sem=sem, off=0,
                    line=node.lineno)

    _DOTTED = {
        f"{PL}.program_id": _h_program_id,
        f"{PL}.num_programs": _h_num_programs,
        f"{PL}.when": _h_when,
        "jax.lax.axis_index": _h_axis_index,
        "jax.lax.axis_size": _h_axis_size,
        "jax.lax.rem": _h_rem,
        f"{PLTPU}.get_barrier_semaphore": _h_barrier,
        f"{PLTPU}.semaphore_signal": _h_signal,
        f"{PLTPU}.semaphore_wait": _h_sem_wait,
        f"{PLTPU}.make_async_remote_copy": _h_remote_copy,
        f"{PLTPU}.make_async_copy": _h_local_copy,
    }


def _load_of(node):
    new = ast.copy_location(ast.Subscript(
        value=node.value, slice=node.slice, ctx=ast.Load()), node) \
        if isinstance(node, ast.Subscript) else ast.copy_location(
            ast.Name(id=node.id, ctx=ast.Load()), node)
    return new


def extract_schedule(project: Project, mod: ModuleSource,
                     info: FunctionInfo, n: int) -> List[List[Event]]:
    """Per-grid-step event schedules for ring size ``n``: the protocol
    kernels in this repo walk the ring with a grid of exactly ``n``
    steps, which is also the modelable-fragment contract."""
    return [ScheduleExtractor(project, mod, info, n, t).run()
            for t in range(n)]
