"""graftlint project model: modules, imports, functions, jit-reachability.

The rules need three whole-program facts no single-node visitor can
supply:

1. **what a dotted name means** — ``np.asarray`` vs a local ``np``;
   resolved through each module's import aliases so rules match
   canonical names (``numpy.asarray``, ``jax.random.split``) instead of
   spellings;
2. **which functions are traced** ("hot") — jit/pmap/vmap decorated,
   passed into ``lax.scan``/``shard_map``/``pallas_call``/… as a body,
   or (transitively) called from such a body. The serving decode loop
   is covered by the same mechanism: ``jax.jit(decode, ...)`` inside
   ``Engine._build_executables`` marks ``decode`` hot, and the ``row``
   fn it vmaps inherits;
3. **where jit call-sites bind** — ``self._decode = jax.jit(decode,
   donate_argnums=...)`` associates the donating wrapper with the
   attribute name the engine loop later calls.

Resolution is best-effort and *underclaiming by design*: an edge the
model can't see means a missed finding, never a false one. The
``# graftlint: hot -- reason`` marker (core.py) patches the holes the
call graph can't reach.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from apex1_tpu.lint.core import Finding, ModuleSource, parse_module

#: Callables whose function-valued arguments become traced bodies.
TRACE_ENTRIES = frozenset({
    "jax.jit", "jax.pmap", "jax.vmap", "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat", "jax.custom_vjp", "jax.custom_jvp",
    "jax.jvp", "jax.vjp", "jax.linearize", "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.lax.scan", "jax.lax.fori_loop", "jax.lax.while_loop",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan", "jax.lax.custom_root",
    "jax.experimental.pallas.pallas_call",
    "flax.linen.remat", "flax.linen.jit", "flax.linen.scan",
})

#: Host-callback escapes: a function handed to these runs on the HOST,
#: so hotness must NOT propagate through them.
CALLBACK_ENTRIES = frozenset({
    "jax.pure_callback", "jax.experimental.io_callback",
    "jax.debug.callback", "jax.debug.print",
})


@dataclasses.dataclass
class FunctionInfo:
    mod: ModuleSource
    node: ast.AST                       # FunctionDef/AsyncFunctionDef/Lambda
    scope: Tuple[str, ...]              # nesting path incl. own name
    cls: Optional[str]                  # innermost enclosing class
    params: List[str]

    @property
    def name(self) -> str:
        return self.scope[-1]

    @property
    def qualname(self) -> str:
        return ".".join(self.scope)

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 0)

    def def_line_range(self) -> Tuple[int, int]:
        """Lines a hot/cold marker may sit on: first decorator through
        the signature (i.e. up to the first body statement)."""
        node = self.node
        start = getattr(node, "lineno", 0)
        for dec in getattr(node, "decorator_list", []):
            start = min(start, dec.lineno)
        body = getattr(node, "body", None)
        end = body[0].lineno if isinstance(body, list) and body else start
        return start, end


@dataclasses.dataclass
class JitSite:
    """One ``jax.jit(...)`` call: its target (when resolvable), its
    static/donate annotations (when constant), and the local / ``self.``
    names the wrapper is bound to."""

    mod: ModuleSource
    call: ast.Call
    target: Optional[FunctionInfo]
    static_argnums: Optional[Tuple[int, ...]]
    static_argnames: Optional[Tuple[str, ...]]
    donate_argnums: Optional[Tuple[int, ...]]
    bound_names: List[str]              # "step_fn", "self._decode", ...
    in_scope: Tuple[str, ...]           # scope the jit call appears in


def _const_argnums(node: Optional[ast.AST]) -> Optional[Tuple[int, ...]]:
    """Evaluate an argnums expression to a tuple of ints. An ``IfExp``
    with literal arms (the engine's CPU-donation toggle) resolves to the
    UNION — code must be donation-correct on the branch where donation
    is on."""
    if node is None:
        return None
    try:
        val = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        if isinstance(node, ast.IfExp):
            a = _const_argnums(node.body)
            b = _const_argnums(node.orelse)
            if a is not None and b is not None:
                return tuple(sorted(set(a) | set(b)))
        return None
    if isinstance(val, int):
        return (val,)
    if isinstance(val, (tuple, list)) and all(
            isinstance(v, int) for v in val):
        return tuple(val)
    return None


def _const_argnames(node: Optional[ast.AST]) -> Optional[Tuple[str, ...]]:
    if node is None:
        return None
    try:
        val = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None
    if isinstance(val, str):
        return (val,)
    if isinstance(val, (tuple, list)) and all(
            isinstance(v, str) for v in val):
        return tuple(val)
    return None


def _param_names(node: ast.AST) -> List[str]:
    a = getattr(node, "args", None)
    if a is None:
        return []
    names = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
    if a.vararg:
        names.append(a.vararg.arg)
    names += [p.arg for p in a.kwonlyargs]
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def own_body_walk(node: ast.AST):
    """Walk a function's OWN statements: descend everywhere except into
    nested function/class/lambda bodies (those are separate scopes with
    their own hotness)."""
    if isinstance(node, ast.Lambda):
        roots = [node.body]
    else:
        roots = list(getattr(node, "body", []))
    stack = list(roots)
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


class Project:
    """Whole-program index over a set of parsed modules."""

    def __init__(self, modules: Sequence[ModuleSource]):
        self.modules: List[ModuleSource] = list(modules)
        self.by_name: Dict[str, ModuleSource] = {
            m.modname: m for m in self.modules if m.modname}
        # per module: import alias -> dotted target
        self.aliases: Dict[str, Dict[str, str]] = {}
        # (modname, local name) -> (defining modname, function name)
        self.imported_funcs: Dict[Tuple[str, str], Tuple[str, str]] = {}
        # (modname, scope tuple) -> FunctionInfo
        self.functions: Dict[Tuple[str, Tuple[str, ...]], FunctionInfo] = {}
        self.jit_sites: List[JitSite] = []
        self.jit_site_by_call: Dict[int, JitSite] = {}  # id(Call) -> site
        self.hot: Set[int] = set()        # id(FunctionInfo.node)
        self._cold: Set[int] = set()
        self._edges: Dict[int, List[FunctionInfo]] = {}
        self._info_by_node: Dict[int, FunctionInfo] = {}

        for mod in self.modules:
            if mod.tree is not None:
                self._index_imports(mod)
        for mod in self.modules:
            if mod.tree is not None:
                self._index_functions(mod)
        for mod in self.modules:
            if mod.tree is not None:
                self._index_calls(mod)
        self._apply_markers()
        self._propagate_hot()

    # ---- imports --------------------------------------------------------

    def _index_imports(self, mod: ModuleSource) -> None:
        amap: Dict[str, str] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    # `import a.b as c` binds c -> a.b; plain
                    # `import a.b` binds only the root name a
                    if al.asname:
                        amap[al.asname] = al.name
                    else:
                        root = al.name.split(".")[0]
                        amap[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.level:
                    base = self._resolve_relative(mod, node)
                    if base is None:
                        continue
                else:
                    base = node.module
                for al in node.names:
                    if al.name == "*":
                        continue
                    local = al.asname or al.name
                    amap[local] = f"{base}.{al.name}"
                    if mod.modname:
                        self.imported_funcs[(mod.modname, local)] = (
                            base, al.name)
        self.aliases[mod.modname or mod.path] = amap

    @staticmethod
    def _resolve_relative(mod: ModuleSource,
                          node: ast.ImportFrom) -> Optional[str]:
        if not mod.modname:
            return None
        parts = mod.modname.split(".")
        # level 1 = current package. For a plain module that means
        # dropping its own name; a package __init__ (modname already
        # IS the package) drops one component fewer.
        drop = node.level
        if mod.path.endswith("__init__.py"):
            drop -= 1
        if drop > len(parts) or drop < 0:
            return None
        base_parts = parts[:len(parts) - drop] if drop else parts
        if node.module:
            base_parts = base_parts + [node.module]
        return ".".join(base_parts) if base_parts else None

    def alias_map(self, mod: ModuleSource) -> Dict[str, str]:
        return self.aliases.get(mod.modname or mod.path, {})

    def resolve_dotted(self, mod: ModuleSource,
                       node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, with the
        base translated through the module's import aliases.
        ``self.x.y`` resolves to ``"self.x.y"`` (callers special-case
        it); a chain rooted at an unimported local returns None."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        parts.append(cur.id)
        parts.reverse()
        base = parts[0]
        amap = self.alias_map(mod)
        if base in ("self", "cls"):
            return ".".join(parts)
        if base in amap:
            return ".".join([amap[base]] + parts[1:])
        if len(parts) == 1:
            return None
        return None

    # ---- functions ------------------------------------------------------

    def _index_functions(self, mod: ModuleSource) -> None:
        def visit(node, scope: Tuple[str, ...], cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    sub = scope + (child.name,)
                    self._register(mod, child, sub, cls)
                    visit(child, sub, cls)
                elif isinstance(child, ast.ClassDef):
                    visit(child, scope + (child.name,), child.name)
                elif isinstance(child, ast.Lambda):
                    sub = scope + (f"<lambda:{child.lineno}>",)
                    self._register(mod, child, sub, cls)
                    visit(child, sub, cls)
                else:
                    visit(child, scope, cls)

        visit(mod.tree, (), None)

    def _register(self, mod, node, scope, cls) -> FunctionInfo:
        info = FunctionInfo(mod=mod, node=node, scope=scope, cls=cls,
                            params=_param_names(node))
        self.functions[(mod.modname or mod.path, scope)] = info
        self._info_by_node[id(node)] = info
        return info

    def info_for(self, node: ast.AST) -> Optional[FunctionInfo]:
        return self._info_by_node.get(id(node))

    def lookup_function(self, mod: ModuleSource, scope: Tuple[str, ...],
                        name: str) -> Optional[FunctionInfo]:
        """Lexical lookup of a bare name from inside ``scope``."""
        key = mod.modname or mod.path
        for k in range(len(scope), -1, -1):
            info = self.functions.get((key, scope[:k] + (name,)))
            if info is not None:
                return info
        imp = self.imported_funcs.get((mod.modname, name))
        if imp is not None:
            return self.functions.get((imp[0], (imp[1],)))
        return None

    def _resolve_func_arg(self, mod: ModuleSource, scope: Tuple[str, ...],
                          arg: ast.AST) -> Optional[FunctionInfo]:
        """A function-valued argument: bare name, lambda, self-method,
        or another trace-entry call wrapping one (``jax.jit(
        jax.shard_map(step, ...), ...)`` reaches ``step``)."""
        if isinstance(arg, ast.Name):
            return self.lookup_function(mod, scope, arg.id)
        if isinstance(arg, ast.Lambda):
            return self.info_for(arg)
        if isinstance(arg, ast.Attribute):
            dotted = self.resolve_dotted(mod, arg)
            if dotted and dotted.startswith(("self.", "cls.")):
                parts = dotted.split(".")
                if len(parts) == 2:
                    info = self._method_lookup(mod, scope, parts[1])
                    if info is not None:
                        return info
            return None
        if isinstance(arg, ast.Call):
            callee = self.resolve_dotted(mod, arg.func)
            if callee in TRACE_ENTRIES or (
                    isinstance(arg.func, ast.Name)
                    and arg.func.id in ("partial",)):
                for sub in list(arg.args):
                    info = self._resolve_func_arg(mod, scope, sub)
                    if info is not None:
                        return info
        return None

    def _method_lookup(self, mod: ModuleSource, scope: Tuple[str, ...],
                       name: str) -> Optional[FunctionInfo]:
        key = mod.modname or mod.path
        # innermost enclosing class on the scope path
        for k in range(len(scope), 0, -1):
            info = self.functions.get((key, scope[:k - 1] + (name,)))
            if info is not None and info.cls is not None:
                return info
        return None

    # ---- calls: hot roots, edges, jit sites -----------------------------

    def _index_calls(self, mod: ModuleSource) -> None:
        for (mkey, scope), info in list(self.functions.items()):
            if mkey != (mod.modname or mod.path):
                continue
            edges: List[FunctionInfo] = []
            for n in own_body_walk(info.node):
                if isinstance(n, ast.Call):
                    self._one_call(mod, scope, n, edges)
            self._edges[id(info.node)] = edges
            # decorators evaluate in the ENCLOSING scope but describe
            # this function
            for dec in getattr(info.node, "decorator_list", []):
                self._decorator(mod, info, dec)
        # module top level: calls outside any def. They run at import
        # time (host) so the edge list is discarded — but _one_call
        # still registers jit sites and hot roots (`step = jax.jit(f,
        # ...)` at module scope).
        edges = []
        for n in own_body_walk_module(mod.tree):
            if isinstance(n, ast.Call):
                self._one_call(mod, (), n, edges)

    def _one_call(self, mod: ModuleSource, scope: Tuple[str, ...],
                  call: ast.Call, edges: List[FunctionInfo]) -> None:
        callee = self.resolve_dotted(mod, call.func)
        if callee in CALLBACK_ENTRIES:
            return  # args run host-side; no edge, no hotness
        if callee in TRACE_ENTRIES:
            for arg in call.args:
                target = self._resolve_func_arg(mod, scope, arg)
                if target is not None:
                    self.hot.add(id(target.node))
            if callee == "jax.jit":
                self._record_jit_site(mod, scope, call)
            return
        if callee == "functools.partial" or (
                isinstance(call.func, ast.Name)
                and call.func.id == "partial"):
            inner = call.args[0] if call.args else None
            if inner is not None and self.resolve_dotted(
                    mod, inner) in TRACE_ENTRIES:
                for arg in call.args[1:]:
                    target = self._resolve_func_arg(mod, scope, arg)
                    if target is not None:
                        self.hot.add(id(target.node))
                if self.resolve_dotted(mod, inner) == "jax.jit":
                    self._record_jit_site(mod, scope, call,
                                          partial_form=True)
            return
        # plain call: call-graph edge for hot propagation
        if isinstance(call.func, ast.Name):
            target = self.lookup_function(mod, scope, call.func.id)
            if target is not None:
                edges.append(target)
        elif isinstance(call.func, ast.Attribute):
            dotted = self.resolve_dotted(mod, call.func)
            if dotted is None:
                return
            if dotted.startswith(("self.", "cls.")):
                parts = dotted.split(".")
                if len(parts) == 2:
                    target = self._method_lookup(mod, scope, parts[1])
                    if target is not None:
                        edges.append(target)
                return
            # alias.func where alias is a project module
            head, _, fname = dotted.rpartition(".")
            if head in self.by_name:
                target = self.functions.get((head, (fname,)))
                if target is not None:
                    edges.append(target)

    def _decorator(self, mod: ModuleSource, info: FunctionInfo,
                   dec: ast.AST) -> None:
        dotted = self.resolve_dotted(mod, dec) if not isinstance(
            dec, ast.Call) else self.resolve_dotted(mod, dec.func)
        if dotted in TRACE_ENTRIES:
            self.hot.add(id(info.node))
            if dotted == "jax.jit" and isinstance(dec, ast.Call):
                self._record_jit_site(mod, info.scope[:-1], dec,
                                      decorator_of=info)
            return
        if isinstance(dec, ast.Call) and (
                self.resolve_dotted(mod, dec.func) == "functools.partial"
                or (isinstance(dec.func, ast.Name)
                    and dec.func.id == "partial")):
            inner = dec.args[0] if dec.args else None
            if inner is not None and self.resolve_dotted(
                    mod, inner) in TRACE_ENTRIES:
                self.hot.add(id(info.node))
                if self.resolve_dotted(mod, inner) == "jax.jit":
                    self._record_jit_site(mod, info.scope[:-1], dec,
                                          partial_form=True,
                                          decorator_of=info)

    def _record_jit_site(self, mod: ModuleSource, scope: Tuple[str, ...],
                         call: ast.Call, partial_form: bool = False,
                         decorator_of: Optional[FunctionInfo] = None
                         ) -> None:
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        target = decorator_of
        if target is None:
            pos = call.args[1:] if partial_form else call.args
            if pos:
                target = self._resolve_func_arg(mod, scope, pos[0])
        site = JitSite(
            mod=mod, call=call, target=target,
            static_argnums=_const_argnums(kw.get("static_argnums")),
            static_argnames=_const_argnames(kw.get("static_argnames")),
            donate_argnums=_const_argnums(kw.get("donate_argnums")),
            bound_names=[], in_scope=scope)
        self.jit_sites.append(site)
        self.jit_site_by_call[id(call)] = site

    # ---- markers + propagation ------------------------------------------

    def _apply_markers(self) -> None:
        """Bind each hot/cold marker to the INNERMOST function whose
        decorator-to-first-statement span contains its target line —
        when a nested def is an enclosing function's first statement,
        both spans contain the def line and only the nested function is
        the marker's subject. Detached markers (binding to nothing)
        become APX000 findings: a marker that silently stops binding
        would silently drop gate coverage."""
        per_marker: Dict[Tuple[int, int, str], FunctionInfo] = {}
        for info in self.functions.values():
            lo, hi = info.def_line_range()
            for kind, table in (("cold", info.mod.cold_lines),
                                ("hot", info.mod.hot_lines)):
                for target in table:
                    if not lo <= target <= hi:
                        continue
                    key = (id(info.mod), target, kind)
                    prev = per_marker.get(key)
                    if prev is None or info.def_line_range()[0] >= \
                            prev.def_line_range()[0]:
                        per_marker[key] = info
        bound: Set[Tuple[int, int, str]] = set()
        for (mod_id, target, kind), info in per_marker.items():
            bound.add((mod_id, target, kind))
            if kind == "cold":
                self._cold.add(id(info.node))
            else:
                self.hot.add(id(info.node))
        for mod in self.modules:
            for kind, table in (("hot", mod.hot_lines),
                                ("cold", mod.cold_lines)):
                for target, comment_line in table.items():
                    if (id(mod), target, kind) not in bound:
                        mod.errors.append(Finding(
                            "APX000", mod.path, comment_line, 0,
                            f"detached '{kind}' marker: no function "
                            f"definition spans line {target} — the "
                            f"marker binds to nothing (gate coverage "
                            f"would silently change)"))

    def _propagate_hot(self) -> None:
        self.hot -= self._cold
        work = list(self.hot)
        while work:
            nid = work.pop()
            for callee in self._edges.get(nid, []):
                cid = id(callee.node)
                if cid in self._cold or cid in self.hot:
                    continue
                self.hot.add(cid)
                work.append(cid)

    def is_hot(self, node: ast.AST) -> bool:
        return id(node) in self.hot

    def hot_functions(self) -> List[FunctionInfo]:
        return [i for i in self.functions.values()
                if id(i.node) in self.hot]


def own_body_walk_module(tree: ast.Module):
    """Module top-level statements, not descending into defs/classes."""
    stack = list(tree.body)
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


def build_project(named_sources: Dict[str, Tuple[str, str]]) -> Project:
    """``{path: (modname, text)}`` -> Project."""
    mods = [parse_module(path, text, modname)
            for path, (modname, text) in named_sources.items()]
    return Project(mods)
