"""graftlint — AST static analysis for this repo's JAX hazard classes.

The framework's invariants (no retraces after warmup, no host syncs on
the decode chain, use-once PRNG keys, donation discipline, one jax
spelling through the compat bridge) are exactly the properties JAX
never enforces statically — they regress silently and cost a TPU
session to rediscover. graftlint walks ``apex1_tpu/``, ``tools/`` and
``examples/``, resolves imports well enough to know what is
jit-reachable, and exits nonzero on any unsuppressed finding: a gate,
not a style checker.

Entry points::

    from apex1_tpu.lint import lint_paths, lint_sources
    res = lint_paths(["apex1_tpu", "tools", "examples"], root=REPO)
    res.unsuppressed()        # -> [Finding]  (gate on this)
    res.as_dict()             # -> the --json payload

``kernels=True`` additionally runs the APX2xx kernel/collective
analyzer (``lint.kernels``: the Pallas semaphore/DMA protocol
model-checker, mesh/axis consistency, and the shared-VMEM budget
pass) — the surface tier-1 can never execute.

CLI: ``python tools/lint.py [--json] [--changed] [--kernels]
[paths...]``. Rule catalogue + suppression grammar: ``docs/lint.md``.

The lint machinery is stdlib ``ast`` only — no new deps, no jax, no
device touch; the whole repo lints in ~1s. (``tools/lint.py`` loads
this subpackage through a stub parent so even the CLI never pays the
package ``__init__``'s jax import.)
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

from apex1_tpu.lint.core import (Finding, ModuleSource, RULE_SLUGS,
                                 apply_suppressions, canonical_rule,
                                 unused_suppressions)
from apex1_tpu.lint.project import Project, build_project  # noqa: F401
from apex1_tpu.lint.rules import RULES

__all__ = ["Finding", "LintResult", "RULES", "RULE_SLUGS",
           "canonical_rule", "collect_files", "lint_files",
           "lint_paths", "lint_sources", "module_name_for"]

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist",
              ".claude"}


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    n_files: int
    unused: List[Tuple[str, int, str]]   # (path, line, rules) — info only
    kernels: bool = False                # APX2xx family included?

    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    @property
    def ok(self) -> bool:
        return not self.unsuppressed()

    def as_dict(self) -> dict:
        per_rule: Dict[str, int] = {}
        for f in self.unsuppressed():
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        rules = list(RULES)
        if self.kernels:
            from apex1_tpu.lint.kernels import KERNEL_RULES
            rules = rules + list(KERNEL_RULES)
        return {
            "tool": "graftlint",
            "rules": {r.code: {"slug": r.slug, "summary": r.summary}
                      for r in rules},
            "n_files": self.n_files,
            "ok": self.ok,
            "counts": {"unsuppressed": len(self.unsuppressed()),
                       "suppressed": len(self.suppressed()),
                       "per_rule": per_rule},
            "findings": [f.as_dict() for f in self.findings],
            "unused_suppressions": [
                {"path": p, "line": ln, "rules": r}
                for p, ln, r in self.unused],
        }


def module_name_for(path: str, root: Optional[str] = None) -> str:
    """Dotted module name for a file: ``apex1_tpu/ops/rope.py`` ->
    ``apex1_tpu.ops.rope``; unknown layouts get a best-effort name
    (only the ``apex1_tpu``-package names carry semantics — the compat
    rule's bridge exemptions and import-runs-__init__ logic)."""
    p = os.path.abspath(path)
    if root:
        try:
            rel = os.path.relpath(p, os.path.abspath(root))
        except ValueError:
            rel = os.path.basename(p)
    else:
        # find the package root by walking up from an apex1_tpu segment
        parts = p.split(os.sep)
        rel = os.sep.join(parts[parts.index("apex1_tpu"):]) \
            if "apex1_tpu" in parts else os.path.basename(p)
    rel = rel[:-3] if rel.endswith(".py") else rel
    name = rel.replace(os.sep, ".")
    if name.endswith(".__init__"):
        name = name[:-len(".__init__")]
    elif name == "__init__":
        name = ""
    return name


def collect_files(paths: Sequence[str],
                  root: Optional[str] = None) -> List[str]:
    files: List[str] = []
    for p in paths:
        full = os.path.join(root, p) if root and not os.path.isabs(p) \
            else p
        if os.path.isfile(full):
            if full.endswith(".py"):
                files.append(full)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    files.append(os.path.join(dirpath, fn))
    return files


def _display_path(path: str, root: Optional[str]) -> str:
    if not root:
        return path
    try:
        rel = os.path.relpath(os.path.abspath(path),
                              os.path.abspath(root))
    except ValueError:
        return path
    return path if rel.startswith("..") else rel


def lint_files(files: Sequence[str], root: Optional[str] = None,
               kernels: bool = False) -> LintResult:
    named: Dict[str, Tuple[str, str]] = {}
    unreadable: List[Finding] = []
    for f in files:
        disp = _display_path(f, root)
        try:
            with open(f, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError as e:
            unreadable.append(Finding("APX001", disp, 1, 0,
                                      f"cannot read file: {e}"))
            continue
        named[disp] = (module_name_for(f, root), text)
    res = lint_sources(named, kernels=kernels)
    res.findings.extend(unreadable)
    return res


def lint_paths(paths: Sequence[str], root: Optional[str] = None,
               kernels: bool = False) -> LintResult:
    return lint_files(collect_files(paths, root), root,
                      kernels=kernels)


def lint_sources(named_sources: Dict[str, Tuple[str, str]],
                 kernels: bool = False) -> LintResult:
    """``{path: (modname, text)}`` -> LintResult. The in-memory entry
    point the tests drive fixtures through. ``kernels=True`` adds the
    APX2xx kernel/collective analyzer to the run."""
    project = build_project(named_sources)
    by_path: Dict[str, ModuleSource] = {m.path: m
                                        for m in project.modules}
    findings: List[Finding] = []
    for mod in project.modules:
        findings.extend(mod.errors)
    for rule in RULES:
        findings.extend(rule.check(project))
    if kernels:
        from apex1_tpu.lint.kernels import check_kernels
        findings.extend(check_kernels(project))
    out: List[Finding] = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None:
            apply_suppressions(mod, [f])
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    unused = []
    for mod in project.modules:
        for s in unused_suppressions(mod):
            unused.append((mod.path, s.line, ",".join(s.rules)))
    return LintResult(findings=out, n_files=len(project.modules),
                      unused=unused, kernels=kernels)
