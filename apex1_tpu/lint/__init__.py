"""graftlint — AST static analysis for this repo's JAX hazard classes.

The framework's invariants (no retraces after warmup, no host syncs on
the decode chain, use-once PRNG keys, donation discipline, one jax
spelling through the compat bridge) are exactly the properties JAX
never enforces statically — they regress silently and cost a TPU
session to rediscover. graftlint walks ``apex1_tpu/``, ``tools/`` and
``examples/``, resolves imports well enough to know what is
jit-reachable, and exits nonzero on any unsuppressed finding: a gate,
not a style checker.

Entry points::

    from apex1_tpu.lint import lint_paths, lint_sources
    res = lint_paths(["apex1_tpu", "tools", "examples"], root=REPO)
    res.unsuppressed()        # -> [Finding]  (gate on this)
    res.as_dict()             # -> the --json payload

``kernels=True`` additionally runs the APX2xx kernel/collective
analyzer (``lint.kernels``: the Pallas semaphore/DMA protocol
model-checker, mesh/axis consistency, and the shared-VMEM budget
pass) — the surface tier-1 can never execute. ``protocols=True``
additionally runs the APX3xx serving control-plane model checker
(``lint.protocols``: bounded exhaustive exploration of the scheduler/
replica/frontend/disagg/autopilot state machines, parameterized by
guards extracted from the real source).

CLI: ``python tools/lint.py [--json] [--changed] [--kernels]
[--protocols] [paths...]``. Rule catalogue + suppression grammar:
``docs/lint.md``.

The lint machinery is stdlib ``ast`` only — no new deps, no jax, no
device touch; the whole repo lints in ~1s. (``tools/lint.py`` loads
this subpackage through a stub parent so even the CLI never pays the
package ``__init__``'s jax import.) When a ``cache`` path is given —
the CLI does this by default — two memo tiers keep the gate cheap as
the file count grows: file-level parses keyed by (mtime_ns, size), and
a whole-run result memo keyed by the full signature vector + flags, so
the repo-wide no-change rerun costs one ``stat`` per file (~1s
end-to-end past 160 files instead of re-walking every AST).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Dict, List, Optional, Sequence, Tuple

from apex1_tpu.lint.core import (Finding, ModuleSource, RULE_SLUGS,
                                 apply_suppressions, canonical_rule,
                                 parse_module, unused_suppressions)
from apex1_tpu.lint.project import Project, build_project  # noqa: F401
from apex1_tpu.lint.rules import RULES

__all__ = ["Finding", "LintResult", "RULES", "RULE_SLUGS",
           "canonical_rule", "collect_files", "lint_files",
           "lint_paths", "lint_sources", "module_name_for"]

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist",
              ".claude"}

#: bump when ModuleSource/Suppression shapes change — stale caches are
#: discarded wholesale, never migrated.
_CACHE_VERSION = 1


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    n_files: int
    unused: List[Tuple[str, int, str]]   # (path, line, rules) — info only
    kernels: bool = False                # APX2xx family included?
    protocols: bool = False              # APX3xx family included?

    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    @property
    def ok(self) -> bool:
        return not self.unsuppressed()

    def as_dict(self) -> dict:
        per_rule: Dict[str, int] = {}
        for f in self.unsuppressed():
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        rules = list(RULES)
        if self.kernels:
            from apex1_tpu.lint.kernels import KERNEL_RULES
            rules = rules + list(KERNEL_RULES)
        if self.protocols:
            from apex1_tpu.lint.protocols import PROTOCOL_RULES
            rules = rules + list(PROTOCOL_RULES)
        return {
            "tool": "graftlint",
            "rules": {r.code: {"slug": r.slug, "summary": r.summary}
                      for r in rules},
            "n_files": self.n_files,
            "ok": self.ok,
            "counts": {"unsuppressed": len(self.unsuppressed()),
                       "suppressed": len(self.suppressed()),
                       "per_rule": per_rule},
            "findings": [f.as_dict() for f in self.findings],
            "unused_suppressions": [
                {"path": p, "line": ln, "rules": r}
                for p, ln, r in self.unused],
        }


def module_name_for(path: str, root: Optional[str] = None) -> str:
    """Dotted module name for a file: ``apex1_tpu/ops/rope.py`` ->
    ``apex1_tpu.ops.rope``; unknown layouts get a best-effort name
    (only the ``apex1_tpu``-package names carry semantics — the compat
    rule's bridge exemptions and import-runs-__init__ logic)."""
    p = os.path.abspath(path)
    if root:
        try:
            rel = os.path.relpath(p, os.path.abspath(root))
        except ValueError:
            rel = os.path.basename(p)
    else:
        # find the package root by walking up from an apex1_tpu segment
        parts = p.split(os.sep)
        rel = os.sep.join(parts[parts.index("apex1_tpu"):]) \
            if "apex1_tpu" in parts else os.path.basename(p)
    rel = rel[:-3] if rel.endswith(".py") else rel
    name = rel.replace(os.sep, ".")
    if name.endswith(".__init__"):
        name = name[:-len(".__init__")]
    elif name == "__init__":
        name = ""
    return name


def collect_files(paths: Sequence[str],
                  root: Optional[str] = None) -> List[str]:
    files: List[str] = []
    for p in paths:
        full = os.path.join(root, p) if root and not os.path.isabs(p) \
            else p
        if os.path.isfile(full):
            if full.endswith(".py"):
                files.append(full)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    files.append(os.path.join(dirpath, fn))
    return files


def _display_path(path: str, root: Optional[str]) -> str:
    if not root:
        return path
    try:
        rel = os.path.relpath(os.path.abspath(path),
                              os.path.abspath(root))
    except ValueError:
        return path
    return path if rel.startswith("..") else rel


# ---------------------------------------------------------------------------
# on-disk cache, two tiers, both keyed by (mtime_ns, size):
#
#   runs     {(kernels, protocols, root): (sig_vector, pickled LintResult)}
#            — whole-run memo. When NO file in the target set changed,
#            the banked result is returned without unpickling a single
#            AST: the repo-wide no-change run costs one stat() per file.
#   entries  {abspath: ((mtime_ns, size), ModuleSource)} — per-file
#            parse memo for incremental runs, stored as a nested pickle
#            blob so the fast path above never pays its deserialize.
#
# Wrong, stale, or corrupt caches are silently IGNORED (fail-open to a
# fresh parse); writes are atomic and best-effort. The known limit of
# the key: editing a file within one mtime granule while preserving its
# size defeats both tiers — same contract as ccache/mypy.
# ---------------------------------------------------------------------------

_CACHE_ERRS = (OSError, pickle.PickleError, EOFError, AttributeError,
               ImportError, IndexError, TypeError)


def _load_cache(path: Optional[str]) -> Tuple[Dict, Optional[bytes]]:
    """-> (runs, entries_blob). The blob stays opaque bytes here —
    ``_entries_from_blob`` deserializes it only on a run-memo miss."""
    if not path:
        return {}, None
    try:
        with open(path, "rb") as fh:
            data = pickle.load(fh)
        if (isinstance(data, dict)
                and data.get("version") == _CACHE_VERSION
                and isinstance(data.get("runs"), dict)
                and isinstance(data.get("entries_blob"),
                               (bytes, type(None)))):
            return data["runs"], data["entries_blob"]
    except _CACHE_ERRS:
        pass
    return {}, None


def _entries_from_blob(blob: Optional[bytes]) -> Dict:
    if not blob:
        return {}
    try:
        entries = pickle.loads(blob)
        if isinstance(entries, dict):
            return entries
    except _CACHE_ERRS:
        pass
    return {}


def _save_cache(path: Optional[str], runs: Dict, entries: Dict) -> None:
    if not path:
        return
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        blob = pickle.dumps(entries, protocol=pickle.HIGHEST_PROTOCOL)
        with open(tmp, "wb") as fh:
            pickle.dump({"version": _CACHE_VERSION, "runs": runs,
                         "entries_blob": blob},
                        fh, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    except _CACHE_ERRS:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _reset_run_state(mod: ModuleSource) -> None:
    """Suppression `used` bits and error-finding suppression flags are
    per-RUN state mutated by apply_suppressions — a cache-hit module
    must start the run pristine."""
    for sup in mod.suppressions:
        sup.used = False
    for f in mod.errors:
        f.suppressed = False
        f.reason = None


def lint_files(files: Sequence[str], root: Optional[str] = None,
               kernels: bool = False, protocols: bool = False,
               cache: Optional[str] = None) -> LintResult:
    runs, blob = _load_cache(cache)
    run_key = (bool(kernels), bool(protocols),
               os.path.abspath(root) if root else "")

    # tier 1: whole-run memo — one stat() per file, no AST unpickle
    sigs: List[Tuple[str, Tuple[int, int]]] = []
    for f in files:
        try:
            st = os.stat(f)
        except OSError:
            sigs = []
            break
        sigs.append((os.path.abspath(f),
                     (int(st.st_mtime_ns), int(st.st_size))))
    sig_vector = tuple(sigs)
    if cache and sigs:
        hit = runs.get(run_key)
        if hit is not None and hit[0] == sig_vector:
            try:
                res = pickle.loads(hit[1])
                if isinstance(res, LintResult):
                    return res
            except _CACHE_ERRS:
                pass

    # tier 2: per-file parse memo
    cached = _entries_from_blob(blob)
    entries: Dict = {}
    mods: List[ModuleSource] = []
    unreadable: List[Finding] = []
    for f in files:
        disp = _display_path(f, root)
        key = os.path.abspath(f)
        try:
            st = os.stat(f)
        except OSError as e:
            unreadable.append(Finding("APX001", disp, 1, 0,
                                      f"cannot read file: {e}"))
            continue
        sig = (int(st.st_mtime_ns), int(st.st_size))
        hit = cached.get(key)
        if hit is not None and hit[0] == sig and hit[1].path == disp:
            mod = hit[1]
            _reset_run_state(mod)
            mods.append(mod)
            entries[key] = hit
            continue
        try:
            with open(f, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError as e:
            unreadable.append(Finding("APX001", disp, 1, 0,
                                      f"cannot read file: {e}"))
            continue
        mod = parse_module(disp, text, module_name_for(f, root))
        mods.append(mod)
        entries[key] = (sig, mod)
    res = _lint_modules(mods, kernels=kernels, protocols=protocols)
    res.findings.extend(unreadable)
    if cache:
        if sigs and not unreadable:
            runs[run_key] = (
                sig_vector,
                pickle.dumps(res, protocol=pickle.HIGHEST_PROTOCOL))
        _save_cache(cache, runs, entries)
    return res


def lint_paths(paths: Sequence[str], root: Optional[str] = None,
               kernels: bool = False, protocols: bool = False,
               cache: Optional[str] = None) -> LintResult:
    return lint_files(collect_files(paths, root), root,
                      kernels=kernels, protocols=protocols, cache=cache)


def lint_sources(named_sources: Dict[str, Tuple[str, str]],
                 kernels: bool = False,
                 protocols: bool = False) -> LintResult:
    """``{path: (modname, text)}`` -> LintResult. The in-memory entry
    point the tests drive fixtures through. ``kernels=True`` adds the
    APX2xx kernel/collective analyzer, ``protocols=True`` the APX3xx
    serving-protocol model checker."""
    mods = [parse_module(path, text, modname)
            for path, (modname, text) in named_sources.items()]
    return _lint_modules(mods, kernels=kernels, protocols=protocols)


def _lint_modules(mods: Sequence[ModuleSource], kernels: bool = False,
                  protocols: bool = False) -> LintResult:
    project = Project(list(mods))
    by_path: Dict[str, ModuleSource] = {m.path: m
                                        for m in project.modules}
    findings: List[Finding] = []
    for mod in project.modules:
        findings.extend(mod.errors)
    for rule in RULES:
        findings.extend(rule.check(project))
    if kernels:
        from apex1_tpu.lint.kernels import check_kernels
        findings.extend(check_kernels(project))
    if protocols:
        from apex1_tpu.lint.protocols import check_protocols
        findings.extend(check_protocols(project))
    out: List[Finding] = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None:
            apply_suppressions(mod, [f])
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    unused = []
    for mod in project.modules:
        for s in unused_suppressions(mod):
            unused.append((mod.path, s.line, ",".join(s.rules)))
    return LintResult(findings=out, n_files=len(project.modules),
                      unused=unused, kernels=kernels,
                      protocols=protocols)
