"""Bounded state-machine models of the serving control plane.

Each model is a tiny, explicit abstraction of one protocol surface —
the QoS scheduler's shed ladder, the `ReplicaSupervisor` lifecycle, the
`ServingFrontend` admission/hedge/failover ladder, the disagg handoff +
re-route ladder, and the autopilot's actuators — parameterized by FACTS
extracted from the real source AST (`extract.py`). A fact is a named
guard the shipped code carries ("restart honors pending cancels",
"feasibility before displacement", ...). Shipped code extracts to
all-true facts and every model explores clean; a pre-fix fixture (or a
regression) extracts a fact to False and the exhaustive exploration
finds the race and names the interleaving.

The bounded configurations are deliberately small (<=3 replicas, <=4
requests, <=2 faults): each model's state space sits in the
hundreds-to-thousands of states, so `tools/lint.py --protocols`
explores EVERY interleaving in well under a second. What that buys is
exactly what review rounds kept doing by hand — and what it does NOT
buy (timing, real thread schedules, hardware windows) is documented in
docs/lint.md.

Violation codes (registered in lint/core.py RULE_SLUGS):

    APX302 double-decode      one rid live twice on one engine, or two
                              terminal results published for one rid
    APX303 qos-inversion      shed victim not strictly weaker than the
                              incoming class
    APX304 cancel-resurrect   an acknowledged cancel later finishes done
    APX305 stranded-result    request or late result uncollectable at
                              quiescence
    APX306 capacity-leak      displacement/hedge/shift_pool destroys or
                              double-spends capacity
    APX307 ladder             a ladder rung unreachable, unexitable, or
                              unbounded; a mandatory gate missing
"""

from __future__ import annotations

import functools
from typing import Dict, FrozenSet, List, NamedTuple, Set, Tuple

from apex1_tpu.lint.protocols.explore import (Violation, explore,
                                              render_trace)

__all__ = ["run_protocol", "ProtoFinding", "FAMILY_FACTS"]


class ProtoFinding(NamedTuple):
    code: str
    key: str
    anchor: str            # fact name, or "" for the family decl line
    message: str           # invariant + counterexample trace


#: fact names each family's extractor produces (True = shipped guard
#: present). Used by extract.py to default unknown facts and by the
#: tests to enumerate the flip surface.
FAMILY_FACTS: Dict[str, Tuple[str, ...]] = {
    "scheduler": ("shed_strictly_weaker",),
    "replica": ("restart_honors_pending_cancels",
                "drain_honors_pending_cancels",
                "generation_fenced",
                "restart_quarantines_poison"),
    "frontend": ("feasibility_before_displacement",
                 "displace_skips_already_shed",
                 "route_waits_for_pending_legs",
                 "hedge_requires_no_first_token",
                 "hedge_excludes_routed",
                 "failover_skips_live_hedge"),
    "disagg": ("reroute_bounded", "pending_checks_live",
               "cancel_purges_window", "verify_before_install"),
    "autopilot": ("evidence_freeze", "donor_keeps_one"),
}


# ---------------------------------------------------------------------------
# scheduler: the shed ladder (PR 7 round 1 — equal-class shed)
# ---------------------------------------------------------------------------

_SCHED_REQS = (("g1", 0), ("b1", 1), ("s1", 2), ("s2", 2))
_SCHED_RANK = dict(_SCHED_REQS)
_SCHED_CLS = {0: "guaranteed", 1: "best_effort", 2: "sheddable"}
_SCHED_CAP = 2


class _SchedState(NamedTuple):
    queue: Tuple[str, ...]       # arrival order
    subd: FrozenSet[str]


class SchedulerModel:
    name = "scheduler"

    def __init__(self, facts: Dict[str, bool], config: str = "shed"):
        self.config = config
        self.strict = facts["shed_strictly_weaker"]

    def initial(self):
        return _SchedState((), frozenset())

    def actions(self, s: _SchedState):
        acts = []
        for rid, rank in _SCHED_REQS:
            if rid in s.subd:
                continue
            subd = s.subd | {rid}
            if len(s.queue) < _SCHED_CAP:
                acts.append((f"submit {rid}",
                             s._replace(queue=s.queue + (rid,), subd=subd),
                             ()))
                continue
            if self.strict:
                eligible = [q for q in s.queue if _SCHED_RANK[q] > rank]
            else:                # pre-fix: skipped only strictly-stronger
                eligible = [q for q in s.queue if _SCHED_RANK[q] >= rank]
            if not eligible:
                acts.append((f"reject {rid} (queue full, no weaker victim)",
                             s._replace(subd=subd), ()))
                continue
            # weakest class first, youngest (latest arrival) within it
            victim = max(eligible,
                         key=lambda q: (_SCHED_RANK[q], s.queue.index(q)))
            viols: Tuple[Violation, ...] = ()
            if _SCHED_RANK[victim] <= rank:
                viols = (Violation(
                    "APX303", "equal-class-shed",
                    f"shed victim '{victim}' "
                    f"({_SCHED_CLS[_SCHED_RANK[victim]]}) is not strictly "
                    f"weaker than the incoming '{rid}' "
                    f"({_SCHED_CLS[rank]}): an equal-or-stronger-class "
                    "request was shed",
                    anchor="shed_strictly_weaker"),)
            queue = tuple(q for q in s.queue if q != victim) + (rid,)
            acts.append((f"submit {rid} (sheds {victim})",
                         s._replace(queue=queue, subd=subd), viols))
        if s.queue:
            best = min(s.queue,
                       key=lambda q: (_SCHED_RANK[q], s.queue.index(q)))
            acts.append((f"pop {best}",
                         s._replace(queue=tuple(q for q in s.queue
                                                if q != best)), ()))
        return acts

    def check(self, s):
        return ()

    def quiescence(self, s):
        return ()

    def required_events(self) -> Set[str]:
        req = {"pop g1", "reject s2 (queue full, no weaker victim)"}
        if self.strict:
            req.add("submit g1 (sheds s2)")
        return req


# ---------------------------------------------------------------------------
# replica: supervisor lifecycle (restart/drain cancel honor, generation
# fencing, poison quarantine)
# ---------------------------------------------------------------------------

_REP_RIDS = ("r0", "r1")
_REP_KILLS = 2
_REP_MAX_RESTARTS = 1


class _RepState(NamedTuple):
    rep: str                     # alive|dead|failed
    restarts: int
    inbox: Tuple[Tuple[str, str], ...]   # ("s"|"c", rid) FIFO
    inflight: FrozenSet[str]
    engine: FrozenSet[str]       # admitted to the CURRENT generation
    abandoned: FrozenSet[str]    # threads of a pre-kill generation
    results: FrozenSet[Tuple[str, str]]
    acked: FrozenSet[str]        # cancel acknowledged to the caller
    kills: int                   # kill budget remaining
    drained: bool
    survivor: FrozenSet[str]     # resubmitted to a surviving replica
    subd: FrozenSet[str]


class ReplicaLifecycleModel:
    name = "replica"
    config = "lifecycle"

    def __init__(self, facts: Dict[str, bool]):
        self.restart_honors = facts["restart_honors_pending_cancels"]
        self.drain_honors = facts["drain_honors_pending_cancels"]
        self.fenced = facts["generation_fenced"]

    def initial(self):
        return _RepState("alive", 0, (), frozenset(), frozenset(),
                         frozenset(), frozenset(), frozenset(),
                         _REP_KILLS, False, frozenset(), frozenset())

    @staticmethod
    def _honor_cancels(inbox, inflight, results):
        for k, rid in inbox:
            if k == "c" and rid in inflight:
                inflight = inflight - {rid}
                results = results | {(rid, "cancelled")}
        return inflight, results

    def actions(self, s: _RepState):
        acts: List = []
        for rid in _REP_RIDS:
            if rid not in s.subd:
                acts.append((f"submit {rid}", s._replace(
                    subd=s.subd | {rid},
                    inbox=s.inbox + (("s", rid),),
                    inflight=s.inflight | {rid}), ()))
            if rid in s.inflight and rid not in s.acked:
                if ("s", rid) in s.inbox:   # cancelled before admission
                    idx = s.inbox.index(("s", rid))
                    acts.append((f"cancel {rid} (pre-admission)",
                                 s._replace(
                                     inbox=s.inbox[:idx] + s.inbox[idx + 1:],
                                     inflight=s.inflight - {rid},
                                     results=s.results | {(rid, "cancelled")},
                                     acked=s.acked | {rid}), ()))
                else:
                    acts.append((f"cancel {rid}", s._replace(
                        inbox=s.inbox + (("c", rid),),
                        acked=s.acked | {rid}), ()))
        if s.rep == "alive" and s.inbox:
            (k, rid), rest = s.inbox[0], s.inbox[1:]
            if k == "s":
                acts.append((f"admit {rid}", s._replace(
                    inbox=rest, engine=s.engine | {rid}), ()))
            else:
                acts.append((f"process cancel {rid}", s._replace(
                    inbox=rest, engine=s.engine - {rid},
                    inflight=s.inflight - {rid},
                    results=s.results | {(rid, "cancelled")}), ()))
        if s.rep == "alive":
            for rid in sorted(s.engine):
                if ("c", rid) in s.inbox:
                    continue     # the inbox drain will cancel it first
                viols: Tuple[Violation, ...] = ()
                if rid in s.acked:
                    viols = (Violation(
                        "APX304", "cancel-resurrect-restart",
                        f"acknowledged cancel resurrected: restart() "
                        f"resubmitted {rid} while its cancel was pending "
                        "in the inbox, and the new generation finished it "
                        "done", anchor="restart_honors_pending_cancels"),)
                acts.append((f"{rid} finishes done", s._replace(
                    engine=s.engine - {rid},
                    inflight=s.inflight - {rid},
                    results=s.results | {(rid, "done")}), viols))
        if s.rep == "alive" and s.kills > 0 and s.engine:
            acts.append(("kill replica", s._replace(
                rep="dead", abandoned=s.engine, engine=frozenset(),
                kills=s.kills - 1), ()))
        if s.rep == "dead":
            if s.restarts >= _REP_MAX_RESTARTS:
                acts.append(("restart budget spent -> failed",
                             s._replace(rep="failed"), ()))
            else:
                inflight, results = s.inflight, s.results
                if self.restart_honors:
                    inflight, results = self._honor_cancels(
                        s.inbox, inflight, results)
                acts.append(("restart (resubmits inflight)", s._replace(
                    rep="alive", restarts=s.restarts + 1,
                    inflight=inflight, results=results,
                    inbox=tuple(("s", rid) for rid in sorted(inflight))),
                    ()))
        if s.rep == "failed" and not s.drained:
            inflight, results = s.inflight, s.results
            if self.drain_honors:
                inflight, results = self._honor_cancels(
                    s.inbox, inflight, results)
            acts.append(("failover drains inflight to survivor",
                         s._replace(drained=True, inbox=(),
                                    inflight=frozenset(), results=results,
                                    survivor=inflight), ()))
        for rid in sorted(s.survivor):
            viols = ()
            if rid in s.acked:
                viols = (Violation(
                    "APX304", "cancel-resurrect-drain",
                    f"acknowledged cancel resurrected at failover: "
                    f"drain_inflight() forwarded {rid} with its cancel "
                    "still pending in the inbox, and a surviving replica "
                    "finished it done",
                    anchor="drain_honors_pending_cancels"),)
            acts.append((f"survivor finishes {rid} done", s._replace(
                survivor=s.survivor - {rid},
                results=s.results | {(rid, "done")}), viols))
        if not self.fenced:
            for rid in sorted(s.abandoned):
                acts.append((f"stale-generation thread publishes {rid}",
                             s._replace(abandoned=s.abandoned - {rid},
                                        results=s.results | {(rid, "done")}),
                             ()))
        return acts

    def check(self, s: _RepState):
        viols = []
        for rid in _REP_RIDS:
            statuses = sorted(st for r, st in s.results if r == rid)
            if len(statuses) >= 2:
                viols.append(Violation(
                    "APX302", "dup-publish",
                    f"two terminal results published for {rid} "
                    f"({' + '.join(statuses)}): a thread from a pre-kill "
                    "generation published after the supervisor restarted "
                    "(publish is not fenced on the replica generation)",
                    anchor="generation_fenced"))
        return tuple(viols)

    def quiescence(self, s: _RepState):
        viols = []
        done = {r for r, _ in s.results}
        for rid in sorted(s.subd - done):
            viols.append(Violation(
                "APX305", f"stranded-{rid}",
                f"request {rid} stranded at quiescence: submitted but no "
                "terminal result (done/cancelled/evicted) was ever "
                "published"))
        return tuple(viols)

    def required_events(self) -> Set[str]:
        return {"kill replica", "restart (resubmits inflight)",
                "restart budget spent -> failed",
                "failover drains inflight to survivor"}


_POISON_THRESHOLD = 1
_POISON_MAX_RESTARTS = 3


class _PoisonState(NamedTuple):
    rep: str
    restarts: int
    kcount: int                  # times p0 killed the replica
    inbox: Tuple[Tuple[str, str], ...]
    inflight: FrozenSet[str]
    results: FrozenSet[Tuple[str, str]]
    subd: FrozenSet[str]
    drained: bool
    survivor: FrozenSet[str]


class ReplicaPoisonModel:
    name = "replica"
    config = "poison"

    def __init__(self, facts: Dict[str, bool]):
        self.quarantines = facts["restart_quarantines_poison"]

    def initial(self):
        return _PoisonState("alive", 0, 0, (), frozenset(), frozenset(),
                            frozenset(), False, frozenset())

    def actions(self, s: _PoisonState):
        acts: List = []
        if "p0" not in s.subd:
            acts.append(("submit p0 (poison)", s._replace(
                subd=s.subd | {"p0"}, inbox=(("s", "p0"),),
                inflight=frozenset({"p0"})), ()))
        if s.rep == "alive" and s.inbox:
            acts.append(("admit p0 -> poison kills replica", s._replace(
                rep="dead", inbox=(), kcount=s.kcount + 1), ()))
        if s.rep == "dead":
            if s.restarts >= _POISON_MAX_RESTARTS:
                acts.append(("restart budget spent -> failed",
                             s._replace(rep="failed"), ()))
            elif self.quarantines and s.kcount > _POISON_THRESHOLD:
                acts.append(("restart quarantines p0 (evicted)",
                             s._replace(rep="alive",
                                        restarts=s.restarts + 1, inbox=(),
                                        inflight=frozenset(),
                                        results=s.results
                                        | {("p0", "evicted")}), ()))
            else:
                acts.append(("restart (resubmits p0)", s._replace(
                    rep="alive", restarts=s.restarts + 1,
                    inbox=(("s", "p0"),)), ()))
        if s.rep == "failed" and not s.drained:
            viols: Tuple[Violation, ...] = ()
            if s.inflight and s.kcount > _POISON_THRESHOLD:
                viols = (Violation(
                    "APX307", "poison-cascade",
                    f"a request that killed its replica {s.kcount}x was "
                    "never quarantined (restart() lacks the "
                    "poison_threshold gate): the replica crash-looped to "
                    "failure and the poison pill is forwarded to a "
                    "survivor at failover",
                    anchor="restart_quarantines_poison"),)
            acts.append(("failover drains inflight to survivor",
                         s._replace(drained=True, inflight=frozenset(),
                                    survivor=s.inflight), viols))
        return acts

    def check(self, s):
        return ()

    def quiescence(self, s: _PoisonState):
        if "p0" in s.subd and not s.results and not s.survivor:
            return (Violation(
                "APX305", "stranded-p0",
                "poison request p0 stranded at quiescence with no "
                "terminal result"),)
        return ()

    def required_events(self) -> Set[str]:
        req = {"admit p0 -> poison kills replica"}
        if self.quarantines:
            req.add("restart quarantines p0 (evicted)")
        return req


# ---------------------------------------------------------------------------
# frontend: admission/displacement (PR 7 round 2) and hedge/failover
# (PR 7 rounds 1-2)
# ---------------------------------------------------------------------------


class _AdmState(NamedTuple):
    live: FrozenSet[str]
    shed: FrozenSet[str]         # displaced, awaiting collection
    subd: FrozenSet[str]
    rejected: FrozenSet[str]
    results: FrozenSet[Tuple[str, str]]


class FrontendAdmissionModel:
    """capacity-1 pool; sheddable + guaranteed arrivals; the two
    PR 7 round-2 displacement races."""

    name = "frontend"

    def __init__(self, facts: Dict[str, bool], config: str,
                 reqs, infeasible: FrozenSet[str]):
        self.config = config
        self.order_ok = facts["feasibility_before_displacement"]
        self.skips_shed = facts["displace_skips_already_shed"]
        self.reqs = reqs                      # ((rid, qos), ...)
        self.infeasible = infeasible
        self.cap = 1

    def initial(self):
        return _AdmState(frozenset(), frozenset(), frozenset(),
                         frozenset(), frozenset())

    def _submit(self, s: _AdmState, rid: str, qos: str):
        subd = s.subd | {rid}
        feasible = rid not in self.infeasible
        if self.order_ok and not feasible:
            return (f"reject {rid} (infeasible)",
                    s._replace(subd=subd, rejected=s.rejected | {rid}), ())
        displaced = None
        live, shed = s.live, s.shed
        if len(live) >= self.cap and qos == "guaranteed":
            victims = [(r, q) for r, q in self.reqs
                       if r in live and q == "sheddable"
                       and not (self.skips_shed and r in shed)]
            if victims:
                displaced = victims[-1][0]    # youngest sheddable
                shed = shed | {displaced}
        if len(live) >= self.cap and displaced is None:
            return (f"reject {rid} (at capacity, no victim)",
                    s._replace(subd=subd, rejected=s.rejected | {rid}), ())
        if not feasible:          # pre-fix order: capacity checked first
            if displaced is None:
                return (f"reject {rid} (infeasible)",
                        s._replace(subd=subd,
                                   rejected=s.rejected | {rid}), ())
            viols = (Violation(
                "APX306", "shed-for-nothing",
                f"capacity destroyed: sheddable '{displaced}' was "
                f"displaced for '{rid}' and THEN the admission was "
                "rejected as infeasible — the victim is gone and the "
                "slot it freed admits nothing (feasibility must be "
                "checked before displacement)",
                anchor="feasibility_before_displacement"),)
            return (f"submit {rid} (displaces {displaced}; then "
                    "rejected infeasible)",
                    s._replace(subd=subd, shed=shed,
                               rejected=s.rejected | {rid}), viols)
        live = live | {rid}
        viols = ()
        if len(live - shed) > self.cap:
            viols = (Violation(
                "APX306", "stale-victim",
                f"capacity leaked: already-displaced sheddable was "
                f"picked as a victim again, so '{rid}' was admitted "
                f"against a slot that was already spent (non-shed "
                f"in-flight {len(live - shed)} > capacity {self.cap})",
                anchor="displace_skips_already_shed"),)
        label = (f"submit {rid} (displaces {displaced})" if displaced
                 else f"submit {rid}")
        return (label, s._replace(live=live, shed=shed, subd=subd), viols)

    def actions(self, s: _AdmState):
        acts = []
        for rid, qos in self.reqs:
            if rid not in s.subd:
                acts.append(self._submit(s, rid, qos))
        for rid in sorted(s.shed & s.live):
            acts.append((f"collect shed {rid} (evicted)", s._replace(
                live=s.live - {rid},
                results=s.results | {(rid, "evicted")}), ()))
        for rid in sorted(s.live - s.shed):
            acts.append((f"finish {rid} done", s._replace(
                live=s.live - {rid},
                results=s.results | {(rid, "done")}), ()))
        return acts

    def check(self, s):
        return ()

    def quiescence(self, s: _AdmState):
        viols = []
        done = {r for r, _ in s.results} | s.rejected
        for rid in sorted(s.subd - done):
            viols.append(Violation(
                "APX305", f"stranded-{rid}",
                f"request {rid} stranded at quiescence: admitted but "
                "never finished, evicted, or rejected"))
        return tuple(viols)

    def required_events(self) -> Set[str]:
        if self.config == "displace":
            return {"submit g1 (displaces s0)", "collect shed s0 (evicted)",
                    "finish g1 done"}
        return set()


_HREPS = ("A", "B")


class _HedgeState(NamedTuple):
    reps: Tuple[str, str]        # alive|dead|failed
    legs: Tuple[Tuple[str, int], ...]    # (rid, replica idx), sorted
    route: Tuple[int, ...]       # replicas ever routed, in order
    ft: bool                     # first token seen on some routed leg
    pub: Tuple[Tuple[str, int, str], ...]  # uncollected results
    late: Tuple[Tuple[str, int], ...]      # cancelled legs, result due
    tracked: bool                # the route entry still exists
    terminal: bool
    hedged: bool
    killed: int
    subd: bool
    evicted: bool


class FrontendHedgeModel:
    """2 replicas, one guaranteed request, one kill: hedge, failover,
    winner collection, loser settlement, route sweep."""

    name = "frontend"
    config = "hedge"

    def __init__(self, facts: Dict[str, bool]):
        self.waits = facts["route_waits_for_pending_legs"]
        self.no_ft = facts["hedge_requires_no_first_token"]
        self.excl_routed = facts["hedge_excludes_routed"]
        self.skips_live = facts["failover_skips_live_hedge"]

    def initial(self):
        return _HedgeState(("alive", "alive"), (), (), False, (), (),
                           False, False, False, 0, False, False)

    @staticmethod
    def _add(seq, item):
        return tuple(sorted(seq + (item,)))

    @staticmethod
    def _drop(seq, item):
        out = list(seq)
        out.remove(item)
        return tuple(out)

    def actions(self, s: _HedgeState):
        acts: List = []
        if not s.subd:
            acts.append(("submit g0 -> A", s._replace(
                subd=True, legs=(("g0", 0),), route=(0,), tracked=True), ()))
        if s.subd and not s.ft and any(s.reps[r] == "alive"
                                       for _, r in s.legs):
            acts.append(("first token streams", s._replace(ft=True), ()))
        if (s.subd and not s.hedged and not s.terminal and s.tracked
                and not (self.no_ft and s.ft)):
            if self.excl_routed:
                cands = [r for r in (0, 1)
                         if s.reps[r] == "alive" and r not in s.route]
            else:                # pre-fix: excluded only the primary leg
                cands = [r for r in (0, 1)
                         if s.reps[r] == "alive" and r != s.route[0]]
            for r in cands:
                viols = []
                if ("g0", r) in s.legs:
                    viols.append(Violation(
                        "APX302", "hedge-double-decode",
                        f"hedge fired onto replica {_HREPS[r]} which "
                        "already holds a live leg for g0: one rid "
                        "decoding concurrently twice on one engine",
                        anchor="hedge_excludes_routed"))
                if s.ft:
                    viols.append(Violation(
                        "APX306", "hedge-streaming",
                        "hedge fired for a request that is already "
                        "streaming (a routed leg has produced its first "
                        "token): the duplicate full decode burns "
                        "hedge-protected capacity for zero tail-latency "
                        "win", anchor="hedge_requires_no_first_token"))
                acts.append((f"hedge -> {_HREPS[r]}", s._replace(
                    hedged=True, legs=self._add(s.legs, ("g0", r)),
                    route=s.route + (r,)), tuple(viols)))
        for r in (0, 1):
            if s.reps[r] == "alive" and s.killed < 1 and ("g0", r) in s.legs:
                acts.append((f"kill {_HREPS[r]}", s._replace(
                    reps=tuple("dead" if i == r else st
                               for i, st in enumerate(s.reps)),
                    killed=s.killed + 1), ()))
            if s.reps[r] == "dead":
                acts.append(self._fail(s, r))
        for rid, r in s.legs:
            if s.reps[r] == "alive" and not s.terminal:
                acts.append((f"{_HREPS[r]} publishes done", s._replace(
                    legs=self._drop(s.legs, (rid, r)),
                    pub=self._add(s.pub, (rid, r, "done"))), ()))
        if s.tracked and not s.terminal:
            for rid, r, st in s.pub:
                losers = tuple(l for l in s.legs if l[0] == rid)
                acts.append((f"collect {st} from {_HREPS[r]}", s._replace(
                    terminal=True, pub=self._drop(s.pub, (rid, r, st)),
                    legs=tuple(l for l in s.legs if l[0] != rid),
                    late=tuple(sorted(s.late + losers)),
                    tracked=self.waits), ()))
        for rid, r in s.late:
            if s.reps[r] == "alive":
                acts.append((f"{_HREPS[r]} publishes late cancelled",
                             s._replace(late=self._drop(s.late, (rid, r)),
                                        pub=self._add(s.pub,
                                                      (rid, r,
                                                       "cancelled"))), ()))
        if (s.tracked and s.terminal and not s.legs and not s.late
                and s.pub):
            acts.append(("route swept (all legs settled)", s._replace(
                pub=(), tracked=False), ()))
        return acts

    def _fail(self, s: _HedgeState, r: int):
        """dead -> failed (restart budget spent) + frontend failover of
        the drained legs."""
        reps = tuple("failed" if i == r else st
                     for i, st in enumerate(s.reps))
        ns = s._replace(reps=reps,
                        late=tuple(l for l in s.late if l[1] != r))
        dead_legs = [l for l in s.legs if l[1] == r]
        if not dead_legs or s.terminal:
            return (f"{_HREPS[r]} fails (no legs to drain)",
                    ns._replace(legs=tuple(l for l in s.legs
                                           if l[1] != r)), ())
        leg = dead_legs[0]
        legs = self._drop(s.legs, leg)
        if self.skips_live:
            others = [q for q in s.route
                      if q != r and s.reps[q] == "alive"
                      and ("g0", q) in legs]
            if others:
                return (f"{_HREPS[r]} fails; dead leg dropped (live "
                        "hedge leg survives)", ns._replace(legs=legs), ())
        targets = [q for q in (0, 1) if q != r and s.reps[q] == "alive"]
        if not targets:
            return (f"{_HREPS[r]} fails; no survivor -> evicted",
                    ns._replace(legs=legs, terminal=True, evicted=True,
                                late=(), tracked=False), ())
        tgt = targets[0]
        viols: Tuple[Violation, ...] = ()
        if ("g0", tgt) in legs:
            viols = (Violation(
                "APX302", "failover-double-decode",
                f"failover resubmitted g0 onto replica {_HREPS[tgt]} "
                "which already holds its live hedge leg: one rid "
                "decoding concurrently twice on one engine",
                anchor="failover_skips_live_hedge"),)
        return (f"{_HREPS[r]} fails; failover -> {_HREPS[tgt]}",
                ns._replace(legs=self._add(legs, ("g0", tgt)),
                            route=s.route + (tgt,)), viols)

    def check(self, s: _HedgeState):
        seen = set()
        for leg in s.legs:
            if leg in seen:
                return (Violation(
                    "APX302", "dup-leg",
                    f"request g0 holds two identical live legs on "
                    f"replica {_HREPS[leg[1]]}: one rid decodes twice "
                    "on one engine", anchor="hedge_excludes_routed"),)
            seen.add(leg)
        return ()

    def quiescence(self, s: _HedgeState):
        viols = []
        if s.subd and not s.terminal:
            viols.append(Violation(
                "APX305", "request-stranded",
                "request g0 stranded at quiescence: no terminal result "
                "and no enabled recovery action"))
        if s.pub:
            viols.append(Violation(
                "APX305", "late-result-stranded",
                "a hedge loser's late result for g0 is stranded: the "
                "route entry was deleted when the winner was collected "
                "while a leg was still pending, so the sweep can never "
                "reclaim it", anchor="route_waits_for_pending_legs"))
        return tuple(viols)

    def required_events(self) -> Set[str]:
        req = {"submit g0 -> A", "first token streams",
               "A fails; failover -> B"}
        if self.excl_routed:
            req |= {"hedge -> B", "route swept (all legs settled)"}
        return req


# ---------------------------------------------------------------------------
# disagg: the handoff window + HandoffError re-route ladder (PR 16)
# ---------------------------------------------------------------------------

_DISAGG_MAX_ATTEMPTS = 1         # model bound, not the shipped default


class _DisaggState(NamedTuple):
    phase: str      # unsub|prefill|window|decode|done|evicted|cancelled
    attempts: int
    faults: int
    palive: bool
    parked: bool                 # page sits in the handoff window
    corrupt: bool
    in_decode: bool              # decode pool's store holds the page
    dec_corrupt: bool
    acked: bool                  # cancel acknowledged


class DisaggHandoffModel:
    name = "disagg"

    def __init__(self, facts: Dict[str, bool], config: str,
                 faults: int, sticky: bool):
        self.config = config
        self.bounded = facts["reroute_bounded"]
        self.pending_live = facts["pending_checks_live"]
        self.cancel_purges = facts["cancel_purges_window"]
        self.verifies = facts["verify_before_install"]
        self.faults = faults
        self.sticky = sticky     # the corruption fault re-fires forever

    def initial(self):
        return _DisaggState("unsub", 0, self.faults, True, False, False,
                            False, False, False)

    def _reroute(self, s: _DisaggState, cause: str):
        """One rung of the ladder; returns (label, state, viols)."""
        n = s.attempts + 1
        ns = s._replace(attempts=n, parked=False, corrupt=False)
        if self.bounded and n > _DISAGG_MAX_ATTEMPTS:
            return (f"{cause}; reroute limit -> evicted "
                    f"(handoff failed after {n} attempts)",
                    ns._replace(phase="evicted"), ())
        if not self.bounded and n > _DISAGG_MAX_ATTEMPTS + 2:
            return (f"{cause}; reroute #{n}",
                    ns._replace(phase="evicted"), (Violation(
                        "APX307", "reroute-unbounded",
                        "the handoff re-route ladder never terminates: a "
                        "persistently failing handoff re-routes forever "
                        "(no max_handoff_attempts eviction rung)",
                        anchor="reroute_bounded"),))
        if s.in_decode:
            return (f"{cause}; reroute: radix hit — decode store already "
                    "holds the page (prefill skipped)",
                    ns._replace(phase="decode"), ())
        if s.palive:
            return (f"{cause}; reroute: re-prefill on the prefill pool",
                    ns._replace(phase="prefill"), ())
        return (f"{cause}; reroute: decode-pool full re-prefill",
                ns._replace(phase="decode"), ())

    def actions(self, s: _DisaggState):
        acts: List = []
        if s.phase == "unsub":
            acts.append(("submit r0", s._replace(phase="prefill"), ()))
        if s.phase == "prefill" and s.palive:
            acts.append(("prefill completes; page extracted to the "
                         "handoff window",
                         s._replace(phase="window", parked=True,
                                    corrupt=False), ()))
            if s.faults > 0:
                acts.append(self._reroute(
                    s._replace(palive=False, faults=s.faults - 1),
                    "prefill replica killed in the handoff window"))
        if s.parked and not s.corrupt and (s.faults > 0 or self.sticky):
            acts.append(("page corrupted on the wire", s._replace(
                corrupt=True,
                faults=s.faults if self.sticky else s.faults - 1), ()))
        if s.phase in ("prefill", "window") and not s.acked:
            ns = s._replace(phase="cancelled", acked=True)
            if self.cancel_purges:
                ns = ns._replace(parked=False)
            acts.append(("cancel r0 (acknowledged)", ns, ()))
        if s.parked and (s.phase == "window"
                         or (s.phase == "cancelled"
                             and not self.pending_live)):
            resurrect = s.phase == "cancelled"
            viols: List[Violation] = []
            if resurrect:
                viols.append(Violation(
                    "APX304", "cancel-window-resurrect",
                    "cancelled request resurrected from the handoff "
                    "window: its parked page was delivered and the "
                    "request re-admitted to the decode pool after the "
                    "cancel was acknowledged",
                    anchor="cancel_purges_window"))
            if s.corrupt and self.verifies:
                acts.append(self._reroute(
                    s._replace(parked=False),
                    "arrival verify fails (integrity)"))
            elif s.corrupt:
                viols.append(Violation(
                    "APX307", "install-noverify",
                    "a page corrupted in the handoff window was "
                    "installed without the arrival re-digest: the decode "
                    "pool serves silently corrupt KV (token parity "
                    "broken, failure untyped)",
                    anchor="verify_before_install"))
                acts.append(("corrupt page installed (no arrival verify)",
                             s._replace(parked=False, phase="decode",
                                        in_decode=True, dec_corrupt=True),
                             tuple(viols)))
            else:
                acts.append(("page delivered; decode submitted",
                             s._replace(parked=False, phase="decode",
                                        in_decode=True), tuple(viols)))
        if s.phase == "decode":
            acts.append(("decode completes r0 (done)",
                         s._replace(phase="done"), ()))
            if s.faults > 0:
                acts.append(self._reroute(
                    s._replace(faults=s.faults - 1),
                    "decode leg lost"))
        if not s.palive and s.phase in ("prefill", "window"):
            acts.append(("prefill replica restarted",
                         s._replace(palive=True), ()))
        return acts

    def check(self, s):
        return ()

    def quiescence(self, s: _DisaggState):
        if s.phase not in ("done", "evicted", "cancelled"):
            return (Violation(
                "APX305", "stranded",
                f"request r0 stranded at quiescence in phase "
                f"'{s.phase}': no terminal result and no enabled "
                "recovery action"),)
        return ()

    def required_events(self) -> Set[str]:
        req = {"submit r0",
               "prefill completes; page extracted to the handoff window",
               "page delivered; decode submitted",
               "decode completes r0 (done)",
               "cancel r0 (acknowledged)"}
        if self.config == "transient":
            req |= {
                "prefill replica killed in the handoff window; reroute: "
                "decode-pool full re-prefill",
                "arrival verify fails (integrity); reroute: re-prefill "
                "on the prefill pool",
                "decode leg lost; reroute: radix hit — decode store "
                "already holds the page (prefill skipped)"}
        if self.config == "sticky" and self.bounded:
            req.add("arrival verify fails (integrity); reroute limit -> "
                    "evicted (handoff failed after 2 attempts)")
        return req


# ---------------------------------------------------------------------------
# autopilot: evidence-freeze and the pool-ratio donor guard
# ---------------------------------------------------------------------------

_CLEAR_SUSTAIN = 2


class _EvState(NamedTuple):
    mode: str
    clear_ticks: int


class AutopilotEvidenceModel:
    """An overloaded fleet whose metrics window goes dark: the ladder
    must freeze, not relax on absence of evidence."""

    name = "autopilot"
    config = "evidence"

    def __init__(self, facts: Dict[str, bool]):
        self.freezes = facts["evidence_freeze"]

    def initial(self):
        return _EvState("shedding", 0)

    def actions(self, s: _EvState):
        acts: List = []
        if s.mode == "shedding":
            if self.freezes:
                acts.append(("tick (metrics blackout; counters frozen)",
                             s, ()))
            else:
                ticks = s.clear_ticks + 1
                if ticks >= _CLEAR_SUSTAIN:
                    acts.append((
                        "tick (metrics blackout) -> relax to normal",
                        s._replace(mode="normal", clear_ticks=0),
                        (Violation(
                            "APX307", "blind-relax",
                            "the mode ladder relaxed during a metrics "
                            "blackout: clear-sustain accrued on "
                            "evidence-free ticks and de-escalated a "
                            "fleet that is still overloaded (decide() "
                            "lacks the evidence freeze)",
                            anchor="evidence_freeze"),)))
                else:
                    acts.append(("tick (metrics blackout)",
                                 s._replace(clear_ticks=ticks), ()))
            acts.append(("tick (overload evidence; sustain resets)",
                         s._replace(clear_ticks=0), ()))
        return acts

    def check(self, s):
        return ()

    def quiescence(self, s):
        return ()

    def required_events(self) -> Set[str]:
        return set()


class _PoolState(NamedTuple):
    prefill: int
    decode: int


class AutopilotPoolModel:
    """Sustained prefill pressure: shift_pool must stop at a 1-replica
    donor, never drain a phase to zero."""

    name = "autopilot"
    config = "pool"

    def __init__(self, facts: Dict[str, bool]):
        self.keeps_one = facts["donor_keeps_one"]

    def initial(self):
        return _PoolState(1, 2)

    def actions(self, s: _PoolState):
        if self.keeps_one and s.decode <= 1:
            return [("shift_pool declined (donor at minimum)", s, ())]
        if s.decode <= 0:
            return []
        ns = _PoolState(s.prefill + 1, s.decode - 1)
        viols: Tuple[Violation, ...] = ()
        if ns.decode == 0:
            viols = (Violation(
                "APX306", "pool-drained",
                "shift_pool drained the decode pool to zero alive "
                "replicas: the donor-keeps-one guard is missing from the "
                "pool-ratio law and the decode phase has no routable "
                "replica", anchor="donor_keeps_one"),)
        return [(f"shift_pool to prefill "
                 f"({ns.prefill}p/{ns.decode}d)", ns, viols)]

    def check(self, s):
        return ()

    def quiescence(self, s):
        return ()

    def required_events(self) -> Set[str]:
        if self.keeps_one:
            return {"shift_pool to prefill (2p/1d)",
                    "shift_pool declined (donor at minimum)"}
        return set()


# ---------------------------------------------------------------------------
# the family runner
# ---------------------------------------------------------------------------


def _models_for(family: str, facts: Dict[str, bool]):
    if family == "scheduler":
        return [SchedulerModel(facts)]
    if family == "replica":
        return [ReplicaLifecycleModel(facts), ReplicaPoisonModel(facts)]
    if family == "frontend":
        return [
            FrontendAdmissionModel(
                facts, "displace",
                reqs=(("s0", "sheddable"), ("g1", "guaranteed"),
                      ("g2", "guaranteed")),
                infeasible=frozenset()),
            FrontendAdmissionModel(
                facts, "infeasible",
                reqs=(("s0", "sheddable"), ("g1", "guaranteed")),
                infeasible=frozenset({"g1"})),
            FrontendHedgeModel(facts),
        ]
    if family == "disagg":
        return [DisaggHandoffModel(facts, "transient", faults=2,
                                   sticky=False),
                DisaggHandoffModel(facts, "sticky", faults=1,
                                   sticky=True)]
    if family == "autopilot":
        return [AutopilotEvidenceModel(facts), AutopilotPoolModel(facts)]
    raise ValueError(f"unknown protocol family {family!r}")


@functools.lru_cache(maxsize=256)
def run_protocol(family: str,
                 facts_key: FrozenSet[Tuple[str, bool]]
                 ) -> Tuple[ProtoFinding, ...]:
    """Explore every bounded configuration of ``family`` under the
    extracted ``facts``; memoized so the same parameterization (e.g.
    every clean file of a family) is explored once per process."""
    facts = {name: True for name in FAMILY_FACTS[family]}
    facts.update(dict(facts_key))
    out: List[ProtoFinding] = []
    seen_keys: Set[str] = set()
    for model in _models_for(family, facts):
        res = explore(model)
        tag = f"[{family}/{model.config}]"
        if res.truncated:
            out.append(ProtoFinding(
                "APX301", f"budget-{model.config}", "",
                f"{tag} bounded exploration exceeded the state budget "
                f"({res.n_states} states): the model configuration no "
                "longer terminates — shrink the bound or fix the model"))
            continue
        for viol, trace in res.violations:
            if viol.key in seen_keys:
                continue
            seen_keys.add(viol.key)
            out.append(ProtoFinding(
                viol.code, viol.key, viol.anchor or "",
                f"{tag} {viol.message}; {render_trace(trace)}"))
        for ev in sorted(model.required_events() - res.labels):
            key = f"unreachable-{model.config}-{ev[:40]}"
            if key in seen_keys:
                continue
            seen_keys.add(key)
            out.append(ProtoFinding(
                "APX307", key, "",
                f"{tag} ladder rung '{ev}' is unreachable in the "
                "bounded exploration: a state the protocol requires has "
                "no path to it"))
    return tuple(out)
