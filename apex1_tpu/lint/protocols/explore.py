"""Bounded exhaustive state-space exploration for the APX3xx serving
protocol models.

Pure stdlib, jax-free (the lint CLI imports this with jax poisoned —
`tests/test_lint_protocols.py` pins that). The explorer is a plain BFS
over hashable model states:

- every enabled action from every reachable state is taken (exhaustive
  interleaving coverage within the model's bounded configuration);
- BFS order means the FIRST time a violation is seen, the recorded
  predecessor chain is a shortest-or-near-shortest counterexample — the
  finding message names the exact interleaving, step by step, which is
  the whole point (review rounds found these races by hand-simulating
  interleavings; the checker hands the simulation back);
- quiescent states (no enabled action) get the model's end-of-world
  audit (nothing stranded, everything terminal).

The model duck-type (see `models.py`):

    model.name          -> str, family name ("replica", "frontend", ...)
    model.config        -> str, bounded-config label for messages
    model.initial()     -> hashable state
    model.actions(s)    -> iterable of (label, next_state, violations)
    model.check(s)      -> violations that hold in state ``s`` itself
    model.quiescence(s) -> violations audited when no action is enabled

Violations are deduplicated by ``key`` keeping the first (shortest
trace) occurrence. State budget overruns are NEVER silent: the result
carries ``truncated`` and the caller turns it into an APX301 finding.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Iterable, List, NamedTuple, Optional, Set, Tuple

__all__ = ["Violation", "ExploreResult", "explore", "MAX_STATES"]

#: Default per-(model, config) state budget. The shipped models sit in
#: the hundreds-to-low-thousands of states; 200k is a runaway backstop
#: (a model edit that explodes past it is itself a finding, not a hang).
MAX_STATES = 200_000


class Violation(NamedTuple):
    """One invariant breach, before trace attachment.

    ``key`` is the dedup identity (one finding per distinct breach, not
    one per interleaving that exhibits it); ``anchor`` names the
    extracted fact whose source line the finding should point at, or
    None for the family's class/def line.
    """

    code: str                    # "APX302".."APX308"
    key: str                     # stable dedup id within the family run
    message: str                 # the invariant statement, no trace yet
    anchor: Optional[str] = None  # fact name -> source line via extraction


class ExploreResult(NamedTuple):
    violations: Tuple[Tuple[Violation, Tuple[str, ...]], ...]
    labels: Set[str]             # every action label that ever fired
    n_states: int
    truncated: bool


def _trace_to(seen: Dict, state) -> List[str]:
    out: List[str] = []
    while True:
        prev, label = seen[state]
        if prev is None:
            break
        out.append(label)
        state = prev
    out.reverse()
    return out


def render_trace(trace: Iterable[str]) -> str:
    steps = list(trace)
    if not steps:
        return "counterexample: (initial state)"
    return ("counterexample (%d steps): %s"
            % (len(steps), " -> ".join(steps)))


def explore(model, max_states: int = MAX_STATES) -> ExploreResult:
    """Exhaustive BFS of ``model``'s bounded state space."""
    init = model.initial()
    # state -> (predecessor state, action label); init has no parent
    seen: Dict = {init: (None, None)}
    frontier = deque([init])
    found: Dict[str, Tuple[Violation, Tuple[str, ...]]] = {}
    labels: Set[str] = set()
    truncated = False

    def note(viols, state, label=None):
        for v in viols:
            if v.key in found:
                continue
            trace = _trace_to(seen, state)
            if label is not None:
                trace.append(label)
            found[v.key] = (v, tuple(trace))

    note(model.check(init), init)
    while frontier:
        s = frontier.popleft()
        acts = sorted(model.actions(s), key=lambda a: a[0])
        if not acts:
            note(model.quiescence(s), s)
            continue
        for label, ns, viols in acts:
            labels.add(label)
            note(viols, s, label)
            if ns in seen:
                continue
            if len(seen) >= max_states:
                truncated = True
                continue
            seen[ns] = (s, label)
            note(model.check(ns), ns)
            frontier.append(ns)

    ordered = tuple(sorted(found.values(),
                           key=lambda vt: (vt[0].code, vt[0].key)))
    return ExploreResult(ordered, labels, len(seen), truncated)
