"""AST-side extraction of protocol facts from the real serving source.

Same trick as the APX2xx kernel extraction: the model checker does not
hardcode what the shipped code looks like — it READS the guard
conditions out of the AST (`shed victim strictly weaker`, `restart
honors pending cancels`, `feasibility before displacement`, ...) and
parameterizes the bounded models with them. Three consequences:

- shipped code with all its guards extracts to all-true facts and the
  exploration runs clean;
- a pre-fix fixture (or a regression that deletes a guard) extracts a
  false fact and the exploration produces the race WITH the
  interleaving trace;
- a refactor that renames/removes a REQUIRED method breaks extraction
  itself — surfaced loudly as APX301 model drift, never silently.

Matching is structural (method-name signatures), not module-name based,
so the committed pre-fix/post-fix fixtures under
tests/fixtures/protocols/ are checked by the very same extractors that
check the live tree.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["Extraction", "extract_all", "FAMILY_REQUIRED_METHODS",
           "FAMILY_REQUIRED_BANKED"]


@dataclasses.dataclass
class Extraction:
    """One protocol-family match in one module."""

    family: str                  # scheduler|replica|frontend|disagg|kv|
    #                              policy|controller
    path: str
    modname: str
    name: str                    # class name or "<module>"
    line: int                    # class/first-def line
    facts: Dict[str, bool]
    anchors: Dict[str, int]      # fact -> source line of the evidence
    missing: List[str]           # required methods absent (APX301)
    banked: Set[str]             # transition names banked module-wide
    kinds: Dict[str, int]        # policy: Action kinds -> line;
    #                              controller: handled kinds -> line
    modes_down: Dict[str, str]   # controller: MODES_DOWN literal

    def line_for(self, fact: str) -> int:
        return self.anchors.get(fact, self.line)


# Method signatures that identify a family. ALL listed names must be
# present for a match-and-extract; a PARTIAL match (>= the detect set)
# with some required method missing is APX301 drift.
_DETECT: Dict[str, Set[str]] = {
    "scheduler": {"_pick_shed_victim_locked", "submit"},
    "replica": {"restart", "drain_inflight"},
    "frontend": {"_displace_sheddable", "_hedge_blown_budgets"},
    "disagg": {"_reroute", "_start_handoff"},
}

FAMILY_REQUIRED_METHODS: Dict[str, Set[str]] = {
    "scheduler": {"_pick_shed_victim_locked", "submit", "pop"},
    "replica": {"restart", "drain_inflight", "cancel", "_iterate"},
    "frontend": {"submit", "_displace_sheddable", "_collect",
                 "_failover", "_hedge_blown_budgets"},
    "disagg": {"_reroute", "_start_handoff", "_process_pending",
               "_retry_deferred", "cancel"},
    "kv": {"extract_page", "verify_page", "install_page"},
    "policy": {"decide", "_escalation", "_relaxation", "_pool_ratio"},
    "controller": {"_apply", "tick"},
}

#: transition names each family MUST bank somewhere in its module
#: (missing -> APX308 unbanked-transition).
FAMILY_REQUIRED_BANKED: Dict[str, Set[str]] = {
    "scheduler": set(),
    "replica": {"replica_dead", "replica_restart", "replica_failed"},
    "frontend": {"shed", "failover", "hedge", "mode"},
    "disagg": {"handoff", "handoff_failure", "handoff_reroute",
               "handoff_parity_mismatch", "pool_shift"},
    "kv": set(),
    "policy": set(),
    "controller": {"autopilot"},
}


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------


def _methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _module_funcs(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _attr_calls(node: ast.AST, name: str) -> List[ast.Call]:
    out = []
    for n in ast.walk(node):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == name):
            out.append(n)
    return out


def _any_calls(node: ast.AST, name: str) -> List[ast.Call]:
    """Calls to ``name`` whether spelled bare or as an attribute."""
    out = []
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if (isinstance(f, ast.Name) and f.id == name) or (
                isinstance(f, ast.Attribute) and f.attr == name):
            out.append(n)
    return out


def _first_pos(calls: List[ast.Call]) -> Optional[Tuple[int, int]]:
    if not calls:
        return None
    return min((c.lineno, c.col_offset) for c in calls)


def _refs_attr(node: ast.AST, attr: str) -> Optional[int]:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == attr:
            return n.lineno
    return None


def _compares(node: ast.AST) -> List[ast.Compare]:
    return [n for n in ast.walk(node) if isinstance(n, ast.Compare)]


def _comp_side_attr(cmp: ast.Compare, attr: str) -> bool:
    sides = [cmp.left] + list(cmp.comparators)
    return any(isinstance(x, ast.Attribute) and x.attr == attr
               for x in sides)


def _comprehension_compares_const(fn: ast.AST, const: str
                                  ) -> Optional[int]:
    """A comprehension whose `if` compares something to ``const`` —
    the `[p for k, p in inbox if k == "cancel"]` honor-scan shape."""
    for n in ast.walk(fn):
        if isinstance(n, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            for gen in n.generators:
                for test in gen.ifs:
                    for cmp in _compares(test):
                        sides = [cmp.left] + list(cmp.comparators)
                        if any(isinstance(x, ast.Constant)
                               and x.value == const for x in sides):
                            return n.lineno
    return None


def _banked_names(tree: ast.Module) -> Set[str]:
    out = set()
    for call in _attr_calls(tree, "transition"):
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            out.add(call.args[0].value)
    return out


# ---------------------------------------------------------------------------
# per-family fact extraction. Each returns (facts, anchors).
# ---------------------------------------------------------------------------


def _fact(facts, anchors, name, line, ok):
    facts[name] = bool(ok)
    if line:
        anchors[name] = line


def _extract_scheduler(m: Dict[str, ast.FunctionDef]):
    facts: Dict[str, bool] = {}
    anchors: Dict[str, int] = {}
    fn = m["_pick_shed_victim_locked"]
    # the strictly-weaker gate: `if r.rank <= incoming: continue`.
    # Pre-fix shape used `<` (skip only strictly-stronger -> equal-class
    # victims slip through).
    ok, line = False, fn.lineno
    for n in ast.walk(fn):
        if isinstance(n, ast.If):
            for cmp in _compares(n.test):
                if _comp_side_attr(cmp, "rank") or any(
                        isinstance(x, ast.Name) and "rank" in x.id
                        for x in [cmp.left] + list(cmp.comparators)):
                    has_continue = any(isinstance(b, ast.Continue)
                                       for b in ast.walk(n))
                    if has_continue and any(isinstance(op, ast.LtE)
                                            for op in cmp.ops):
                        ok, line = True, n.lineno
                    elif has_continue:
                        line = n.lineno
    _fact(facts, anchors, "shed_strictly_weaker", line, ok)
    return facts, anchors


def _extract_replica(m: Dict[str, ast.FunctionDef]):
    facts: Dict[str, bool] = {}
    anchors: Dict[str, int] = {}
    for fact, meth in (("restart_honors_pending_cancels", "restart"),
                       ("drain_honors_pending_cancels", "drain_inflight")):
        fn = m[meth]
        line = _comprehension_compares_const(fn, "cancel")
        _fact(facts, anchors, fact, line or fn.lineno, line is not None)
    fn = m["restart"]
    line = _refs_attr(fn, "poison_threshold")
    _fact(facts, anchors, "restart_quarantines_poison",
          line or fn.lineno, line is not None)
    it = m.get("_iterate")
    ok, line = False, (it.lineno if it else m["restart"].lineno)
    if it is not None:
        for n in ast.walk(it):
            if isinstance(n, ast.If):
                for cmp in _compares(n.test):
                    if _comp_side_attr(cmp, "generation") and any(
                            isinstance(op, ast.NotEq) for op in cmp.ops):
                        if any(isinstance(b, ast.Return)
                               for b in ast.walk(n)):
                            ok, line = True, n.lineno
    _fact(facts, anchors, "generation_fenced", line, ok)
    return facts, anchors


def _extract_frontend(m: Dict[str, ast.FunctionDef]):
    facts: Dict[str, bool] = {}
    anchors: Dict[str, int] = {}
    sub = m["submit"]
    p_pick = _first_pos(_attr_calls(sub, "_pick_replica"))
    p_disp = _first_pos(_attr_calls(sub, "_displace_sheddable"))
    ok = p_pick is not None and (p_disp is None or p_pick < p_disp)
    _fact(facts, anchors, "feasibility_before_displacement",
          (p_disp or p_pick or (sub.lineno, 0))[0], ok)
    disp = m["_displace_sheddable"]
    line = None
    for cmp in _compares(disp):
        if any(isinstance(op, (ast.In, ast.NotIn)) for op in cmp.ops) \
                and (_comp_side_attr(cmp, "_shed_rids")):
            line = cmp.lineno
    _fact(facts, anchors, "displace_skips_already_shed",
          line or disp.lineno, line is not None)
    col = m["_collect"]
    line = _first_pos(_attr_calls(col, "pending"))
    _fact(facts, anchors, "route_waits_for_pending_legs",
          (line or (col.lineno, 0))[0], line is not None)
    hedge = m["_hedge_blown_budgets"]
    line = _first_pos(_attr_calls(hedge, "first_token_seen"))
    _fact(facts, anchors, "hedge_requires_no_first_token",
          (line or (hedge.lineno, 0))[0], line is not None)
    line = None
    for cmp in _compares(hedge):
        if any(isinstance(op, ast.NotIn) for op in cmp.ops) and any(
                isinstance(x, ast.Name) and x.id == "routed"
                for x in cmp.comparators):
            line = cmp.lineno
    _fact(facts, anchors, "hedge_excludes_routed",
          line or hedge.lineno, line is not None)
    fo = m["_failover"]
    line = None
    for n in ast.walk(fo):
        if isinstance(n, (ast.ListComp, ast.GeneratorExp)):
            for gen in n.generators:
                if _refs_attr(gen.iter, "_route") is not None:
                    line = n.lineno
    _fact(facts, anchors, "failover_skips_live_hedge",
          line or fo.lineno, line is not None)
    return facts, anchors


def _extract_disagg(m: Dict[str, ast.FunctionDef]):
    facts: Dict[str, bool] = {}
    anchors: Dict[str, int] = {}
    rr = m["_reroute"]
    line = _refs_attr(rr, "max_handoff_attempts")
    _fact(facts, anchors, "reroute_bounded", line or rr.lineno,
          line is not None)
    live_ok, live_line = True, None
    for fact_meth in ("_process_pending", "_retry_deferred"):
        fn = m[fact_meth]
        found = None
        for cmp in _compares(fn):
            if any(isinstance(op, (ast.In, ast.NotIn))
                   for op in cmp.ops) and _comp_side_attr(cmp, "_live"):
                found = cmp.lineno
        if found is None:
            live_ok, live_line = False, fn.lineno
        elif live_line is None:
            live_line = found
    _fact(facts, anchors, "pending_checks_live",
          live_line or m["_process_pending"].lineno, live_ok)
    can = m["cancel"]
    line = None
    for n in ast.walk(can):
        if isinstance(n, (ast.ListComp, ast.GeneratorExp)):
            for gen in n.generators:
                if _refs_attr(gen.iter, "_pending") is not None:
                    line = n.lineno
    _fact(facts, anchors, "cancel_purges_window", line or can.lineno,
          line is not None)
    return facts, anchors


def _extract_kv(m: Dict[str, ast.FunctionDef]):
    facts: Dict[str, bool] = {}
    anchors: Dict[str, int] = {}
    inst = m["install_page"]
    p_ver = _first_pos(_any_calls(inst, "verify_page"))
    p_put = _first_pos(_attr_calls(inst, "put_prefix"))
    ok = p_ver is not None and (p_put is None or p_ver < p_put)
    _fact(facts, anchors, "verify_before_install",
          (p_put or p_ver or (inst.lineno, 0))[0], ok)
    return facts, anchors


def _extract_policy(m: Dict[str, ast.FunctionDef]):
    facts: Dict[str, bool] = {}
    anchors: Dict[str, int] = {}
    dec = m["decide"]
    ok, line = False, dec.lineno
    for n in ast.walk(dec):
        if isinstance(n, ast.If) and isinstance(n.test, ast.UnaryOp) \
                and isinstance(n.test.op, ast.Not):
            if _any_calls(n.test, "_has_evidence") and any(
                    isinstance(b, ast.Return) for b in ast.walk(n)):
                ok, line = True, n.lineno
    _fact(facts, anchors, "evidence_freeze", line, ok)
    pr = m["_pool_ratio"]
    ok, line = False, pr.lineno
    for cmp in _compares(pr):
        if any(isinstance(op, ast.LtE) for op in cmp.ops) and any(
                isinstance(x, ast.Constant) and x.value == 1
                for x in cmp.comparators):
            ok, line = True, cmp.lineno
    _fact(facts, anchors, "donor_keeps_one", line, ok)
    return facts, anchors


def _policy_action_kinds(tree: ast.Module) -> Dict[str, int]:
    kinds: Dict[str, int] = {}
    for call in _any_calls(tree, "Action"):
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            kinds.setdefault(call.args[0].value, call.lineno)
    return kinds


def _controller_handled_kinds(apply_fn: ast.FunctionDef
                              ) -> Dict[str, int]:
    kinds: Dict[str, int] = {}
    for cmp in _compares(apply_fn):
        if not (isinstance(cmp.left, ast.Attribute)
                and cmp.left.attr == "kind"
                and any(isinstance(op, ast.Eq) for op in cmp.ops)):
            continue
        for x in cmp.comparators:
            if isinstance(x, ast.Constant) and isinstance(x.value, str):
                kinds.setdefault(x.value, cmp.lineno)
    return kinds


def _modes_down(tree: ast.Module) -> Dict[str, str]:
    for n in tree.body:
        if isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "MODES_DOWN"
                for t in n.targets) and isinstance(n.value, ast.Dict):
            out = {}
            for k, v in zip(n.value.keys, n.value.values):
                if isinstance(k, ast.Constant) and isinstance(
                        v, ast.Constant):
                    out[k.value] = v.value
            return out
    return {}


# ---------------------------------------------------------------------------
# the walk
# ---------------------------------------------------------------------------

_CLASS_EXTRACTORS = {
    "scheduler": _extract_scheduler,
    "replica": _extract_replica,
    "frontend": _extract_frontend,
    "disagg": _extract_disagg,
}


def extract_all(mod) -> List[Extraction]:
    """All protocol-family matches in one parsed ``ModuleSource``."""
    out: List[Extraction] = []
    tree = mod.tree
    if tree is None:
        return out
    banked = _banked_names(tree)

    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        meths = _methods(node)
        names = set(meths)
        for family, detect in _DETECT.items():
            if not detect <= names:
                continue
            required = FAMILY_REQUIRED_METHODS[family]
            missing = sorted(required - names)
            facts: Dict[str, bool] = {}
            anchors: Dict[str, int] = {}
            if not missing:
                facts, anchors = _CLASS_EXTRACTORS[family](meths)
            out.append(Extraction(
                family=family, path=mod.path, modname=mod.modname,
                name=node.name, line=node.lineno, facts=facts,
                anchors=anchors, missing=missing, banked=banked,
                kinds={}, modes_down={}))
        # the controller family: a class applying Action records
        if "_apply" in names and "tick" in names:
            missing = sorted(FAMILY_REQUIRED_METHODS["controller"]
                             - names)
            out.append(Extraction(
                family="controller", path=mod.path, modname=mod.modname,
                name=node.name, line=node.lineno, facts={}, anchors={},
                missing=missing, banked=banked,
                kinds=(_controller_handled_kinds(meths["_apply"])
                       if "_apply" in meths else {}),
                modes_down=_modes_down(tree)))

    funcs = _module_funcs(tree)
    fnames = set(funcs)
    if {"install_page", "verify_page"} <= fnames:
        missing = sorted(FAMILY_REQUIRED_METHODS["kv"] - fnames)
        facts, anchors = ({}, {})
        if not missing:
            facts, anchors = _extract_kv(funcs)
        out.append(Extraction(
            family="kv", path=mod.path, modname=mod.modname,
            name="<module>", line=funcs["install_page"].lineno,
            facts=facts, anchors=anchors, missing=missing,
            banked=banked, kinds={}, modes_down={}))
    if {"decide", "_escalation"} <= fnames:
        missing = sorted(FAMILY_REQUIRED_METHODS["policy"] - fnames)
        facts, anchors = ({}, {})
        if not missing:
            facts, anchors = _extract_policy(funcs)
        out.append(Extraction(
            family="policy", path=mod.path, modname=mod.modname,
            name="<module>", line=funcs["decide"].lineno, facts=facts,
            anchors=anchors, missing=missing, banked=banked,
            kinds=_policy_action_kinds(tree), modes_down={}))
    return out
