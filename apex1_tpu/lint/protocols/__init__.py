"""graftlint serving-protocol analyzer — the APX3xx rule family.

The third leg of the static gate: APX1xx gates host-side JAX hazards,
APX2xx gates the compiled-TPU kernel/collective protocols, and APX3xx
gates the SERVING CONTROL PLANE — the scheduler/supervisor/frontend/
disagg/autopilot state machines whose interleaving bugs dominated the
PR 7 and PR 16 review rounds (stranded hedge losers, failover
double-decode, cancel resurrection from the inbox,
displacement-before-feasibility capacity destruction, handoff-window
races).

How it works (all stdlib-``ast`` + a plain BFS, no jax, no device, no
threads):

- **extract** reads the protocol guard conditions out of the real
  source AST (`extract.py`): is the shed victim strictly weaker? does
  `restart()` honor pending cancels? is feasibility checked before
  displacement? Matching is structural (method signatures), so the
  committed pre-fix fixtures under tests/fixtures/protocols/ are
  checked by the same extractors as the live tree, and a refactor that
  removes a required method is APX301 model drift — never silent.
- **models** parameterizes five bounded state-machine models with the
  extracted facts (`models.py`): scheduler shed ladder, replica
  lifecycle (+ poison quarantine), frontend admission/hedge/failover,
  disagg handoff + re-route ladder, autopilot evidence/pool actuators.
- **explore** walks EVERY interleaving of every bounded configuration
  (`explore.py`, <=3 replicas / <=4 requests / <=2 faults, hundreds to
  thousands of states) and reports each invariant breach with a
  shortest-path counterexample naming the exact interleaving.

Entry points: ``tools/lint.py --protocols`` (the ``== graftlint
protocols ==`` check_all step), ``lint_paths(..., protocols=True)``,
and the tier-1 repo self-check. The APX1xx suppression grammar and
exit-code contract apply unchanged: ``# graftlint: allow(APX304) --
reason``.

What this does NOT prove (docs/lint.md spells it out): wall-clock
timing, hardware handoff-window behavior, real thread schedules beyond
the modeled interleavings, or anything about configurations larger
than the explored bounds.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple

from apex1_tpu.lint.core import Finding
from apex1_tpu.lint.project import Project
from apex1_tpu.lint.protocols.extract import (FAMILY_REQUIRED_BANKED,
                                              Extraction, extract_all)
from apex1_tpu.lint.protocols.models import run_protocol

__all__ = ["PROTOCOL_RULES", "ProtocolRule", "check_protocols"]


class ProtocolRule(NamedTuple):
    code: str
    slug: str
    summary: str


#: catalogue (exploration is model-level, not per-rule — docs/lint.md
#: documents each invariant and the bounded-config contract)
PROTOCOL_RULES = [
    ProtocolRule("APX301", "protocol-model",
                 "protocol model drift: a required method is gone, the "
                 "guard extraction failed, or a bounded exploration "
                 "blew its state budget — never silently skipped"),
    ProtocolRule("APX302", "double-decode",
                 "one request id live on two engine legs at once, or "
                 "two terminal results published for one rid"),
    ProtocolRule("APX303", "qos-inversion",
                 "a shed victim not strictly weaker than the incoming "
                 "class (equal-or-stronger-class shed)"),
    ProtocolRule("APX304", "cancel-resurrect",
                 "an acknowledged cancel later finishes done — via "
                 "restart resubmission, failover drain, or the disagg "
                 "handoff window"),
    ProtocolRule("APX305", "stranded-result",
                 "a request or a late leg result uncollectable at "
                 "quiescence (no enabled action can ever reclaim it)"),
    ProtocolRule("APX306", "capacity-leak",
                 "capacity destroyed or double-spent: displacement "
                 "before feasibility, a stale shed victim recounted, a "
                 "hedge on a streaming request, a drained donor pool"),
    ProtocolRule("APX307", "ladder",
                 "a ladder rung unreachable, unexitable, or unbounded; "
                 "a mandatory gate (verify-before-install, evidence "
                 "freeze, poison quarantine, MODES_DOWN inverse) "
                 "missing"),
    ProtocolRule("APX308", "unbanked-transition",
                 "a protocol transition the module never banks via "
                 "metrics.transition(), or a policy Action kind the "
                 "controller cannot actuate"),
]

_LADDER_MODES = ("shedding", "degraded")


def _finding(code: str, ex: Extraction, line: int, msg: str) -> Finding:
    return Finding(code, ex.path, line, 0, msg)


def _family_findings(ex: Extraction, family: str) -> List[Finding]:
    out = []
    facts_key = frozenset(ex.facts.items())
    for pf in run_protocol(family, facts_key):
        line = ex.line_for(pf.anchor) if pf.anchor else ex.line
        out.append(_finding(pf.code, ex, line,
                            f"{ex.name}: {pf.message}"))
    return out


def _static_findings(ex: Extraction) -> List[Finding]:
    """APX301 drift + APX308 banked-transition audit, all families."""
    out = []
    for meth in ex.missing:
        out.append(_finding(
            "APX301", ex, ex.line,
            f"protocol model drift: {ex.family} family matched "
            f"'{ex.name}' but required method '{meth}' is gone — "
            "re-anchor the APX3xx extractor or restore the method "
            "(the model cannot be checked against this source)"))
    if not ex.missing:
        for name in sorted(FAMILY_REQUIRED_BANKED.get(ex.family, set())
                           - ex.banked):
            out.append(_finding(
                "APX308", ex, ex.line,
                f"{ex.name}: protocol transition '{name}' is never "
                "banked in this module via metrics.transition() — the "
                f"{ex.family} episode record is unreconstructable from "
                "banked events"))
    return out


def _controller_findings(ex: Extraction) -> List[Finding]:
    out = []
    md = ex.modes_down
    if not md:
        out.append(_finding(
            "APX307", ex, ex.line,
            f"{ex.name}: MODES_DOWN de-escalation table not found at "
            "module scope — the mode ladder has no machine-checkable "
            "inverse"))
        return out
    for mode in _LADDER_MODES:
        if mode not in md:
            out.append(_finding(
                "APX307", ex, ex.line,
                f"{ex.name}: mode '{mode}' has no MODES_DOWN edge — "
                "the ladder can escalate into it but never de-escalate "
                "out (unexitable rung)"))
            continue
        cur, hops = mode, 0
        while cur in md and hops <= len(md) + 1:
            cur, hops = md[cur], hops + 1
        if cur != "normal":
            out.append(_finding(
                "APX307", ex, ex.line,
                f"{ex.name}: MODES_DOWN chain from '{mode}' terminates "
                f"at '{cur}', not 'normal' — relaxation cannot reach "
                "the ground mode"))
    return out


def check_protocols(project: Project) -> List[Finding]:
    """Extract + model-check every protocol-family match in the
    project; cross-check policy Action kinds against controller
    dispatch when both sides are present."""
    findings: List[Finding] = []
    policies: List[Extraction] = []
    controllers: List[Extraction] = []
    for mod in project.modules:
        for ex in extract_all(mod):
            findings.extend(_static_findings(ex))
            if ex.missing:
                continue
            if ex.family in ("scheduler", "replica", "frontend",
                             "disagg"):
                findings.extend(_family_findings(ex, ex.family))
            elif ex.family == "kv":
                # the verify-before-install gate feeds the disagg
                # handoff model (the only kv-side protocol fact)
                findings.extend(_family_findings(ex, "disagg"))
            elif ex.family == "policy":
                policies.append(ex)
                findings.extend(_family_findings(ex, "autopilot"))
            elif ex.family == "controller":
                controllers.append(ex)
                findings.extend(_controller_findings(ex))
    for pol in policies:
        for ctl in controllers:
            handled = set(ctl.kinds)
            for kind in sorted(set(pol.kinds) - handled):
                findings.append(_finding(
                    "APX308", pol, pol.kinds[kind],
                    f"policy emits Action kind '{kind}' that "
                    f"{ctl.name}._apply never dispatches — actuation "
                    "raises ValueError at runtime (policy/controller "
                    "version skew)"))
    return findings
