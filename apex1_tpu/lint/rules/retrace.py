"""APX102 retrace: things that silently recompile (or bake in trace
garbage) on every call.

Four sub-checks:

a. **static annotation sanity** — ``static_argnums`` out of range /
   ``static_argnames`` naming a nonexistent parameter (jit raises late,
   at first call, with an unhelpful message), and a static-marked
   parameter whose default is a mutable literal (unhashable ->
   TypeError at dispatch; hashable-but-mutated -> a retrace per call).
b. **trace-time clocks** — ``time.time()`` / ``perf_counter()`` /
   ``datetime.now()`` inside a traced body bake the TRACE time into
   the executable as a constant: not a retrace, a silent wrong-answer.
c. **trace-time f-strings** — an f-string inside a traced body renders
   the TRACER's repr, not the runtime value. Allowed inside ``raise``
   and ``assert`` (trace-time error text is exactly what you want
   there).
d. **python branch on a traced value** — ``if``/``while`` on a value
   that flows from a ``jnp``/``jax.lax``/``jax.random`` call raises
   TracerBoolConversionError under jit, or — when the function is only
   SOMETIMES jitted — forks one retrace per observed truth value.
   ``x.shape``/``x.ndim``/``x.dtype`` accesses are static and exempt.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from apex1_tpu.lint.core import Finding
from apex1_tpu.lint.project import (FunctionInfo, Project, own_body_walk)

_CLOCKS = {
    "time.time", "time.time_ns", "time.perf_counter",
    "time.perf_counter_ns", "time.monotonic", "time.monotonic_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}

#: calls whose results are traced arrays (prefix match)
_TRACED_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.random.", "jax.nn.",
                    "jax.scipy.")

#: jax.lax.* calls that return PYTHON statics at trace time (axis_size
#: is psum of a literal — an int, branching on it is idiomatic)
_STATIC_CALLS = {"jax.lax.axis_size", "jax.numpy.shape",
                 "jax.numpy.ndim", "jax.numpy.result_type"}

#: attribute accesses on an array that are STATIC under tracing
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    _check_static_annotations(project, findings)
    for info in project.hot_functions():
        _check_clocks(project, info, findings)
        _check_fstrings(project, info, findings)
        _check_traced_branch(project, info, findings)
    return findings


# ---- a: static_argnums / static_argnames sanity -------------------------

def _check_static_annotations(project: Project,
                              findings: List[Finding]) -> None:
    for site in project.jit_sites:
        if site.target is None:
            continue
        params = site.target.params
        has_varargs = bool(getattr(site.target.node, "args", None)
                           and site.target.node.args.vararg)
        line, col = site.call.lineno, site.call.col_offset
        path = site.mod.path
        if site.static_argnums:
            for i in site.static_argnums:
                if i >= len(params) and not has_varargs:
                    findings.append(Finding(
                        "APX102", path, line, col,
                        f"static_argnums={i} is out of range for "
                        f"'{site.target.qualname}' "
                        f"({len(params)} parameters) — jit will fail "
                        f"at first call"))
                elif i < len(params):
                    _check_static_default(site, params[i], findings)
        if site.static_argnames:
            for name in site.static_argnames:
                if name not in params:
                    findings.append(Finding(
                        "APX102", path, line, col,
                        f"static_argnames={name!r} does not name a "
                        f"parameter of '{site.target.qualname}'"))
                else:
                    _check_static_default(site, name, findings)


def _check_static_default(site, pname: str,
                          findings: List[Finding]) -> None:
    node = site.target.node
    a = getattr(node, "args", None)
    if a is None:
        return
    pos = a.posonlyargs + a.args
    defaults = a.defaults
    # defaults align to the TAIL of the positional params
    offset = len(pos) - len(defaults)
    for idx, p in enumerate(pos):
        if p.arg != pname or idx < offset:
            continue
        d = defaults[idx - offset]
        if isinstance(d, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
            findings.append(Finding(
                "APX102", site.mod.path, d.lineno, d.col_offset,
                f"static parameter {pname!r} of "
                f"'{site.target.qualname}' has a mutable default — "
                f"unhashable under jit (and a retrace per mutation "
                f"if made hashable)"))
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if p.arg == pname and isinstance(
                d, (ast.List, ast.Dict, ast.Set)):
            findings.append(Finding(
                "APX102", site.mod.path, d.lineno, d.col_offset,
                f"static parameter {pname!r} of "
                f"'{site.target.qualname}' has a mutable default — "
                f"unhashable under jit"))


# ---- b: trace-time clocks ----------------------------------------------

def _check_clocks(project: Project, info: FunctionInfo,
                  findings: List[Finding]) -> None:
    for node in own_body_walk(info.node):
        if isinstance(node, ast.Call):
            dotted = project.resolve_dotted(info.mod, node.func)
            if dotted in _CLOCKS:
                findings.append(Finding(
                    "APX102", info.mod.path, node.lineno,
                    node.col_offset,
                    f"{dotted}() inside traced function "
                    f"'{info.qualname}' is evaluated ONCE at trace "
                    f"time and baked into the executable"))


# ---- c: f-strings at trace time ----------------------------------------

def _check_fstrings(project: Project, info: FunctionInfo,
                    findings: List[Finding]) -> None:
    """Flag f-strings that interpolate a possibly-traced name (a
    parameter or a jnp/lax/random-derived local) outside raise/assert/
    warnings.warn — those three legitimately render at trace time, on
    the static path, as their whole point."""
    maybe_traced = set(info.params) | _traced_locals(project, info)
    if not maybe_traced:
        return

    def interpolates_traced(js: ast.JoinedStr) -> Optional[str]:
        for v in js.values:
            if not isinstance(v, ast.FormattedValue):
                continue
            for n in ast.walk(v.value):
                if isinstance(n, ast.Name) and n.id in maybe_traced:
                    return n.id
        return None

    def is_warn_call(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, (ast.Name, ast.Attribute))
                and (node.func.id if isinstance(node.func, ast.Name)
                     else node.func.attr).endswith("warn"))

    def visit(node: ast.AST, exempt: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            if (isinstance(child, (ast.Raise, ast.Assert))
                    or is_warn_call(child)):
                visit(child, True)
                continue
            if isinstance(child, ast.JoinedStr) and not exempt:
                name = interpolates_traced(child)
                if name is not None:
                    findings.append(Finding(
                        "APX102", info.mod.path, child.lineno,
                        child.col_offset,
                        f"f-string interpolates possibly-traced "
                        f"'{name}' in '{info.qualname}' — renders at "
                        f"TRACE time (a tracer repr, not the runtime "
                        f"value); ok only inside raise/assert/warn"))
                    continue
            visit(child, exempt)

    if isinstance(info.node, ast.Lambda):
        return  # a lambda body holds no raise/assert statements
    for stmt in getattr(info.node, "body", []):
        visit(stmt, isinstance(stmt, (ast.Raise, ast.Assert)))


# ---- d: python branch on a traced value --------------------------------

def _traced_locals(project: Project, info: FunctionInfo) -> Set[str]:
    """Names assigned (anywhere in the function) from jnp/lax/random
    calls, plus one propagation round through BinOp/compare chains."""
    traced: Set[str] = set()
    for _ in range(2):
        for node in own_body_walk(info.node):
            if not isinstance(node, ast.Assign):
                continue
            if _is_traced_expr(project, info, node.value, traced):
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            traced.add(n.id)
    return traced


def _is_traced_expr(project: Project, info: FunctionInfo, expr: ast.AST,
                    traced: Set[str]) -> bool:
    if isinstance(expr, ast.Call):
        dotted = project.resolve_dotted(info.mod, expr.func)
        if (dotted and dotted.startswith(_TRACED_PREFIXES)
                and dotted not in _STATIC_CALLS):
            # shape/dtype queries stay python-static
            return not (isinstance(expr.func, ast.Attribute)
                        and expr.func.attr in _STATIC_ATTRS)
        return False
    if isinstance(expr, ast.Name):
        return expr.id in traced
    if isinstance(expr, ast.BinOp):
        return (_is_traced_expr(project, info, expr.left, traced)
                or _is_traced_expr(project, info, expr.right, traced))
    if isinstance(expr, ast.UnaryOp):
        return _is_traced_expr(project, info, expr.operand, traced)
    if isinstance(expr, ast.Compare):
        return any(_is_traced_expr(project, info, e, traced)
                   for e in [expr.left] + list(expr.comparators))
    return False


def _check_traced_branch(project: Project, info: FunctionInfo,
                         findings: List[Finding]) -> None:
    traced = _traced_locals(project, info)
    if not traced:
        return
    for node in own_body_walk(info.node):
        test = None
        kind = None
        if isinstance(node, ast.If):
            test, kind = node.test, "if"
        elif isinstance(node, ast.While):
            test, kind = node.test, "while"
        elif isinstance(node, ast.IfExp):
            test, kind = node.test, "conditional expression"
        if test is None:
            continue
        name = _traced_name_in_test(test, traced)
        if name is not None:
            findings.append(Finding(
                "APX102", info.mod.path, test.lineno, test.col_offset,
                f"python {kind} on traced value '{name}' in "
                f"'{info.qualname}' — TracerBoolConversionError under "
                f"jit, or one retrace per truth value; use jnp.where/"
                f"lax.cond (or lift the value to static_argnums)"))


def _traced_name_in_test(test: ast.AST, traced: Set[str]):
    parents: Dict[int, ast.AST] = {}
    comp_bound: Set[str] = set()  # comprehension targets shadow locals
    for node in ast.walk(test):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
        if isinstance(node, (ast.GeneratorExp, ast.ListComp,
                             ast.SetComp, ast.DictComp)):
            for gen in node.generators:
                comp_bound.update(n.id for n in ast.walk(gen.target)
                                  if isinstance(n, ast.Name))
    for node in ast.walk(test):
        if not (isinstance(node, ast.Name) and node.id in traced
                and node.id not in comp_bound):
            continue
        # x.shape / x.ndim / len(x) / isinstance(x, ...) are static
        par = parents.get(id(node))
        if isinstance(par, ast.Attribute) and par.attr in _STATIC_ATTRS:
            continue
        if (isinstance(par, ast.Call) and isinstance(par.func, ast.Name)
                and par.func.id in ("len", "isinstance", "getattr",
                                    "hasattr", "type")):
            continue
        if isinstance(par, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot))
                for op in par.ops):
            continue  # `x is (not) None` is a static identity check
        return node.id
    return None
