"""APX103 prng-reuse: the same key consumed by two samplers.

JAX keys are use-once: two consumers of one key draw CORRELATED (often
identical) streams — the classic "my dropout masks repeat every step"
bug, invisible in loss curves until convergence quietly degrades. The
rule runs a straight-line abstract interpretation over every function:

- a name becomes a KEY when assigned from ``jax.random.{PRNGKey,key,
  split,fold_in,clone,wrap_key_data}``, aliased/subscripted from a key,
  or when a parameter is key-named (``key``/``rng``/``*_key``/…);
- a key is CONSUMED when passed to a ``jax.random`` sampler, to
  ``split`` (splitting an already-used key correlates the children
  with the earlier draw), or as a bare argument to any other call (the
  callee presumably draws from it);
- ``fold_in(key, salt)`` does NOT consume — deriving many streams from
  one base key with distinct salts is the sanctioned pattern;
- assignment to a name clears its consumed state (``rng, sub =
  split(rng)`` is the idiomatic refresh);
- the Pallas TPU kernel PRNG (``pltpu.prng_seed`` /
  ``pltpu.prng_random_bits``) does NOT consume: its argument is a plain
  int32 COUNTER SEED, not a jax.random key — re-seeding in a forward
  kernel and again in the backward's mask recompute is the in-kernel
  stochasticity contract (`apex1_tpu.ops.stochastic`), not key reuse.
  Deriving such seeds at the call site via one ``jax.random.randint``
  draw (which consumes its key ONCE, correctly tracked) or
  ``ops.stochastic.fold_seed`` is the sanctioned idiom.

A consumed key consumed again -> finding. Branches are analyzed
independently and merged conservatively (a key must be consumed on ALL
paths to stay consumed); loop bodies get a second pass seeded with the
first pass's exit state so loop-carried reuse (``for i: x =
normal(key)``) is caught and labeled as such.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

from apex1_tpu.lint.core import Finding
from apex1_tpu.lint.project import FunctionInfo, Project

_KEY_PARAM = re.compile(r"^(key|keys|rng|prng|rngs)$|(_key|_rng|_keys)$")

_MAKERS = {"PRNGKey", "key", "wrap_key_data", "clone"}
_NONCONSUMING = {"fold_in", "key_data", "key_impl"}
# Pallas TPU in-kernel PRNG: consumes int32 counter seeds, never keys —
# matched by dotted-path suffix (pltpu.prng_seed resolves to
# jax.experimental.pallas.tpu.prng_seed) or bare attribute name when the
# import alias cannot be resolved
_KERNEL_PRNG = {"prng_seed", "prng_random_bits"}


@dataclasses.dataclass
class _State:
    keys: Set[str]
    consumed: Dict[str, int]  # name -> line of consuming call

    def copy(self) -> "_State":
        return _State(set(self.keys), dict(self.consumed))

    def merge(self, other: "_State") -> "_State":
        # keys: union (being a key is monotone); consumed: intersection
        # (only flag reuse that happens on every path)
        consumed = {n: ln for n, ln in self.consumed.items()
                    if n in other.consumed}
        return _State(self.keys | other.keys, consumed)


class _FnChecker:
    def __init__(self, project: Project, info: FunctionInfo):
        self.project = project
        self.info = info
        self.findings: List[Finding] = []
        self._seen: Set[Tuple[int, int]] = set()  # (line, col) dedupe

    # -- entry ------------------------------------------------------------

    def run(self) -> List[Finding]:
        node = self.info.node
        if isinstance(node, ast.Lambda):
            return []
        state = _State(keys={p for p in self.info.params
                             if _KEY_PARAM.search(p)}, consumed={})
        self._block(list(getattr(node, "body", [])), state,
                    loop_pass=False)
        return self.findings

    # -- interpretation ---------------------------------------------------

    def _block(self, stmts: List[ast.stmt], state: _State,
               loop_pass: bool) -> _State:
        for stmt in stmts:
            state = self._stmt(stmt, state, loop_pass)
        return state

    def _stmt(self, stmt: ast.stmt, state: _State,
              loop_pass: bool) -> _State:
        if isinstance(stmt, ast.If):
            a = self._block(stmt.body, state.copy(), loop_pass)
            b = self._block(stmt.orelse, state.copy(), loop_pass)
            return a.merge(b)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._eval_calls(stmt.iter, state, loop_pass)
            self._rebind_target(stmt.target, None, state)
            once = self._block(stmt.body, state.copy(), loop_pass)
            # second pass: catches reuse carried around the back edge
            end = self._block(stmt.body, once.copy(), True)
            end = self._block(stmt.orelse, end, loop_pass)
            return state.merge(end)
        if isinstance(stmt, ast.While):
            self._eval_calls(stmt.test, state, loop_pass)
            once = self._block(stmt.body, state.copy(), loop_pass)
            end = self._block(stmt.body, once.copy(), True)
            end = self._block(stmt.orelse, end, loop_pass)
            return state.merge(end)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._eval_calls(item.context_expr, state, loop_pass)
            return self._block(stmt.body, state, loop_pass)
        if isinstance(stmt, ast.Try):
            body = self._block(stmt.body, state.copy(), loop_pass)
            merged = body
            for h in stmt.handlers:
                merged = merged.merge(
                    self._block(h.body, state.copy(), loop_pass))
            merged = self._block(stmt.orelse, merged, loop_pass)
            return self._block(stmt.finalbody, merged, loop_pass)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return state  # separate scope, checked on its own
        # simple statement: evaluate calls, then rebind targets
        self._eval_calls(stmt, state, loop_pass)
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                self._rebind_target(tgt, stmt.value, state)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._rebind_target(stmt.target, stmt.value, state)
        elif isinstance(stmt, ast.AugAssign):
            self._rebind_target(stmt.target, None, state)
        return state

    # -- calls ------------------------------------------------------------

    def _eval_calls(self, node: ast.AST, state: _State,
                    loop_pass: bool) -> None:
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(n, ast.Call):
                self._call(n, state, loop_pass)
            stack.extend(ast.iter_child_nodes(n))

    def _call(self, call: ast.Call, state: _State,
              loop_pass: bool) -> None:
        dotted = self.project.resolve_dotted(self.info.mod, call.func)
        leaf = (dotted.rsplit(".", 1)[-1] if dotted
                else (call.func.attr
                      if isinstance(call.func, ast.Attribute) else None))
        if leaf in _KERNEL_PRNG:
            return  # int32 counter seed, not a key — re-seeding is fine
        if dotted and dotted.startswith("jax.random."):
            fn = dotted[len("jax.random."):]
            if fn in _MAKERS or fn in _NONCONSUMING:
                return
            # split and every sampler consume their key argument
            key_arg = call.args[0] if call.args else None
            if isinstance(key_arg, ast.Name):
                self._consume(key_arg.id, call, state, loop_pass,
                              via=f"jax.random.{fn}")
                state.keys.add(key_arg.id)
            return
        # any other call: a bare key argument escapes into the callee
        for arg in list(call.args) + [k.value for k in call.keywords]:
            if isinstance(arg, ast.Name) and arg.id in state.keys:
                self._consume(arg.id, call, state, loop_pass,
                              via=ast.unparse(call.func))

    def _consume(self, name: str, call: ast.Call, state: _State,
                 loop_pass: bool, via: str) -> None:
        prev = state.consumed.get(name)
        if name in state.keys and prev is not None:
            pos = (call.lineno, call.col_offset)
            if pos not in self._seen:
                self._seen.add(pos)
                carried = " (loop-carried)" if loop_pass else ""
                self.findings.append(Finding(
                    "APX103", self.info.mod.path, call.lineno,
                    call.col_offset,
                    f"PRNG key '{name}' already consumed at line "
                    f"{prev} is used again by {via} in "
                    f"'{self.info.qualname}'{carried} — split or "
                    f"fold_in first"))
        state.consumed[name] = call.lineno
        if prev is not None:
            state.consumed[name] = prev  # keep the FIRST consumption

    # -- assignment -------------------------------------------------------

    def _rebind_target(self, tgt: ast.AST, value: Optional[ast.AST],
                       state: _State) -> None:
        names = [n.id for n in ast.walk(tgt) if isinstance(n, ast.Name)]
        is_key = value is not None and self._is_key_expr(value, state)
        for nm in names:
            state.consumed.pop(nm, None)
            if is_key:
                state.keys.add(nm)
            elif value is not None:
                state.keys.discard(nm)

    def _is_key_expr(self, value: ast.AST, state: _State) -> bool:
        if isinstance(value, ast.Call):
            dotted = self.project.resolve_dotted(self.info.mod,
                                                 value.func)
            if dotted and dotted.startswith("jax.random."):
                fn = dotted[len("jax.random."):]
                return fn in _MAKERS | {"split", "fold_in"}
            return False
        if isinstance(value, ast.Name):
            return value.id in state.keys
        if isinstance(value, ast.Subscript):
            return (isinstance(value.value, ast.Name)
                    and value.value.id in state.keys)
        if isinstance(value, (ast.Tuple, ast.List)):
            return any(self._is_key_expr(e, state) for e in value.elts)
        return False


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for info in project.functions.values():
        findings.extend(_FnChecker(project, info).run())
    return findings
