"""graftlint rule registry.

A rule is ``(code, slug, summary, check)`` where ``check(project) ->
list[Finding]``. Rules see the whole :class:`~apex1_tpu.lint.project.
Project` (hot set, jit sites, import aliases) and must anchor each
finding to the line of the offending node so per-line suppressions
land. To add a rule: write ``check`` in a new module here, register the
code/slug in ``core.RULE_SLUGS``, append to ``RULES``, document it in
``docs/lint.md``, and give it a positive + negative + suppressed
fixture in ``tests/test_lint.py`` (the self-check test will hold you to
a clean dogfood run).
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple

from apex1_tpu.lint.core import Finding
from apex1_tpu.lint.project import Project
from apex1_tpu.lint.rules import (compat, donation, host_sync, prng,
                                  retrace)


class Rule(NamedTuple):
    code: str
    slug: str
    summary: str
    check: Callable[[Project], List[Finding]]


RULES = [
    Rule("APX101", "host-sync",
         "host synchronization inside a traced/hot function",
         host_sync.check),
    Rule("APX102", "retrace",
         "retrace hazards: bad static_argnums/argnames, trace-time "
         "clocks and f-strings, python branches on traced values",
         retrace.check),
    Rule("APX103", "prng-reuse",
         "a PRNG key consumed twice without split/fold_in between",
         prng.check),
    Rule("APX104", "donation",
         "a donate_argnums buffer read after the donating call",
         donation.check),
    Rule("APX105", "compat-spelling",
         "newer-jax spelling that bypasses the _install_jax_compat "
         "bridge", compat.check),
]
