"""APX105 compat-spelling: newer-jax spellings that bypass the bridge.

`apex1_tpu.__init__._install_jax_compat` is the ONLY reason
``jax.shard_map`` / ``jax.set_mesh`` / ``jax.lax.pcast`` /
``jax.lax.axis_size`` work on the 0.4.x verify image — the exact
failure class that cost 126 tests before PR 1 added the bridge. The
invariants this rule holds:

- **bridged spellings need the bridge installed**: a module OUTSIDE
  the ``apex1_tpu`` package (tools/, examples/) that uses a bridged
  spelling must import ``apex1_tpu`` somewhere — package modules get
  the bridge for free via ``__init__``. AttributeError otherwise, but
  only on the old image, which is why it ships.
- **``jax.typeof`` is NEVER bridged**: it has no 0.4.x equivalent and
  the bridge deliberately does not fake one (a wrong vma is worse than
  none). The sanctioned access is ``ops._common.out_struct`` (or a
  local getattr guard). Flagged everywhere outside the two bridge
  files.
- **legacy spellings are banned too**: ``jax.experimental.shard_map``
  imports and ``check_rep=`` kwargs pin the OLD api, bypassing the
  bridge's check_vma translation — one spelling (``jax.shard_map``)
  everywhere, the bridge makes it true.
"""

from __future__ import annotations

import ast
from typing import List

from apex1_tpu.lint.core import Finding, ModuleSource
from apex1_tpu.lint.project import Project

#: modules that ARE the bridge — exempt from every sub-check
BRIDGE_MODULES = {"apex1_tpu", "apex1_tpu.ops._common"}

_BRIDGED = {"jax.shard_map", "jax.set_mesh", "jax.lax.pcast",
            "jax.lax.axis_size"}
_NEVER_BRIDGED = {"jax.typeof"}


def _has_bridge(mod: ModuleSource) -> bool:
    if mod.modname == "apex1_tpu" or mod.modname.startswith("apex1_tpu."):
        return True  # importing any submodule runs the package __init__
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            if any(al.name.split(".")[0] == "apex1_tpu"
                   for al in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] == "apex1_tpu":
                return True
    return False


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.modules:
        if mod.tree is None or mod.modname in BRIDGE_MODULES:
            continue
        bridged_ok = _has_bridge(mod)
        seen = set()  # (line, col): nested Attribute chains collide

        def emit(line, col, msg):
            if (line, col) not in seen:
                seen.add((line, col))
                findings.append(Finding("APX105", mod.path, line, col,
                                        msg))

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module.startswith("jax.experimental.shard_map"):
                    emit(node.lineno, node.col_offset,
                         "legacy 'jax.experimental.shard_map' import — "
                         "use the unified jax.shard_map spelling (the "
                         "compat bridge makes it work on 0.4.x)")
                continue
            if isinstance(node, ast.Attribute):
                dotted = project.resolve_dotted(mod, node)
                if dotted is None:
                    continue
                if dotted in _NEVER_BRIDGED:
                    emit(node.lineno, node.col_offset,
                         f"{dotted} has NO 0.4.x fallback and is not "
                         f"bridged — use ops._common.out_struct or a "
                         f"getattr guard")
                elif dotted in _BRIDGED and not bridged_ok:
                    emit(node.lineno, node.col_offset,
                         f"{dotted} is a bridged spelling but this "
                         f"module never imports apex1_tpu — "
                         f"AttributeError on jax 0.4.x (the bridge "
                         f"installs it)")
                elif dotted.startswith("jax.experimental.shard_map"):
                    emit(node.lineno, node.col_offset,
                         "legacy jax.experimental.shard_map spelling — "
                         "use jax.shard_map (bridged)")
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg == "check_rep":
                        emit(kw.value.lineno, kw.value.col_offset,
                             "check_rep= is the legacy spelling of "
                             "check_vma= — the bridge translates "
                             "check_vma, spell it that way")
    return findings
