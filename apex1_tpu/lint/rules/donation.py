"""APX104 donation: a donated buffer read after the donating call.

``donate_argnums`` hands the argument's buffer to XLA for in-place
reuse; the python reference left behind is POISON — reading it raises
on strict backends and silently serves stale/garbage memory on others
(and on CPU jax skips donation entirely, so the bug ships invisibly:
correct on the dev box, corrupt on the TPU). The repo's own
`utils/debug.py` lists this as hazard #1.

Mechanics: the project index records every ``jax.jit(...,
donate_argnums=...)`` site. A module pre-pass binds each donating
wrapper to the names it's assigned to (``g = jax.jit(f, donate...)``,
``self._decode = jax.jit(decode, ...)``, or the decorated function's
own name). Each function is then scanned statement-by-statement: a
call through a donating binding marks the argument expressions at the
donated positions dead; a later READ of a dead name (before
reassignment) is a finding. Reads and rebinds inside one statement
resolve in call order (reads first, then donation, then the
assignment targets), so the engine's canonical
``nxt, ..., self.kv.cache, ... = self._decode(..., self.kv.cache, ...)``
— donate + rebind in one statement — is correctly clean.

Branches merge conservatively: a buffer must be donated on ALL paths
to stay dead (no false positives from one-armed donation).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from apex1_tpu.lint.core import Finding
from apex1_tpu.lint.project import (FunctionInfo, JitSite, Project,
                                    own_body_walk)


def _expr_str(node: ast.AST) -> Optional[str]:
    """Stable string for a Name or dotted-Name chain; None otherwise."""
    parts = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    parts.append(cur.id)
    return ".".join(reversed(parts))


def _donating_bindings(project: Project,
                       mod) -> Dict[str, JitSite]:
    """name ('g', 'self._decode', 'f') -> donating JitSite, module-wide.

    Coarse on purpose: `self._x` bindings are matched by spelling, not
    per-class dataflow — two classes in one module sharing an attribute
    name would alias. That trade buys the common engine pattern without
    a type system."""
    bindings: Dict[str, JitSite] = {}
    for site in project.jit_sites:
        if site.mod is not mod or not site.donate_argnums:
            continue
        if site.target is not None and site.call in getattr(
                site.target.node, "decorator_list", []):
            bindings[site.target.name] = site
    for info in list(project.functions.values()):
        if info.mod is not mod:
            continue
        for n in own_body_walk(info.node):
            if not isinstance(n, ast.Assign):
                continue
            site = project.jit_site_by_call.get(id(n.value))
            if site is None or not site.donate_argnums:
                continue
            for tgt in n.targets:
                name = _expr_str(tgt)
                if name:
                    bindings[name] = site
                    site.bound_names.append(name)
    return bindings


class _FnChecker:
    def __init__(self, project: Project, info: FunctionInfo,
                 bindings: Dict[str, JitSite]):
        self.project = project
        self.info = info
        self.bindings = bindings
        self.findings: List[Finding] = []
        self._seen: Set[tuple] = set()

    def run(self) -> List[Finding]:
        node = self.info.node
        if isinstance(node, ast.Lambda):
            return []
        self._block(list(getattr(node, "body", [])), {})
        return self.findings

    # dead: expr string -> line where it was donated
    def _block(self, stmts, dead: Dict[str, int]) -> Dict[str, int]:
        for stmt in stmts:
            dead = self._stmt(stmt, dead)
        return dead

    def _stmt(self, stmt, dead: Dict[str, int]) -> Dict[str, int]:
        if isinstance(stmt, ast.If):
            a = self._block(stmt.body, dict(dead))
            b = self._block(stmt.orelse, dict(dead))
            return {k: v for k, v in a.items() if k in b}
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            head = stmt.iter if isinstance(
                stmt, (ast.For, ast.AsyncFor)) else stmt.test
            self._check_reads(head, dead)
            once = self._block(stmt.body, dict(dead))
            end = self._block(stmt.body, dict(once))  # loop-carried
            end = self._block(stmt.orelse, end)
            return {k: v for k, v in dead.items() if k in end}
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_reads(item.context_expr, dead)
            return self._block(stmt.body, dead)
        if isinstance(stmt, ast.Try):
            out = self._block(stmt.body, dict(dead))
            for h in stmt.handlers:
                hb = self._block(h.body, dict(dead))
                out = {k: v for k, v in out.items() if k in hb}
            out = self._block(stmt.orelse, out)
            return self._block(stmt.finalbody, out)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return dead
        # simple statement: reads, then donations, then rebinds
        self._check_reads(stmt, dead)
        for call, site in self._donating_calls(stmt):
            for i in site.donate_argnums:
                if i < len(call.args):
                    name = _expr_str(call.args[i])
                    if name:
                        dead[name] = call.lineno
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                self._rebind(tgt, dead)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            self._rebind(stmt.target, dead)
        return dead

    def _donating_calls(self, stmt):
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                name = _expr_str(n.func)
                if name in self.bindings:
                    yield n, self.bindings[name]

    def _rebind(self, tgt: ast.AST, dead: Dict[str, int]) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._rebind(e, dead)
            return
        name = _expr_str(tgt)
        if name is None:
            return
        # rebinding x revives x AND x.anything
        for k in [k for k in dead
                  if k == name or k.startswith(name + ".")]:
            del dead[k]

    def _check_reads(self, node: ast.AST, dead: Dict[str, int]) -> None:
        if not dead:
            return
        for n in ast.walk(node):
            if not isinstance(n, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(n, "ctx", None), ast.Load):
                continue
            name = _expr_str(n)
            if name is None or name not in dead:
                continue
            pos = (n.lineno, n.col_offset)
            if pos in self._seen:
                continue
            self._seen.add(pos)
            self.findings.append(Finding(
                "APX104", self.info.mod.path, n.lineno, n.col_offset,
                f"'{name}' was donated (donate_argnums) at line "
                f"{dead[name]} and read afterwards in "
                f"'{self.info.qualname}' — the buffer may be "
                f"invalidated on TPU (CPU runs hide this)"))


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    per_mod_bindings = {}
    for mod in project.modules:
        if mod.tree is not None:
            per_mod_bindings[id(mod)] = _donating_bindings(project, mod)
    for info in project.functions.values():
        bindings = per_mod_bindings.get(id(info.mod), {})
        if bindings:
            findings.extend(
                _FnChecker(project, info, bindings).run())
    return findings
