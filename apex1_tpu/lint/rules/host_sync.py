"""APX101 host-sync: host synchronization inside a traced function.

Inside a jit/scan/shard_map body (or anything those bodies call — the
"hot" set), a host-synchronizing call either crashes at trace time
(``.item()`` on a tracer raises ConcretizationTypeError) or — worse —
silently works during warmup because the value is still concrete, then
stalls the dispatch chain in production (the serving engine's
async-dispatch contract: the host must never block on a step's
outputs). The flagged set:

- ``x.item()``             — concretizes; the classic accidental sync
- ``np.asarray(x)`` / ``np.array(x)`` — pulls a device array to host
- ``jax.device_get(x)``    — explicit fetch
- ``jax.block_until_ready`` / ``x.block_until_ready()`` — explicit sync

Host-side code (engine loops, metrics drains, tools) is untouched:
the rule fires only on functions the reachability pass marked hot.
"""

from __future__ import annotations

import ast
from typing import List

from apex1_tpu.lint.core import Finding
from apex1_tpu.lint.project import Project, own_body_walk

_SYNC_CALLS = {
    "numpy.asarray": "np.asarray pulls the value to host",
    "numpy.array": "np.array pulls the value to host",
    "jax.device_get": "jax.device_get is a host fetch",
    "jax.block_until_ready": "block_until_ready stalls dispatch",
}

_SYNC_METHODS = {
    "item": ".item() concretizes (host sync; breaks under tracing)",
    "block_until_ready": ".block_until_ready() stalls dispatch",
    "tolist": ".tolist() concretizes (host sync; breaks under tracing)",
}


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for info in project.hot_functions():
        for node in own_body_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            msg = None
            dotted = project.resolve_dotted(info.mod, node.func)
            if dotted in _SYNC_CALLS:
                msg = _SYNC_CALLS[dotted]
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in _SYNC_METHODS):
                msg = _SYNC_METHODS[node.func.attr]
            if msg:
                findings.append(Finding(
                    "APX101", info.mod.path, node.lineno, node.col_offset,
                    f"{msg} — inside traced function "
                    f"'{info.qualname}' (jit-reachable)"))
    return findings
