"""graftlint core: findings, the suppression grammar, per-file parsing.

The linter is stdlib-``ast`` only (no new deps) so it runs anywhere the
repo does — including the no-TPU CI image. Everything here is
runtime-free: no jax import, no device touch.

Suppression grammar (per line, reason MANDATORY)::

    x = jax.device_get(y)  # graftlint: allow(APX101) -- metrics drain, off hot path
    # graftlint: allow(prng-reuse, APX102) -- fixture: intentional reuse
    y = jax.random.normal(key)

A suppression comment on a code line covers findings anchored to that
line; a comment-ONLY line covers the next line (for lines too long to
carry the comment). Rules are named by code (``APX101``) or slug
(``host-sync``). A malformed suppression — missing ``--``, empty
reason, unknown rule — is itself a finding (``APX000 bad-suppression``)
and cannot be suppressed: the grammar is the audit trail, so it must
stay parseable.

Reachability markers (same placement rules)::

    def _debug_dump(...):   # graftlint: cold -- host-side debug helper
    def _step_body(...):    # graftlint: hot -- driven by the serving loop

``hot`` force-marks a function as traced (linted as a jit body) when
the call graph can't see the connection; ``cold`` severs it (e.g. a
callback that only ever runs under ``jax.pure_callback``). Both take a
mandatory reason too — a reachability override is as load-bearing as a
suppression.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Dict, List, Optional, Set, Tuple

#: rule code -> slug. The registry in ``rules/__init__.py`` holds the
#: checker callables; this table exists so suppressions can be validated
#: without importing the rule modules (core must not depend on rules).
RULE_SLUGS: Dict[str, str] = {
    "APX000": "bad-suppression",
    "APX001": "parse-error",
    "APX101": "host-sync",
    "APX102": "retrace",
    "APX103": "prng-reuse",
    "APX104": "donation",
    "APX105": "compat-spelling",
    # APX2xx: the kernel/collective analyzer (lint/kernels/, opt-in
    # via lint_*(kernels=True) / `tools/lint.py --kernels`)
    "APX201": "sem-protocol",
    "APX202": "dma-race",
    "APX203": "kernel-hang",
    "APX204": "ring-guard",
    "APX205": "ppermute-perm",
    "APX206": "axis-binding",
    "APX207": "exclusive-knobs",
    "APX208": "vmem-budget",
    "APX209": "kernel-binding",
    # APX3xx: the serving control-plane protocol model checker
    # (lint/protocols/, opt-in via lint_*(protocols=True) /
    # `tools/lint.py --protocols`)
    "APX301": "protocol-model",
    "APX302": "double-decode",
    "APX303": "qos-inversion",
    "APX304": "cancel-resurrect",
    "APX305": "stranded-result",
    "APX306": "capacity-leak",
    "APX307": "ladder",
    "APX308": "unbanked-transition",
}

_SLUG_TO_CODE = {v: k for k, v in RULE_SLUGS.items()}


def canonical_rule(token: str) -> Optional[str]:
    """``'APX101'`` or ``'host-sync'`` -> ``'APX101'``; None if unknown."""
    token = token.strip()
    up = token.upper()
    if up in RULE_SLUGS:
        return up
    return _SLUG_TO_CODE.get(token.lower())


@dataclasses.dataclass
class Finding:
    rule: str                    # "APX101"
    path: str                    # repo-relative where possible
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: Optional[str] = None  # the suppression's reason when suppressed

    @property
    def slug(self) -> str:
        return RULE_SLUGS.get(self.rule, "?")

    def as_dict(self) -> dict:
        d = {"rule": self.rule, "slug": self.slug, "path": self.path,
             "line": self.line, "col": self.col, "message": self.message,
             "suppressed": self.suppressed}
        if self.reason is not None:
            d["reason"] = self.reason
        return d

    def render(self) -> str:
        tag = " [suppressed]" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"({self.slug}) {self.message}{tag}")


@dataclasses.dataclass
class Suppression:
    line: int                    # line the comment sits on
    target_line: int             # line whose findings it covers
    rules: Tuple[str, ...]       # canonical codes
    reason: str
    used: bool = False


_DIRECTIVE = re.compile(r"#\s*graftlint:\s*(.*)$")
_ALLOW = re.compile(r"allow\(([^)]*)\)\s*(?:--\s*(.*))?$")
_MARKER = re.compile(r"(hot|cold)\b\s*(?:--\s*(.*))?$")


@dataclasses.dataclass
class ModuleSource:
    """One parsed file plus its suppression/marker side tables."""

    path: str                    # as given (display)
    modname: str                 # dotted module name, "" if unknown
    text: str
    tree: Optional[ast.Module]
    suppressions: List[Suppression]
    hot_lines: Dict[int, int]    # marker target line -> comment line
    cold_lines: Dict[int, int]   # marker target line -> comment line
    errors: List[Finding]        # APX000/APX001 raised during parse

    def suppression_for(self, finding: Finding) -> Optional[Suppression]:
        for sup in self.suppressions:
            if sup.target_line == finding.line and finding.rule in sup.rules:
                return sup
        return None


def _comment_lines(text: str):
    """Yield (line, col, comment_text, target_line) via tokenize — the
    only way to find comments without tripping on '#' inside strings.

    ``target_line`` is the line a directive on this comment governs:
    the comment's own line when code precedes it, otherwise the next
    line that carries CODE (a standalone directive above a def may be
    followed by more comment lines before the def itself)."""
    code_lines: Set[int] = set()
    comments: List[Tuple[int, int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.start[1], tok.string))
            elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                                  tokenize.INDENT, tokenize.DEDENT,
                                  tokenize.ENCODING, tokenize.ENDMARKER):
                code_lines.add(tok.start[0])
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # the ast parse reports the real error
    for line, col, comment in comments:
        if line in code_lines:
            target = line
        else:
            later = [ln for ln in code_lines if ln > line]
            target = min(later) if later else line + 1
        yield line, col, comment, target


def parse_module(path: str, text: str, modname: str = "") -> ModuleSource:
    errors: List[Finding] = []
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        errors.append(Finding("APX001", path, e.lineno or 1,
                              e.offset or 0, f"syntax error: {e.msg}"))
        tree = None

    suppressions: List[Suppression] = []
    hot_lines: Dict[int, int] = {}
    cold_lines: Dict[int, int] = {}
    for line, col, comment, target in _comment_lines(text):
        m = _DIRECTIVE.search(comment)
        if not m:
            continue
        body = m.group(1).strip()
        am = _ALLOW.match(body)
        if am:
            raw_rules = [t for t in (s.strip() for s in
                                     am.group(1).split(",")) if t]
            reason = (am.group(2) or "").strip()
            codes = []
            bad = None
            for tok in raw_rules:
                code = canonical_rule(tok)
                if code is None:
                    bad = f"unknown rule {tok!r}"
                    break
                codes.append(code)
            if not raw_rules:
                bad = "allow() names no rules"
            if not reason:
                bad = bad or "missing '-- reason' (reason is mandatory)"
            if bad:
                errors.append(Finding(
                    "APX000", path, line, col,
                    f"bad suppression: {bad} in {comment.strip()!r}"))
                continue
            suppressions.append(Suppression(line=line, target_line=target,
                                            rules=tuple(codes),
                                            reason=reason))
            continue
        mm = _MARKER.match(body)
        if mm:
            reason = (mm.group(2) or "").strip()
            if not reason:
                errors.append(Finding(
                    "APX000", path, line, col,
                    f"bad marker: '{mm.group(1)}' needs '-- reason'"))
                continue
            (hot_lines if mm.group(1) == "hot" else
             cold_lines)[target] = line
            continue
        errors.append(Finding(
            "APX000", path, line, col,
            f"unrecognized graftlint directive {body!r} "
            f"(expected allow(RULE,...) -- reason, hot -- reason, "
            f"or cold -- reason)"))
    return ModuleSource(path=path, modname=modname, text=text, tree=tree,
                        suppressions=suppressions, hot_lines=hot_lines,
                        cold_lines=cold_lines, errors=errors)


def apply_suppressions(mod: ModuleSource,
                       findings: List[Finding]) -> List[Finding]:
    """Mark findings covered by a suppression; APX000/APX001 never
    suppress (they ARE the suppression machinery's own errors)."""
    out = []
    for f in findings:
        if f.rule not in ("APX000", "APX001"):
            sup = mod.suppression_for(f)
            if sup is not None:
                f.suppressed = True
                f.reason = sup.reason
                sup.used = True
        out.append(f)
    return out


def unused_suppressions(mod: ModuleSource) -> List[Suppression]:
    return [s for s in mod.suppressions if not s.used]
