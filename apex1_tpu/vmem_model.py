"""The ONE per-kernel VMEM sizing model — shared by the tuning
registry, the graftlint kernel analyzer, and the AOT gate.

History: these formulas started life private to ``tuning.registry``
(gating table entries against ``core.capability.vmem_budget``), while
the RDMA reduce-scatter's sizing rule lived as prose in
``ops/fused_collective.matmul_reduce_scatter_rdma``'s docstring and a
comment beside ``tools/aot_check.py``'s compile gate. Three consumers,
three copies, zero machine checks. This module is the deduplication:

- ``tuning.registry`` builds its :class:`KernelSpec` ``check``
  callables from the ``*_check`` functions here (gating behavior pinned
  bit-identical to the pre-refactor formulas by
  ``tests/test_lint_kernels.py::TestVmemModelShared``);
- ``apex1_tpu.lint.kernels`` (graftlint APX208) prices statically
  evaluable ``pallas_call`` frames against ``budget_bytes`` — the gate
  that runs with NO jax and NO hardware;
- ``tools/aot_check.py`` sizes the RDMA gate shape through
  :func:`rdma_check` instead of restating the ``16·chunk·N`` bound in
  a comment.

Everything here is stdlib-only and jax-free: the lint CLI imports this
module through its stub-parent path (``tools/lint.py``), so nothing
below may import jax, numpy, or any ``apex1_tpu`` module that does.
The generation budgets come from ``core.capability`` (itself jax-free
at import; jax is touched only inside ``detect_generation``).

All models are GATING models, not performance models: coarse, monotone
in the block sizes, generous enough that every block shape the analytic
heuristics produce passes, tight enough that the shapes AOT analysis
showed OOMing do not.
"""

from __future__ import annotations

from typing import Mapping

#: fp32 scratch/statistics lanes — every row-stat scratch buffer is
#: (rows, 128) fp32 regardless of input dtype
LANES = 128
#: Pallas double-buffers every blocked operand
DB = 2


def budget_bytes(generation: str | None = None) -> int:
    """``core.capability.vmem_budget`` — re-exported here so every
    sizing consumer prices against the same figure. Off-TPU (and for
    the static analyzer, always) this is the conservative v5e planning
    budget."""
    from apex1_tpu.core.capability import vmem_budget
    return vmem_budget(generation)


def flash_check(blocks, dims, es, budget):
    """Flash attention frame: q/k/v/o blocks (double-buffered, input
    dtype), fp32 (acc, m, l) scratch, and the live fp32 score + exp
    tiles (bq, bk) the MXU step materializes in vregs/VMEM."""
    bq, bk = blocks["block_q"], blocks["block_k"]
    dp = dims["Dp"]
    est = (DB * es * (bq * dp + 2 * bk * dp)       # q, k, v in
           + DB * es * bq * dp                     # o out
           + 4 * (bq * dp + 2 * bq * LANES)        # acc, m, l scratch
           + 2 * 4 * bq * bk)                      # s and e tiles
    return est <= budget, est


def row_check(n_passes):
    """Row-wise kernels (softmax/LN/xentropy/rope): ``n_passes``
    row-block operands of (br, lanes_p), double-buffered, priced fp32
    (compute is fp32 even for bf16 inputs)."""
    def check(blocks, dims, _es, budget):
        br = blocks["block_rows"]
        est = n_passes * DB * br * dims["lanes"] * 4
        return est <= budget, est
    return check


def linear_xent_check(blocks, dims, es, budget):
    """Fused LM-head CE: the binding constraint is the AOT-established
    accumulator bound (``ops/linear_xent._auto_blocks``): the fp32
    dx (bt, Hp) + dw (bv, Hp) accumulators must fit 3/4 of a quarter of
    the VMEM budget; the double-buffered operand blocks and the live
    (bt, bv) logit tile are additionally bounded by the full budget."""
    bt, bv = blocks["block_t"], blocks["block_v"]
    hp = dims["Hp"]
    acc = 4 * (bt + bv) * hp
    est = (acc + DB * es * (bt + bv) * hp + 2 * 4 * bt * bv)
    ok = est <= budget and acc <= (budget // 4) * 3 // 4
    return ok, est


def cm_check(blocks, dims, es, budget):
    """Fused-collective chunk matmul (`ops.fused_collective.
    _chunk_matmul`, the tile loop of the ppermute-ring and RDMA
    reduce-scatter forms): x (bm, Kp) and w (Kp, bn) operand blocks
    (double-buffered, input dtype) + the fp32 (bm, bn) output block.
    K is untiled by design (one MXU dot per output tile, no cross-grid
    accumulation), so Kp itself bounds the frame."""
    bm, bn = blocks["block_m"], blocks["block_n"]
    kp = dims["Kp"]
    est = DB * es * (bm * kp + kp * bn) + DB * 4 * bm * bn
    return est <= budget, est


def agf_check(blocks, dims, es, budget):
    """All-gather-fused flash attention (`ops.fused_collective.
    _agf_kernel`): the flash frame plus the carried fp32 (prev_out,
    prev_lse) merge operands and the fp32 merged output block the
    epilogue writes (the plain kernel's output is input-dtype)."""
    ok, est = flash_check(blocks, dims, es, budget)
    bq, dp = blocks["block_q"], dims["Dp"]
    extra = (DB * 4 * (bq * dp + bq * LANES)     # prev_out, prev_lse in
             + DB * 4 * bq * dp                  # merged fp32 out
             - DB * es * bq * dp)                # replaces q-dtype out
    est = est + extra
    return est <= budget, est


def paged_decode_check(blocks, dims, es, budget):
    """Paged ragged decode attention (`ops.paged_decode.paged_attend`):
    one (page, Dp) K page block + one V page block per grid step
    (double-buffered, CACHE dtype ``es`` — int8 pages are a quarter of
    the f32 frame, which is the capacity-tier point), the (Rq, Dp)
    query and output blocks, fp32 (acc, m, l) flash scratch, and the
    live fp32 (Rq, page) score + exp tiles."""
    p = blocks["page_p"]
    dp, rq = dims["Dp"], dims["Rq"]
    est = (DB * es * 2 * p * dp                    # k, v page blocks
           + DB * 4 * rq * dp                      # q block (fp32 path)
           + DB * 4 * rq * dp                      # o block
           + 4 * (rq * dp + 2 * rq * LANES)        # acc, m, l scratch
           + 2 * 4 * rq * p)                       # s and e tiles
    return est <= budget, est


def fused_sample_check(blocks, dims, _es, budget):
    """Fused sampling epilogue (`ops.paged_decode.fused_sample`): one
    (8, block_v) fp32 logits block (a sublane-aligned tile of rows,
    double-buffered) + the (8, LANES) key/token lanes, plus the live
    fp32/uint32 temporaries of the in-kernel threefry->gumbel pipeline
    (~6 block-width vectors: counter pair, two threefry lanes, bits,
    gumbel+logits)."""
    rows = 8                                       # sublane row tile
    bv = blocks["block_v"]
    est = (DB * 4 * rows * bv                      # logits block
           + 2 * DB * 4 * rows * LANES             # keys in, tokens out
           + 6 * 4 * rows * bv)                    # pipeline temporaries
    return est <= budget, est


def chunked_loss_check(blocks, dims, es, budget):
    """Chunked preference/distill losses (`ops.chunked_loss`): the
    streaming frame is one sublane row-tile of the per-chunk logits —
    (8, chunk_v) fp32, double-buffered — beside the (8, Hp) hidden rows
    feeding the chunk matmul and the (8, LANES) packed-stat lanes.
    The inner Pallas work is priced separately by ``linear_xent_check``
    (the chunk rides ``shard_stats_packed``); this model bounds the
    CHUNK choice itself so a mis-tuned chunk_v fails loudly at trace
    time instead of OOMing the recompute on silicon."""
    cv = blocks["chunk_v"]
    hp = dims["Hp"]
    rows = 8                                       # sublane row tile
    est = (DB * 4 * rows * cv                      # live chunk logit tile
           + DB * es * rows * hp                   # hidden rows in
           + 4 * rows * LANES)                     # packed stat lanes
    return est <= budget, est


def fused_swiglu_check(blocks, dims, es, budget):
    """Fused SwiGLU/GeGLU MLP (`ops.fused_dense.fused_glu`): x (bt, Hp)
    block + the two weight (Hp, bf) blocks (double-buffered, input
    dtype), the (bt, bf) output block, and the two live fp32 (bt, bf)
    gate/up tiles the elementwise glu consumes before the cast."""
    bt, bf = blocks["block_t"], blocks["block_f"]
    hp = dims["Hp"]
    est = (DB * es * (bt * hp + 2 * hp * bf)       # x, w_gate, w_up in
           + DB * es * bt * bf                     # out block
           + 2 * 4 * bt * bf)                      # fp32 g and u tiles
    return est <= budget, est


def lora_epilogue_check(blocks, dims, es, budget):
    """Multi-tenant LoRA decode epilogue (`ops.lora_epilogue.lora_delta`):
    per grid step one gathered A page (sublane-padded (8, Hp)) and one
    B page vocab tile (8, block_v), both double-buffered in page dtype,
    beside the (8, Hp) hidden row, the (8, block_v) delta output block
    and its fp32 accumulator scratch. Rank is a GRID axis (pages stream
    one at a time through the block-table gather), so it never enters
    the frame — only Hp and block_v do."""
    bv = blocks["block_v"]
    hp = dims["Hp"]
    rows = 8                                       # sublane row tile
    est = (DB * es * rows * hp                     # A page block
           + DB * es * rows * bv                   # B page vocab tile
           + DB * es * rows * hp                   # hidden row in
           + DB * es * rows * bv                   # delta out block
           + 4 * rows * bv)                        # fp32 accumulator
    return est <= budget, est


def int8_check(blocks, dims, _es, budget):
    """int8 decode GEMM at the kernel's worst-case row count (T <= 1024,
    ``ops/quantized._aligned_for_kernel``): bf16 x block, int8 w block
    (double-buffered), fp32 out block + scales."""
    bn, bk = blocks["block_n"], blocks["block_k"]
    t = 1024
    est = (DB * (t * bk * 2 + bn * bk * 1 + bn * 4) + t * bn * 4)
    return est <= budget, est


# ---------------------------------------------------------------------------
# the RDMA reduce-scatter sizing rule — previously comment-only
# ---------------------------------------------------------------------------

def rdma_slot_bytes(chunk: int, n_cols: int) -> int:
    """The four fp32 chunk slots (2 recv + 2 send double buffers) of
    ``ops.fused_collective._mrs_rdma_kernel``: ``16 * chunk * N``
    bytes — the bound PR 9's review established from the measured
    RESOURCE_EXHAUSTED at chunk=512, N=1024 on v5e."""
    return 4 * 4 * chunk * n_cols


def rdma_check(chunk: int, k: int, n_cols: int, es: int,
               budget: int) -> tuple[bool, int]:
    """Full static frame of the RDMA matmul->reduce-scatter kernel:
    the four fp32 chunk slots beside the double-buffered x (chunk, K)
    and w (K, N) operand blocks and the fp32 (chunk, N) output block.
    At the v5e budget this reproduces both gate data points: (256,
    1024, 512) bf16 fits with margin (~6 MiB), (512, 1024, 1024) does
    not (measured RESOURCE_EXHAUSTED)."""
    est = (rdma_slot_bytes(chunk, n_cols)
           + DB * es * (chunk * k + k * n_cols)   # x, w operand blocks
           + DB * 4 * chunk * n_cols)             # fp32 out block
    return est <= budget, est


#: the registry-facing name -> check table; ``tuning.registry`` builds
#: its SPECS from this, and the analyzer uses it to price kernels it can
#: match to a registered spec.
CHECKS: dict[str, object] = {
    "flash_attention": flash_check,
    "fused_softmax": row_check(3),       # y, dy, dx row blocks
    "layer_norm": row_check(5),          # x, dy, dx + dg/db acc
    "rope": row_check(6),                # x1, x2, cos, sin, o1, o2
    "xentropy": row_check(2),            # x in, dx out (stats are
                                         # (br, 1) noise)
    "bias_dropout_add": row_check(4),    # x, residual, out (+ dy/dx in
                                         # bwd); mask is PRNG-recomputed,
                                         # never stored
    "linear_xent": linear_xent_check,
    "fused_collective_matmul": cm_check,
    "fused_ag_flash": agf_check,
    "int8_matmul": int8_check,
    "paged_decode": paged_decode_check,
    "fused_sample": fused_sample_check,
    "chunked_loss": chunked_loss_check,
    "fused_swiglu": fused_swiglu_check,
    "lora_epilogue": lora_epilogue_check,
}


def static_frame_bytes(block_bytes: Mapping[str, int] | None = None, *,
                       operand_bytes: int = 0,
                       scratch_bytes: int = 0) -> int:
    """Generic lower-bound frame for a ``pallas_call`` the analyzer can
    price without a registered spec: double-buffered blocked operands
    plus (single-buffered) scratch. A LOWER bound by construction —
    anything unpriceable contributes zero — so exceeding the budget is
    proof, not heuristic."""
    return DB * operand_bytes + scratch_bytes
