"""Tracing / profiling / metrics — SURVEY.md §5.1, §5.5.

Reference: ``apex.pyprof`` monkey-patched every torch callable with
``torch.cuda.nvtx.range_push(json_args)`` so nsys timelines carry op names,
and post-processed profiler SQLite into per-kernel FLOPs/bytes
(``pyprof/prof``). ``apex/transformer`` threads an optional ``timers``
callable through the pipeline schedules.

TPU-native equivalents:
- `annotate` — ``jax.named_scope`` + ``jax.profiler.TraceAnnotation``
  (≙ nvtx ranges; names land in XLA HLO metadata AND the profiler trace).
- `trace` — context manager around ``jax.profiler.start_trace`` writing a
  TensorBoard-loadable trace (≙ running under nsys).
- `cost_analysis` — compile-time FLOPs/bytes attribution from XLA
  (≙ pyprof/prof's per-kernel FLOP counting, but exact and free).
- `Timers` — named wall-clock timers with device sync, the
  ``apex/transformer`` ``timers`` contract.
- `MetricsLogger` — per-step structured metrics (loss, grad-norm,
  loss-scale, skip-count, tokens/sec/chip — the BASELINE.json metric).
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Any, Callable, Optional

import jax
import numpy as np


@contextlib.contextmanager
def annotate(name: str):
    """Name a region for both XLA metadata and profiler timelines."""
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a TensorBoard profiler trace of the enclosed block."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def cost_analysis(fn: Callable, *args, **kwargs) -> dict:
    """Compile ``fn`` (without running it) and return XLA's cost model:
    ``{"flops": ..., "bytes accessed": ..., "transcendentals": ...}``."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0]
    return dict(ca) if ca else {}


def flops_per_step(fn: Callable, *args, **kwargs) -> float:
    return float(cost_analysis(fn, *args, **kwargs).get("flops", 0.0))


class Timers:
    """Named cumulative timers (``timers("fwd").start()/.stop()``) — the
    calling convention ``apex/transformer`` schedules expect. ``stop``
    blocks on ``sync`` trees so device work is attributed correctly."""

    class _Timer:
        def __init__(self):
            self.elapsed_ = 0.0
            self.count = 0
            self._t0: Optional[float] = None

        def start(self):
            self._t0 = time.perf_counter()

        def stop(self, sync: Any = None):
            if sync is not None:
                jax.block_until_ready(sync)
            self.elapsed_ += time.perf_counter() - self._t0
            self.count += 1
            self._t0 = None

        def elapsed(self, reset: bool = False) -> float:
            e = self.elapsed_
            if reset:
                self.elapsed_, self.count = 0.0, 0
            return e

    def __init__(self):
        self._timers: dict[str, Timers._Timer] = {}

    def __call__(self, name: str) -> "Timers._Timer":
        return self._timers.setdefault(name, Timers._Timer())

    def log(self, names=None, *, reset: bool = True) -> dict[str, float]:
        names = list(self._timers) if names is None else names
        return {n: self._timers[n].elapsed(reset=reset) for n in names
                if n in self._timers}


class MetricsLogger:
    """Structured per-step metrics with tokens/sec/chip derivation.

    ``log(step, metrics, tokens=...)`` fetches scalars (one small transfer)
    and emits a JSON line via ``print`` or a supplied writer."""

    def __init__(self, writer: Optional[Callable[[str], None]] = None,
                 n_chips: Optional[int] = None):
        self.writer = writer or print
        self.n_chips = n_chips or jax.device_count()
        self._last_t: Optional[float] = None
        self._last_step: Optional[int] = None

    def log(self, step: int, metrics: dict, *, tokens: Optional[int] = None
            ) -> dict:
        now = time.perf_counter()
        rec = {"step": int(step)}
        for k, v in metrics.items():
            if isinstance(v, (str, bool)):
                rec[k] = v
                continue
            try:
                arr = np.asarray(jax.device_get(v))
                if arr.size == 1 and arr.dtype != object:
                    rec[k] = float(arr)
                elif arr.dtype != object:
                    rec[k] = arr.tolist()  # vectors go in whole
                else:
                    raise TypeError("non-array metric")
            except (TypeError, ValueError):
                # arbitrary pytrees (e.g. train-step aux) — keep a
                # readable form rather than crashing or dropping the key
                rec[k] = repr(v)[:500]
        if tokens is not None and self._last_t is not None:
            dt = now - self._last_t
            steps = step - (self._last_step or 0)
            if dt > 0 and steps > 0:
                rec["tokens_per_sec_per_chip"] = (
                    tokens * steps / dt / self.n_chips)
        self._last_t, self._last_step = now, step
        self.writer(json.dumps(rec))
        return rec
