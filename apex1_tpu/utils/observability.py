"""Tracing / profiling / metrics — SURVEY.md §5.1, §5.5.

Reference: ``apex.pyprof`` monkey-patched every torch callable with
``torch.cuda.nvtx.range_push(json_args)`` so nsys timelines carry op names,
and post-processed profiler SQLite into per-kernel FLOPs/bytes
(``pyprof/prof``). ``apex/transformer`` threads an optional ``timers``
callable through the pipeline schedules.

TPU-native equivalents:
- `annotate` — ``jax.named_scope`` + ``jax.profiler.TraceAnnotation``
  (≙ nvtx ranges; names land in XLA HLO metadata AND the profiler trace).
- `trace` — context manager around ``jax.profiler.start_trace`` writing a
  TensorBoard-loadable trace (≙ running under nsys).
- `cost_analysis` — compile-time FLOPs/bytes attribution from XLA
  (≙ pyprof/prof's per-kernel FLOP counting, but exact and free).
- `Timers` — named wall-clock timers with device sync, the
  ``apex/transformer`` ``timers`` contract.
- `MetricsLogger` — per-step structured metrics (loss, grad-norm,
  loss-scale, skip-count, tokens/sec/chip — the BASELINE.json metric).

Since PR 10 both sit on the telemetry spine (`apex1_tpu.obs.spine`):
`Timers` is a thin adapter over the spine's `StopWatch` span primitive
(the ONE host-side timing implementation — serving and bench use the
same one), and `MetricsLogger` keeps its public surface but MIRRORS
every record into the run-scoped JSONL sink when ``APEX1_OBS_DIR`` is
set, so the examples' training loops join the same event stream as
bench/tuning/serving without touching their call sites.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Any, Callable, Optional

import jax
import numpy as np

from apex1_tpu.obs import spine


@contextlib.contextmanager
def annotate(name: str):
    """Name a region for both XLA metadata and profiler timelines."""
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a TensorBoard profiler trace of the enclosed block."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def cost_analysis(fn: Callable, *args, **kwargs) -> dict:
    """Compile ``fn`` (without running it) and return XLA's cost model:
    ``{"flops": ..., "bytes accessed": ..., "transcendentals": ...}``."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0]
    return dict(ca) if ca else {}


def flops_per_step(fn: Callable, *args, **kwargs) -> float:
    return float(cost_analysis(fn, *args, **kwargs).get("flops", 0.0))


class Timers:
    """Named cumulative timers (``timers("fwd").start()/.stop()``) — the
    calling convention ``apex/transformer`` schedules expect. ``stop``
    blocks on ``sync`` trees so device work is attributed correctly.
    Each timer IS a spine `StopWatch` (same primitive as
    `bench.timed_steps` and the serving clock), and ``log`` mirrors the
    read-out as spine counters when ``APEX1_OBS_DIR`` is set."""

    #: the spine primitive, re-exported under the historical name
    _Timer = spine.StopWatch

    def __init__(self):
        self._timers: dict[str, spine.StopWatch] = {}

    def __call__(self, name: str) -> spine.StopWatch:
        return self._timers.setdefault(name, spine.StopWatch())

    def log(self, names=None, *, reset: bool = True) -> dict[str, float]:
        names = list(self._timers) if names is None else names
        out = {}
        for n in names:
            if n not in self._timers:
                continue
            t = self._timers[n]
            count = t.count
            out[n] = t.elapsed(reset=reset)
            spine.emit("counter", f"timer.{n}", value=round(out[n], 6),
                       count=count)
        return out


class MetricsLogger:
    """Structured per-step metrics with tokens/sec/chip derivation.

    ``log(step, metrics, tokens=...)`` fetches scalars (one small transfer)
    and emits a JSON line via ``print`` or a supplied writer. Every
    record is ALSO mirrored into the telemetry spine's run file when
    ``APEX1_OBS_DIR`` is set (kind ``event``, name ``metrics``) — the
    training loops, serving lifecycle, and bench records then share one
    joinable stream (docs/observability.md)."""

    def __init__(self, writer: Optional[Callable[[str], None]] = None,
                 n_chips: Optional[int] = None):
        self.writer = writer or print
        self.n_chips = n_chips or jax.device_count()
        self._last_t: Optional[float] = None
        self._last_step: Optional[int] = None

    def log(self, step: int, metrics: dict, *,
            tokens: Optional[int] = None,
            _obs_name: Optional[str] = "metrics") -> dict:
        # _obs_name: spine event name for the mirror; None = caller
        # already emitted a structured spine event for this record
        # (serving.ServingMetrics) — suppress the generic one
        now = time.perf_counter()
        rec = {"step": int(step)}
        for k, v in metrics.items():
            if isinstance(v, (str, bool)):
                rec[k] = v
                continue
            try:
                arr = np.asarray(jax.device_get(v))
                if arr.size == 1 and arr.dtype != object:
                    rec[k] = float(arr)
                elif arr.dtype != object:
                    rec[k] = arr.tolist()  # vectors go in whole
                else:
                    raise TypeError("non-array metric")
            except (TypeError, ValueError):
                # arbitrary pytrees (e.g. train-step aux) — keep a
                # readable form rather than crashing or dropping the key
                rec[k] = repr(v)[:500]
        if tokens is not None and self._last_t is not None:
            dt = now - self._last_t
            steps = step - (self._last_step or 0)
            if dt > 0 and steps > 0:
                rec["tokens_per_sec_per_chip"] = (
                    tokens * steps / dt / self.n_chips)
        self._last_t, self._last_step = now, step
        self.writer(json.dumps(rec))
        if _obs_name:
            spine.emit("event", _obs_name, **rec)
        return rec
