"""Race / divergence / aliasing debug tools — SURVEY.md §5.2.

The reference's only artifact here is
``tests/distributed/DDP/ddp_race_condition_test.py`` (stressing the
grad-hook/allreduce overlap); CUDA-side correctness rests on manual
stream-ordering discipline. Under XLA the compiler owns scheduling, so the
remaining TPU failure modes are different, and each gets a tool:

- **cross-host program divergence** (ranks tracing different programs →
  mismatched collectives → hang): `program_fingerprint` hashes the jaxpr;
  `assert_same_program_across_processes` compares it across the cluster
  BEFORE launching the real computation — a hang turned into an assert.
- **donation/aliasing corruption** (``donate_argnums`` reusing a buffer
  the host still references): `assert_donation_safe` runs a step twice
  from bitwise-identical inputs and asserts identical outputs.
- **nondeterminism**: `enable_deterministic` flips the jax flags tests
  should run under (partitionable threefry; deterministic reductions are
  the TPU default).
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


def program_fingerprint(fn: Callable, *args, **kwargs) -> int:
    """Stable 63-bit hash of ``fn``'s traced jaxpr for these args."""
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    digest = hashlib.sha256(str(jaxpr).encode()).digest()
    return int.from_bytes(digest[:8], "big") >> 1


def assert_same_program_across_processes(fn: Callable, *args,
                                         **kwargs) -> int:
    """All processes must trace the same program (≙ the hang-preventing
    pre-flight check multi-controller JAX lacks). Single-process: no-op
    beyond returning the fingerprint."""
    fp = program_fingerprint(fn, *args, **kwargs)
    if jax.process_count() == 1:
        return fp
    from jax.experimental import multihost_utils

    # two uint32 halves: a 63-bit int overflows uint32-truncated jnp
    # arrays under default x64-disabled jax
    halves = jnp.asarray([fp >> 32, fp & 0xFFFFFFFF], jnp.uint32)
    fps = np.asarray(multihost_utils.process_allgather(halves))
    fps = fps.reshape(-1, 2)
    joined = [(int(hi) << 32) | int(lo) for hi, lo in fps]
    if any(j != joined[0] for j in joined):
        raise AssertionError(
            f"program divergence across processes: fingerprints "
            f"{[hex(j) for j in joined]} (process "
            f"{jax.process_index()} has {hex(fp)}) — ranks would issue "
            f"mismatched collectives and hang")
    return fp


def assert_donation_safe(step: Callable, *args, n_checks: int = 2,
                         rtol: float = 0.0, atol: float = 0.0) -> None:
    """Run ``step`` ``n_checks`` times from bitwise-identical copies of
    ``args``; any divergence means a donated/aliased buffer was consumed
    while still referenced (or nondeterminism). ≙ the reference's DDP
    race-condition test, for XLA's failure mode."""
    def copy_args():
        return jax.tree.map(
            lambda x: jnp.array(x, copy=True)
            if isinstance(x, jax.Array) else x, args)

    ref = None
    for i in range(n_checks):
        out = jax.tree.map(np.asarray, jax.device_get(step(*copy_args())))
        if ref is None:
            ref = out
            continue
        for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
            if not np.allclose(a, b, rtol=rtol, atol=atol):
                raise AssertionError(
                    "donation/aliasing corruption (or nondeterminism): "
                    f"run {i} diverged from run 0 by "
                    f"{np.max(np.abs(a.astype(np.float64) - b.astype(np.float64)))}")


def enable_deterministic() -> None:
    """Deterministic-run flags for tests (SURVEY §5.2c)."""
    jax.config.update("jax_threefry_partitionable", True)
