"""Multi-tenant LoRA adapter pages — the store beside the KV pool.

One deployed base model serves many tenants: each tenant's low-rank
LM-head adapter ``(A (H, r), B (r, V))`` lives as ``r`` PAGES in a pair
of device pools (``a_pages`` (P, H) / ``b_pages`` (P, V)), allocated by
the same refcounted :class:`~apex1_tpu.serving.kv_pool.PageAllocator`
the paged KV pool uses.  A serving slot carries a rank-length
block-table row of page ids, and `ops.lora_epilogue.lora_delta` streams
those pages into the decode matmul epilogue — the `ops.paged_decode`
indirection applied to adapter weights instead of K/V.

Page 0 is the ZERO page (all-zero payload, never allocated): a slot
with no adapter keeps an all-zero block-table row and its delta is an
exact ``0.0`` — LoRA-off slots ride the same executable, no retrace.

PUBLISH ORDER IS LOAD-BEARING (the APX202 fixture race, adapter-page
edition): `register` writes every page PAYLOAD first and publishes the
adapter's block-table row LAST.  A decode step that raced the register
either sees the old row (no pages of the new adapter) or the new row
over fully-written pages — never a torn row naming half-written pages.
The same discipline, inverted, protects teardown: `unregister` only
drops the registry's ref; pages free when the last in-flight slot
releases, so a decode step that already holds the row keeps reading
consistent payloads ("a page is freed only after nothing is still
reading it").

Scale folding: ``scale/r`` (the conventional ``alpha/r``) is folded
into the B payloads at register time, so serving-path math is exactly
``(h @ A) @ B`` with no per-step scalar traffic.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from apex1_tpu.serving.kv_pool import PageAllocator


class LoraAdapterStore:
    """Paged store of per-tenant LoRA LM-head adapters.

    ``register``/``unregister`` manage adapter lifetime; the engine
    calls ``acquire(adapter_id, slot)`` at admission (pins the pages,
    returns the slot's block-table row) and ``release(slot)`` at
    retirement.  All methods are host-side bookkeeping plus at most one
    device scatter per page — never on the decode step path.
    """

    def __init__(self, hidden: int, vocab: int, rank: int,
                 max_adapters: int, dtype=jnp.float32):
        if rank < 1:
            raise ValueError(f"LoRA rank must be >= 1, got {rank}")
        if max_adapters < 1:
            raise ValueError(
                f"max_adapters must be >= 1, got {max_adapters}")
        self.hidden = int(hidden)
        self.vocab = int(vocab)
        self.rank = int(rank)
        self.max_adapters = int(max_adapters)
        self.dtype = jnp.dtype(dtype)
        # +1 for the reserved zero page — sized so max_adapters full
        # registrations can never exhaust the pool (the KV pool's
        # no-page-faults sizing invariant)
        self.num_pages = 1 + self.max_adapters * self.rank
        self.a_pages = jnp.zeros((self.num_pages, self.hidden),
                                 self.dtype)
        self.b_pages = jnp.zeros((self.num_pages, self.vocab),
                                 self.dtype)
        self._alloc = PageAllocator(self.num_pages)
        self._adapters: Dict[str, Tuple[int, ...]] = {}
        self._slot_pages: Dict[int, Tuple[int, ...]] = {}

    # ---- registration ---------------------------------------------------

    def register(self, adapter_id: str, A, B, *,
                 scale: float = 1.0) -> Tuple[int, ...]:
        """Install adapter ``adapter_id``: ``A`` (H, r), ``B`` (r, V);
        ``scale/r`` is folded into the stored B pages.  Two-phase
        publish: page payloads land first, the adapter row publishes
        last (see module docstring). Returns the page ids."""
        A = np.asarray(A)
        B = np.asarray(B)
        if A.shape != (self.hidden, self.rank):
            raise ValueError(
                f"adapter {adapter_id!r}: A shape {A.shape} != "
                f"({self.hidden}, {self.rank})")
        if B.shape != (self.rank, self.vocab):
            raise ValueError(
                f"adapter {adapter_id!r}: B shape {B.shape} != "
                f"({self.rank}, {self.vocab})")
        if adapter_id in self._adapters:
            raise ValueError(
                f"adapter {adapter_id!r} already registered — "
                f"unregister first (in-flight slots keep their pages)")
        pages = tuple(self._alloc.take() for _ in range(self.rank))
        a_rows = jnp.asarray(A.T, self.dtype)                 # (r, H)
        b_rows = jnp.asarray(B, self.dtype) * jnp.asarray(
            scale / self.rank, self.dtype)                    # (r, V)
        # phase 1: page payloads (device scatters, one per rank page)
        for j, pid in enumerate(pages):
            self.a_pages = self.a_pages.at[pid].set(a_rows[j])
            self.b_pages = self.b_pages.at[pid].set(b_rows[j])
        # phase 2: publish — nothing could name these pages before now
        self._adapters[adapter_id] = pages
        return pages

    def unregister(self, adapter_id: str) -> None:
        """Drop the registry's ref.  Pages with in-flight slot refs
        stay readable until the last `release`; fully-unreferenced
        pages return to the free list (payloads are overwritten by the
        next `register`, so no zeroing scatter is needed — page 0 alone
        carries the always-zero contract)."""
        pages = self._adapters.pop(adapter_id, None)
        if pages is None:
            raise KeyError(f"adapter {adapter_id!r} not registered")
        for pid in pages:
            self._alloc.unref(pid)

    def has(self, adapter_id: Optional[str]) -> bool:
        return adapter_id is not None and adapter_id in self._adapters

    # ---- per-slot lifetime ----------------------------------------------

    def acquire(self, adapter_id: Optional[str],
                slot: int) -> Tuple[np.ndarray, bool]:
        """Pin ``adapter_id``'s pages for ``slot``; returns the slot's
        ``(rank,)`` int32 block-table row and an on-flag.  An unknown
        or ``None`` adapter yields the all-zero row (page 0) and
        ``False`` — adapterless requests are the same code path."""
        if slot in self._slot_pages:
            raise ValueError(f"slot {slot} already holds adapter pages")
        if not self.has(adapter_id):
            return np.zeros((self.rank,), np.int32), False
        pages = self._adapters[adapter_id]
        for pid in pages:
            self._alloc.ref(pid)
        self._slot_pages[slot] = pages
        return np.asarray(pages, np.int32), True

    def release(self, slot: int) -> None:
        """Unpin whatever ``slot`` acquired (no-op for adapterless
        slots — they never entered ``_slot_pages``)."""
        pages = self._slot_pages.pop(slot, None)
        if pages is None:
            return
        for pid in pages:
            self._alloc.unref(pid)

    @property
    def n_free(self) -> int:
        return self._alloc.n_free

    def page_refcount(self, pid: int) -> int:
        return self._alloc.refs[pid]


def _drill() -> int:
    """Standalone multi-tenant token-parity drill (tools/check_all.sh):
    one engine batch mixing LoRA-on slots across two adapters with a
    LoRA-off slot must emit streams BIT-IDENTICAL to per-tenant solo
    runs of the same requests.  Exercises the full integration — store,
    admission acquire/release, and the fused epilogue in both the
    prefill and decode executables."""
    import jax

    from apex1_tpu.models.llama import Llama, LlamaConfig
    from apex1_tpu.models.generate import llama_decoder
    from apex1_tpu.serving.engine import Engine, EngineConfig

    cfg = LlamaConfig(vocab_size=97, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, ffn_size=64,
                      max_seq_len=64)
    model = Llama(cfg)
    rng = jax.random.key(0)
    params = model.init(rng, jnp.zeros((1, 4), jnp.int32))["params"]
    apply_fn, make_cache = llama_decoder(model)

    rank = 2
    k = jax.random.key(1)
    adapters = {}
    for name in ("tenant-a", "tenant-b"):
        k, ka, kb = jax.random.split(k, 3)
        adapters[name] = (
            jax.random.normal(ka, (cfg.hidden_size, rank)) * 0.2,
            jax.random.normal(kb, (rank, cfg.vocab_size)) * 0.2)

    prompts = {101: ([3, 1, 4, 1, 5], "tenant-a"),
               102: ([2, 7, 1, 8], "tenant-b"),
               103: ([3, 1, 4, 1, 5], None)}       # adapterless control

    def run(active, paged=False):
        eng = Engine(apply_fn, make_cache, params,
                     EngineConfig(max_slots=4, max_len=32,
                                  prefill_chunk=4, temperature=0.7,
                                  seed=7, lora_rank=rank,
                                  lora_max_adapters=4, paged=paged),
                     lora_head=params["output"])
        for name, (A, B) in adapters.items():
            eng.register_adapter(name, A, B, scale=2.0)
        for rid, (toks, tenant) in prompts.items():
            if rid in active:
                eng.submit(np.asarray(toks, np.int32), 8, req_id=rid,
                           tenant=tenant, seed=1000 + rid)
        eng.run(max_steps=64)
        return {rid: eng.results[rid].tokens.tolist() for rid in active}

    mixed = run(set(prompts))
    solo = {}
    for rid in prompts:
        solo.update(run({rid}))

    ok = True
    for rid in prompts:
        match = mixed[rid] == solo[rid]
        ok &= match
        print(f"req {rid} (tenant={prompts[rid][1]}): mixed "
              f"{mixed[rid]} vs solo {solo[rid]} -> "
              f"{'OK' if match else 'MISMATCH'}")
    # the two tenants share a prompt with the control — adapters must
    # actually change the stream or the drill proves nothing
    if mixed[101] == mixed[103]:
        print("WARNING: tenant-a stream equals adapterless stream — "
              "adapter had no effect")
        ok = False

    # the paged engine routes the adapter delta through the fused
    # `ops.lora_epilogue.lora_delta` kernel (interpret on CPU, real
    # Mosaic on TPU) — the kernel path must be invisible in the tokens
    from apex1_tpu.ops import _common
    with _common.force_impl("pallas"):
        paged_mixed = run(set(prompts), paged=True)
    kmatch = paged_mixed == mixed
    ok &= kmatch
    print(f"paged-kernel epilogue vs dense: "
          f"{'OK' if kmatch else 'MISMATCH'}")
    print("multi-tenant parity drill:", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    import sys
    sys.exit(_drill())
