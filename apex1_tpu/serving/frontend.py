"""Fault-tolerant multi-replica serving front — N supervised engine
replicas behind ONE submit/poll surface (ROADMAP item 2(d), built
through the robustness lens: scale and fault tolerance as one design).

- **Routing**: least-loaded replica, gated by a deadline FEASIBILITY
  check (load x smoothed step time vs time-to-deadline — an estimate,
  never a guarantee; an infeasible deadline is rejected at the door
  with ``retry_after_s=0`` rather than admitted to fail).
- **QoS admission**: per-tenant classes (`scheduler.QOS_CLASSES`).
  At frontend capacity, a guaranteed request displaces the youngest
  sheddable in-flight request (cancelled — the engine releases its KV
  slot immediately — and finished as evicted/"shed"); anything else
  gets a structured `Backpressure` (queue depth + retry-after floor).
- **Failover**: a dead replica is restarted with in-flight
  resubmission by its supervisor; once its restart budget is spent
  (``failed``) the frontend drains its in-flight submissions and
  re-routes them to surviving replicas. Stable ids + pinned seeds make
  both paths regenerate token-identical streams.
- **Hedged dispatch**: a guaranteed-class request with no result past
  its TTFT budget is duplicated to a second replica; first terminal
  result wins, the loser is cancelled. Hedging bounds TAIL latency
  against a slow/wedged replica — it does NOT add capacity (it spends
  it), and both executions produce the same tokens by construction, so
  the race has one observable winner and zero observable variance.
- **Degraded modes**: sustained overload walks ``normal → shedding →
  degraded`` (and back). Shedding cancels sheddable-class load first;
  degraded additionally caps new admissions' ``max_new_tokens`` to the
  `DegradeProfile` and (when the engine factory accepts
  ``cache_dtype``) restarts future replicas on the quantized-KV
  profile — pressure relief instead of hard failure. EVERY transition
  is banked as a JSON event through `ServingMetrics.transition`.

Drive modes mirror `ReplicaSupervisor`: `start()` + threaded
replicas for production/bench, `pump()` inline for deterministic
tier-1 drills. `pump` is also the supervision tick in threaded mode
(watchdogs, restarts, hedges, mode transitions, result collection).
"""

from __future__ import annotations

import dataclasses
import inspect
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from apex1_tpu.serving.engine import Engine, RequestResult, \
    derive_request_seed
from apex1_tpu.serving.metrics import ServingMetrics
from apex1_tpu.serving.replica import (ReplicaConfig, ReplicaSupervisor,
                                       Submission)
from apex1_tpu.serving.scheduler import (Backpressure, new_request_id,
                                         qos_rank)

#: overload modes, escalation order
MODES = ("normal", "shedding", "degraded")


@dataclasses.dataclass
class DegradeProfile:
    """The pressure-relief admission profile: what the frontend trades
    away under sustained overload instead of hard-failing."""

    max_new_tokens_cap: int = 32
    cache_dtype: Optional[object] = None   # e.g. jnp.int8 — applied to
    #  replicas (re)built while degraded, when make_engine accepts
    #  cache_dtype (the int8-KV machinery of ops/quantized.py rides the
    #  pool's existing dtype knob); None = length-cap only


@dataclasses.dataclass
class FrontendConfig:
    """Router + admission knobs. Load fractions are measured against
    ``n_alive_replicas * capacity_per_replica`` (in-flight requests a
    replica absorbs: engine slots + queue)."""

    n_replicas: int = 2
    capacity_per_replica: int = 16
    seed: int = 0                  # base for derived per-request seeds
    hedge_after_s: float = 0.25    # guaranteed-class TTFT budget before
    #                                a hedge fires (None disables)
    enter_shed: float = 0.75       # load fraction -> shedding
    enter_degraded: float = 0.95   # load fraction -> degraded
    exit_overload: float = 0.5     # load fraction to step back down
    sustain_rounds: int = 3        # consecutive pump rounds to flip
    degrade: DegradeProfile = dataclasses.field(
        default_factory=DegradeProfile)
    replica: ReplicaConfig = dataclasses.field(
        default_factory=ReplicaConfig)
    retry_after_s: float = 0.05    # frontend 429 backoff floor base


class ServingFrontend:
    """N supervised replicas behind one submit/poll surface.

    ``make_engine() -> Engine`` builds ONE replica's engine (fresh per
    restart). Give every replica the same params/config — routing and
    failover assume replicas are interchangeable. If the factory
    accepts a ``cache_dtype`` kwarg, degraded-mode restarts pass the
    profile's quantized-KV dtype through it.
    """

    def __init__(self, make_engine: Callable[..., Engine],
                 config: Optional[FrontendConfig] = None, *,
                 metrics: Optional[ServingMetrics] = None,
                 fault=None):
        self.cfg = cfg = config or FrontendConfig()
        if cfg.n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self.metrics = metrics or ServingMetrics()
        self._make_engine = make_engine
        self._takes_cache_dtype = "cache_dtype" in \
            inspect.signature(make_engine).parameters
        self.mode = "normal"
        self._above = 0                      # sustained-overload counters
        self._below = 0
        self.replicas: List[ReplicaSupervisor] = [
            ReplicaSupervisor(self._build_engine, i, config=cfg.replica,
                              metrics=self.metrics, fault=fault,
                              seed=cfg.seed)
            for i in range(cfg.n_replicas)]
        self._subs: Dict[int, Submission] = {}      # all accepted, by id
        self._live: set = set()                     # accepted, not terminal
        self._route: Dict[int, List[int]] = {}      # rid -> replica ids
        self._shed_rids: set = set()                # relabel cancelled->shed
        self._hedged: set = set()
        self._terminal: Dict[int, RequestResult] = {}
        self._threaded = False

    # ---- lifecycle ------------------------------------------------------

    def start(self) -> "ServingFrontend":
        """Spawn every replica's serve thread (production mode); keep
        calling `pump()` as the supervision tick."""
        self._threaded = True
        for rep in self.replicas:
            rep.start()
        return self

    def stop(self) -> None:
        for rep in self.replicas:
            rep.stop()

    # ---- submission -----------------------------------------------------

    def submit(self, tokens, max_new_tokens: int, *,
               qos: str = "best_effort", tenant: Optional[str] = None,
               deadline: Optional[float] = None, prefix=None,
               seed: Optional[int] = None,
               req_id: Optional[int] = None) -> int:
        """Admit + route one request; returns its id (poll with it).
        Raises `Backpressure` (structured) when admission control says
        no: frontend at capacity with nothing sheddable, sheddable
        class refused while shedding/degraded, or no replica can
        feasibly meet the deadline."""
        qos_rank(qos)                        # validate loudly
        now = time.monotonic()
        rid = new_request_id() if req_id is None else int(req_id)
        if seed is None:
            # pinned HERE, not per engine: failover must regenerate the
            # identical stream on ANY replica
            seed = derive_request_seed(self.cfg.seed, rid)
        seed = int(seed) & 0x7FFFFFFF    # int32 counter-key contract
        if self.mode in ("shedding", "degraded") and qos == "sheddable":
            raise Backpressure(
                f"{self.mode}: sheddable admissions refused",
                queue_depth=self.total_inflight,
                retry_after_s=self._retry_after())
        if self.mode == "degraded":
            capped = min(int(max_new_tokens),
                         self.cfg.degrade.max_new_tokens_cap)
            if capped < int(max_new_tokens):
                self.metrics.incr("degraded_admissions")
            max_new_tokens = capped
        # feasibility BEFORE displacement: an admission that is going
        # to be rejected as infeasible must not first evict an
        # innocent sheddable victim for nothing (review finding)
        rep = self._pick_replica(max_new_tokens, deadline, now)
        if rep is None:
            raise Backpressure(
                "no replica can feasibly meet the deadline",
                queue_depth=self.total_inflight, retry_after_s=0.0)
        if self.total_inflight >= self.capacity:
            if qos == "guaranteed" and self._displace_sheddable():
                pass                         # freed a unit of capacity
            else:
                raise Backpressure(
                    f"frontend at capacity ({self.capacity})",
                    queue_depth=self.total_inflight,
                    retry_after_s=self._retry_after())
        sub = Submission(
            tokens=np.asarray(tokens, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens), req_id=rid,
            seed=int(seed), prefix=prefix, deadline=deadline, qos=qos,
            tenant=tenant, submitted_at=now)
        self._subs[rid] = sub
        self._live.add(rid)
        self._route[rid] = [rep.replica_id]
        rep.submit_sub(sub)
        return rid

    def cancel(self, req_id: int) -> bool:
        if req_id in self._terminal:
            return False
        routed = self._route.get(req_id)
        if not routed:
            return False
        for r in routed:
            self.replicas[r].cancel(req_id)
        return True

    # ---- results --------------------------------------------------------

    def poll(self, req_id: int) -> Optional[RequestResult]:
        """Terminal result, or None while in flight. (Collection
        happens in `pump`; poll only reads.)"""
        return self._terminal.get(req_id)

    def pop_result(self, req_id: int) -> Optional[RequestResult]:
        """Remove and return a terminal result, dropping every trace of
        the request — the long-running server's pressure valve (pair
        with `metrics.drain()`); `_terminal`/`_subs` are otherwise
        bounded only by requests ever served."""
        res = self._terminal.pop(req_id, None)
        if res is not None:
            self._subs.pop(req_id, None)
            self._shed_rids.discard(req_id)
            self._hedged.discard(req_id)
            self._route.pop(req_id, None)
        return res

    @property
    def results(self) -> Dict[int, RequestResult]:
        return dict(self._terminal)

    # ---- the supervision tick -------------------------------------------

    def pump(self, rounds: int = 1) -> None:
        """One supervision round x ``rounds``: drive replicas (inline
        mode), fire watchdogs, restart/fail-over dead replicas, collect
        results, hedge blown TTFT budgets, walk the overload ladder."""
        for _ in range(rounds):
            for rep in self.replicas:
                if self._threaded:
                    rep.check()
                elif rep.state in ("new", "alive"):
                    rep.pump(1)
            self._recover_dead()
            self._collect()
            self._hedge_blown_budgets()
            self._update_mode()
            if self._threaded:
                time.sleep(0.001)            # supervision cadence, not
        #                                      the serve loop's

    def run_until_drained(self, *, timeout_s: float = 60.0,
                          max_rounds: int = 100_000
                          ) -> Dict[int, RequestResult]:
        """Pump until every accepted request is terminal (drills /
        benches). Raises on timeout — a drained=False return would just
        get asserted anyway."""
        t0 = time.monotonic()
        for _ in range(max_rounds):
            if not self._live:
                return self.results
            if time.monotonic() - t0 > timeout_s:
                break
            self.pump()
        if self._live:
            raise TimeoutError(
                f"undrained after {time.monotonic() - t0:.1f}s "
                f"(budget {timeout_s}s/{max_rounds} rounds): "
                f"{sorted(self._live)} "
                f"(states: {[r.state for r in self.replicas]})")
        return self.results

    # ---- internals ------------------------------------------------------

    @property
    def capacity(self) -> int:
        n_live = sum(r.state in ("new", "alive") for r in self.replicas)
        return max(1, n_live) * self.cfg.capacity_per_replica

    @property
    def total_inflight(self) -> int:
        return len(self._live)

    def _retry_after(self) -> float:
        return self.cfg.retry_after_s * max(
            1.0, self.total_inflight / self.capacity)

    def _build_engine(self) -> Engine:
        prof = self.cfg.degrade
        if (self.mode == "degraded" and self._takes_cache_dtype
                and prof.cache_dtype is not None):
            return self._make_engine(cache_dtype=prof.cache_dtype)
        return self._make_engine()

    def _alive(self) -> List[ReplicaSupervisor]:
        return [r for r in self.replicas if r.state in ("new", "alive")]

    def _pick_replica(self, max_new_tokens: int,
                      deadline: Optional[float], now: float
                      ) -> Optional[ReplicaSupervisor]:
        """Least-loaded alive replica passing the deadline-feasibility
        estimate; least-loaded overall when the deadline is None or no
        replica has timing history yet."""
        alive = self._alive()
        if not alive:
            return None
        ranked = sorted(alive, key=lambda r: (r.load, r.replica_id))
        if deadline is None:
            return ranked[0]
        left = deadline - now
        for rep in ranked:
            est = (rep.load + 1) * max_new_tokens * rep.step_ewma
            if rep.step_ewma == 0.0 or est <= left:
                return rep
        return None

    def _displace_sheddable(self) -> bool:
        """Shed the YOUNGEST in-flight sheddable request to admit a
        guaranteed one — the QoS contract's teeth: sheddable capacity
        is borrowed, guaranteed capacity is owed. A victim already
        being shed (cancelled, result not yet collected) is skipped —
        it must not 'free' the same unit of capacity twice under a
        guaranteed burst (review finding)."""
        victim = None
        for rid in self._live:
            sub = self._subs[rid]
            if sub.qos != "sheddable" or rid in self._shed_rids:
                continue
            if victim is None or sub.submitted_at > victim.submitted_at:
                victim = sub
        if victim is None:
            return False
        self._shed(victim, "shed (displaced by guaranteed)")
        return True

    def _shed(self, sub: Submission, reason: str):
        self._shed_rids.add(sub.req_id)
        self.metrics.incr("sheds")
        self.metrics.transition("shed", req=sub.req_id, qos=sub.qos,
                                reason=reason)
        for r in self._route.get(sub.req_id, []):
            self.replicas[r].cancel(sub.req_id)

    def _recover_dead(self):
        for rep in self.replicas:
            if rep.state != "dead":
                continue
            if not rep.restart():
                # budget spent: fail over its in-flight work
                subs = rep.drain_inflight()
                targets = self._alive()
                for sub in subs:
                    # a hedge leg may already be running elsewhere —
                    # re-routing would double-decode the same id on
                    # one engine; dropping the failed leg suffices
                    others = [r for r in self._route.get(sub.req_id, [])
                              if r != rep.replica_id
                              and self.replicas[r].state
                              in ("new", "alive")]
                    if others:
                        continue
                    if not targets:
                        self._terminal[sub.req_id] = RequestResult(
                            req_id=sub.req_id, status="evicted",
                            tokens=np.zeros((0,), np.int32),
                            reason="no surviving replicas")
                        self._live.discard(sub.req_id)
                        continue
                    tgt = min(targets,
                              key=lambda r: (r.load, r.replica_id))
                    self._route.setdefault(sub.req_id, []).append(
                        tgt.replica_id)
                    tgt.submit_sub(sub)
                    self.metrics.incr("retries")
                self.metrics.transition(
                    "failover", source=rep.replica_id,
                    rerouted=[s.req_id for s in subs])

    def _collect(self):
        # sweep settled hedge/cancel races: a loser leg publishes its
        # cancelled result an iteration AFTER the winner was collected —
        # keep draining until every leg has either yielded its result
        # or provably never will (nothing pending in that supervisor),
        # THEN drop the route entry; deleting earlier would strand the
        # late result in the supervisor's dict forever (review finding)
        for rid in [r for r in self._route if r in self._terminal]:
            if all(self.replicas[r].pop_result(rid) is not None
                   or not self.replicas[r].pending(rid)
                   for r in self._route[rid]):
                del self._route[rid]
        for rid in list(self._live):
            for r in self._route.get(rid, []):
                res = self.replicas[r].pop_result(rid)
                if res is None:
                    continue
                if rid in self._shed_rids and res.status == "cancelled":
                    res = dataclasses.replace(
                        res, status="evicted", reason="shed (overload)")
                self._terminal[rid] = res
                self._live.discard(rid)
                # hedge race settled: cancel the other leg(s)
                for other in self._route.get(rid, []):
                    if other != r:
                        self.replicas[other].cancel(rid)
                        self.replicas[other].pop_result(rid)
                if rid in self._hedged and r != self._route[rid][0]:
                    self.metrics.incr("hedges_won")
                break

    def _hedge_blown_budgets(self):
        if self.cfg.hedge_after_s is None:
            return
        now = time.monotonic()
        for rid in list(self._live):
            sub = self._subs[rid]
            if sub.qos != "guaranteed" or rid in self._hedged:
                continue
            if now - sub.submitted_at <= self.cfg.hedge_after_s:
                continue
            routed = set(self._route[rid])
            # the budget is a TTFT budget: a primary that has already
            # streamed the first token is slow-but-healthy, and a
            # duplicate full decode would burn the very capacity
            # hedging protects — hedge only while NO leg has produced
            # a first token (review finding)
            if any(self.replicas[r].first_token_seen(rid)
                   for r in routed):
                continue
            # exclude EVERY replica already on the route (a failover
            # may have appended the survivor) — hedging onto a replica
            # that already serves the request would double-decode it
            # (review finding)
            primary = self._route[rid][0]
            others = [r for r in self._alive()
                      if r.replica_id not in routed]
            if not others:
                continue
            tgt = min(others, key=lambda r: (r.load, r.replica_id))
            self._hedged.add(rid)
            self._route[rid].append(tgt.replica_id)
            tgt.submit_sub(sub)
            self.metrics.incr("hedges_fired")
            self.metrics.transition("hedge", req=rid, primary=primary,
                                    secondary=tgt.replica_id)

    def _update_mode(self):
        """The overload ladder. Escalation requires the load fraction
        to hold above the threshold for ``sustain_rounds`` consecutive
        pump rounds (a burst is not an overload); de-escalation is
        symmetric. Every flip is banked."""
        frac = self.total_inflight / self.capacity
        cfg = self.cfg
        enter = (cfg.enter_shed if self.mode == "normal"
                 else cfg.enter_degraded)
        if self.mode != "degraded" and frac >= enter:
            self._above += 1
        else:
            self._above = 0
        if self.mode != "normal" and frac <= cfg.exit_overload:
            self._below += 1
        else:
            self._below = 0
        if self._above >= cfg.sustain_rounds:
            nxt = MODES[MODES.index(self.mode) + 1]
            self._flip_mode(nxt, frac)
            self._above = 0
            if nxt == "shedding":
                # first relief valve: sheddable-class load goes first
                for rid in list(self._live):
                    sub = self._subs[rid]
                    if (sub.qos == "sheddable"
                            and rid not in self._shed_rids):
                        self._shed(sub, "shed (overload)")
        elif self._below >= cfg.sustain_rounds:
            self._flip_mode("normal", frac)
            self._below = 0

    def _flip_mode(self, new_mode: str, frac: float):
        old, self.mode = self.mode, new_mode
        fields = dict(frm=old, to=new_mode, load_fraction=round(frac, 4),
                      inflight=self.total_inflight,
                      capacity=self.capacity)
        if new_mode == "degraded":
            fields["max_new_tokens_cap"] = \
                self.cfg.degrade.max_new_tokens_cap
            fields["cache_dtype"] = str(self.cfg.degrade.cache_dtype)
        self.metrics.transition("mode", **fields)

    # ---- introspection --------------------------------------------------

    def replica_states(self) -> List[str]:
        return [r.state for r in self.replicas]

    def summary(self) -> dict:
        s = self.metrics.summary()
        s["mode"] = self.mode
        s["replicas"] = {
            r.replica_id: {"state": r.state, "restarts": r.restarts,
                           "generation": r.generation,
                           "engines_built": r.engines_built,
                           "steps": r.steps}
            for r in self.replicas}
        return s
