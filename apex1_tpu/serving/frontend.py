"""Fault-tolerant multi-replica serving front — N supervised engine
replicas behind ONE submit/poll surface (ROADMAP item 2(d), built
through the robustness lens: scale and fault tolerance as one design).

- **Routing**: least-loaded replica, gated by a deadline FEASIBILITY
  check (load x smoothed step time vs time-to-deadline — an estimate,
  never a guarantee; an infeasible deadline is rejected at the door
  with ``retry_after_s=0`` rather than admitted to fail).
- **QoS admission**: per-tenant classes (`scheduler.QOS_CLASSES`).
  At frontend capacity, a guaranteed request displaces the youngest
  sheddable in-flight request (cancelled — the engine releases its KV
  slot immediately — and finished as evicted/"shed"); anything else
  gets a structured `Backpressure` (queue depth + retry-after floor).
- **Failover**: a dead replica is restarted with in-flight
  resubmission by its supervisor; once its restart budget is spent
  (``failed``) the frontend drains its in-flight submissions and
  re-routes them to surviving replicas. Stable ids + pinned seeds make
  both paths regenerate token-identical streams.
- **Hedged dispatch**: a guaranteed-class request with no result past
  its TTFT budget is duplicated to a second replica; first terminal
  result wins, the loser is cancelled. Hedging bounds TAIL latency
  against a slow/wedged replica — it does NOT add capacity (it spends
  it), and both executions produce the same tokens by construction, so
  the race has one observable winner and zero observable variance.
- **Degraded modes**: sustained overload walks ``normal → shedding →
  degraded`` (and back). Shedding cancels sheddable-class load first;
  degraded additionally caps new admissions' ``max_new_tokens`` to the
  `DegradeProfile` and (when the engine factory accepts
  ``cache_dtype``) restarts future replicas on the quantized-KV
  profile — pressure relief instead of hard failure. EVERY transition
  is banked as a JSON event through `ServingMetrics.transition`.

Drive modes mirror `ReplicaSupervisor`: `start()` + threaded
replicas for production/bench, `pump()` inline for deterministic
tier-1 drills. `pump` is also the supervision tick in threaded mode
(watchdogs, restarts, hedges, mode transitions, result collection).

**Actuation surface** (docs/autopilot.md): the knobs a controller —
`apex1_tpu.autopilot` — turns at runtime, every call banked as a
transition with its caller (`by=`) and evidence attached:

- `add_replica()` / `retire_replica()` — elastic fleet size. A
  retiring replica takes no new routes, drains its in-flight work,
  then stops; its slot in ``replicas`` stays (ids are route indices).
- `set_mode()` — external overload-ladder control. With
  ``FrontendConfig.mode_control="external"`` the built-in
  load-fraction ladder is off and transitions are driven by whatever
  signal the controller watches (per-class latency percentiles, not
  raw queue depth).
- `set_admission_limit()` — admission setpoint: caps `capacity`
  below the structural ``n_alive * capacity_per_replica``.
- `set_hedge_budget()` — per-tenant TTFT/hedge budgets fit from
  measured distributions (falls back to ``cfg.hedge_after_s``).

The frontend also RECORDS every accepted request's lifecycle
(queued → first_token → terminal) into its own shared
`ServingMetrics`, so `summary()["window"]` carries the rolling
per-class percentiles the controller consumes — engine-level metrics
stay per-replica and are not aggregated here. ``clock`` is injectable
(`testing.fleetsim` passes virtual time for deterministic replay).
"""

from __future__ import annotations

import dataclasses
import inspect
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from apex1_tpu.serving.engine import Engine, RequestResult, \
    derive_request_seed
from apex1_tpu.serving.metrics import TERMINAL, ServingMetrics
from apex1_tpu.serving.replica import (ReplicaConfig, ReplicaSupervisor,
                                       Submission)
from apex1_tpu.serving.scheduler import (Backpressure, new_request_id,
                                         qos_rank)

#: overload modes, escalation order
MODES = ("normal", "shedding", "degraded")


@dataclasses.dataclass
class DegradeProfile:
    """The pressure-relief admission profile: what the frontend trades
    away under sustained overload instead of hard-failing."""

    max_new_tokens_cap: int = 32
    cache_dtype: Optional[object] = None   # e.g. jnp.int8 — applied to
    #  replicas (re)built while degraded, when make_engine accepts
    #  cache_dtype (the int8-KV machinery of ops/quantized.py rides the
    #  pool's existing dtype knob); None = length-cap only


@dataclasses.dataclass
class FrontendConfig:
    """Router + admission knobs. Load fractions are measured against
    ``n_alive_replicas * capacity_per_replica`` (in-flight requests a
    replica absorbs: engine slots + queue)."""

    n_replicas: int = 2
    capacity_per_replica: int = 16
    seed: int = 0                  # base for derived per-request seeds
    hedge_after_s: float = 0.25    # guaranteed-class TTFT budget before
    #                                a hedge fires (None disables)
    enter_shed: float = 0.75       # load fraction -> shedding
    enter_degraded: float = 0.95   # load fraction -> degraded
    exit_overload: float = 0.5     # load fraction to step back down
    sustain_rounds: int = 3        # consecutive pump rounds to flip
    degrade: DegradeProfile = dataclasses.field(
        default_factory=DegradeProfile)
    replica: ReplicaConfig = dataclasses.field(
        default_factory=ReplicaConfig)
    retry_after_s: float = 0.05    # frontend 429 backoff floor base
    mode_control: str = "load"     # "load" = the built-in load-fraction
    #  ladder walks modes; "external" = ONLY set_mode() flips them (an
    #  attached autopilot drives transitions from latency percentiles)
    metrics_window: int = 128      # rolling-percentile ring size for a
    #                                frontend-constructed ServingMetrics
    cache_dtype: Optional[object] = None  # STEADY-STATE KV tier for
    #  every replica build (e.g. jnp.int8 — half the bytes/slot buys
    #  ~2x resident batch for the same HBM; docs/serving.md § int8
    #  capacity tier). Distinct from DegradeProfile.cache_dtype, which
    #  only kicks in for replicas (re)built while degraded and takes
    #  precedence there. Requires a make_engine that accepts
    #  ``cache_dtype``; silently unused otherwise (same rule as the
    #  degrade profile).


class ServingFrontend:
    """N supervised replicas behind one submit/poll surface.

    ``make_engine() -> Engine`` builds ONE replica's engine (fresh per
    restart). Give every replica the same params/config — routing and
    failover assume replicas are interchangeable. If the factory
    accepts a ``cache_dtype`` kwarg, degraded-mode restarts pass the
    profile's quantized-KV dtype through it.
    """

    def __init__(self, make_engine: Callable[..., Engine],
                 config: Optional[FrontendConfig] = None, *,
                 metrics: Optional[ServingMetrics] = None,
                 fault=None,
                 clock: Optional[Callable[[], float]] = None):
        self.cfg = cfg = config or FrontendConfig()
        if cfg.n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        if cfg.mode_control not in ("load", "external"):
            raise ValueError(
                f"mode_control must be 'load' or 'external', "
                f"got {cfg.mode_control!r}")
        # instance state seeded from the config: attaching an Autopilot
        # flips THIS frontend to external control without mutating a
        # (possibly shared) FrontendConfig object
        self.mode_control = cfg.mode_control
        self.clock = clock or time.monotonic
        self.metrics = metrics or ServingMetrics(
            window=cfg.metrics_window, clock=self.clock)
        self._make_engine = make_engine
        self._fault = fault
        self._takes_cache_dtype = "cache_dtype" in \
            inspect.signature(make_engine).parameters
        self.mode = "normal"
        self._above = 0                      # sustained-overload counters
        self._below = 0
        self.replicas: List[ReplicaSupervisor] = []
        self._rep_counters: Dict[int, Dict[str, int]] = {}
        for _ in range(cfg.n_replicas):
            self._new_replica()
        self._subs: Dict[int, Submission] = {}      # all accepted, by id
        self._live: set = set()                     # accepted, not terminal
        self._route: Dict[int, List[int]] = {}      # rid -> replica ids
        self._shed_rids: set = set()                # relabel cancelled->shed
        self._hedged: set = set()
        self._ttft_marked: set = set()              # first_token recorded
        self._retiring: set = set()                 # replica ids draining
        self._admission_limit: Optional[int] = None
        self._hedge_budgets: Dict[Optional[str], Optional[float]] = {}
        self._terminal: Dict[int, RequestResult] = {}
        self._threaded = False

    def _new_replica(self) -> ReplicaSupervisor:
        rep = ReplicaSupervisor(
            self._build_engine, len(self.replicas),
            config=self.cfg.replica, metrics=self.metrics,
            fault=self._fault, seed=self.cfg.seed, clock=self.clock)
        self.replicas.append(rep)
        self._rep_counters[rep.replica_id] = {"hedges": 0, "sheds": 0}
        return rep

    # ---- lifecycle ------------------------------------------------------

    def start(self) -> "ServingFrontend":
        """Spawn every replica's serve thread (production mode); keep
        calling `pump()` as the supervision tick."""
        self._threaded = True
        for rep in self.replicas:
            rep.start()
        return self

    def stop(self) -> None:
        for rep in self.replicas:
            rep.stop()

    # ---- submission -----------------------------------------------------

    def submit(self, tokens, max_new_tokens: int, *,
               qos: str = "best_effort", tenant: Optional[str] = None,
               deadline: Optional[float] = None, prefix=None,
               seed: Optional[int] = None,
               req_id: Optional[int] = None) -> int:
        """Admit + route one request; returns its id (poll with it).
        Raises `Backpressure` (structured) when admission control says
        no: frontend at capacity with nothing sheddable, sheddable
        class refused while shedding/degraded, or no replica can
        feasibly meet the deadline."""
        qos_rank(qos)                        # validate loudly
        now = self.clock()
        rid = new_request_id() if req_id is None else int(req_id)
        if seed is None:
            # pinned HERE, not per engine: failover must regenerate the
            # identical stream on ANY replica
            seed = derive_request_seed(self.cfg.seed, rid)
        seed = int(seed) & 0x7FFFFFFF    # int32 counter-key contract
        if self.mode in ("shedding", "degraded") and qos == "sheddable":
            raise self._reject(
                rid, now, qos, tenant,
                f"{self.mode}: sheddable admissions refused",
                retry_after_s=self._retry_after())
        if self.mode == "degraded":
            capped = min(int(max_new_tokens),
                         self.cfg.degrade.max_new_tokens_cap)
            if capped < int(max_new_tokens):
                self.metrics.incr("degraded_admissions")
            max_new_tokens = capped
        # feasibility BEFORE displacement: an admission that is going
        # to be rejected as infeasible must not first evict an
        # innocent sheddable victim for nothing (review finding)
        rep = self._pick_replica(max_new_tokens, deadline, now)
        if rep is None:
            raise self._reject(
                rid, now, qos, tenant,
                "no replica can feasibly meet the deadline",
                retry_after_s=0.0)
        if self.total_inflight >= self.capacity:
            if qos == "guaranteed" and self._displace_sheddable():
                pass                         # freed a unit of capacity
            else:
                raise self._reject(
                    rid, now, qos, tenant,
                    f"frontend at capacity ({self.capacity})",
                    retry_after_s=self._retry_after())
        sub = Submission(
            tokens=np.asarray(tokens, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens), req_id=rid,
            seed=int(seed), prefix=prefix, deadline=deadline, qos=qos,
            tenant=tenant, submitted_at=now)
        self._subs[rid] = sub
        self._live.add(rid)
        self._route[rid] = [rep.replica_id]
        # the frontend-level lifecycle record: per-class/tenant rolling
        # percentiles (summary()["window"]) are fed from THESE events,
        # which survive replica restarts and failover — engine-level
        # records die with their engine
        self.metrics.event(rid, "queued", now=now,
                           n_prompt=int(sub.tokens.size), qos=qos,
                           tenant=tenant)
        rep.submit_sub(sub)
        return rid

    def cancel(self, req_id: int) -> bool:
        if req_id in self._terminal:
            return False
        routed = self._route.get(req_id)
        if not routed:
            return False
        for r in routed:
            self.replicas[r].cancel(req_id)
        return True

    # ---- results --------------------------------------------------------

    def poll(self, req_id: int) -> Optional[RequestResult]:
        """Terminal result, or None while in flight. (Collection
        happens in `pump`; poll only reads.)"""
        return self._terminal.get(req_id)

    def pop_result(self, req_id: int) -> Optional[RequestResult]:
        """Remove and return a terminal result, dropping every trace of
        the request — the long-running server's pressure valve (pair
        with `metrics.drain()`); `_terminal`/`_subs` are otherwise
        bounded only by requests ever served."""
        res = self._terminal.pop(req_id, None)
        if res is not None:
            self._subs.pop(req_id, None)
            self._shed_rids.discard(req_id)
            self._hedged.discard(req_id)
            self._route.pop(req_id, None)
        return res

    @property
    def results(self) -> Dict[int, RequestResult]:
        return dict(self._terminal)

    # ---- the supervision tick -------------------------------------------

    def pump(self, rounds: int = 1) -> None:
        """One supervision round x ``rounds``: drive replicas (inline
        mode), fire watchdogs, restart/fail-over dead replicas, collect
        results, hedge blown TTFT budgets, walk the overload ladder."""
        for _ in range(rounds):
            for rep in self.replicas:
                if self._threaded:
                    rep.check()
                elif rep.state in ("new", "alive"):
                    rep.pump(1)
            self._recover_dead()
            # TTFT before collection: a request whose first token and
            # terminal result land in the same round must still get its
            # first_token stamp (collection pops it from _live)
            self._observe_first_tokens()
            self._collect()
            self._complete_retirements()
            self._hedge_blown_budgets()
            self._update_mode()
            if self._threaded:
                time.sleep(0.001)            # supervision cadence, not
        #                                      the serve loop's

    def run_until_drained(self, *, timeout_s: float = 60.0,
                          max_rounds: int = 100_000
                          ) -> Dict[int, RequestResult]:
        """Pump until every accepted request is terminal (drills /
        benches). Raises on timeout — a drained=False return would just
        get asserted anyway."""
        t0 = time.monotonic()
        for _ in range(max_rounds):
            if not self._live:
                return self.results
            if time.monotonic() - t0 > timeout_s:
                break
            self.pump()
        if self._live:
            raise TimeoutError(
                f"undrained after {time.monotonic() - t0:.1f}s "
                f"(budget {timeout_s}s/{max_rounds} rounds): "
                f"{sorted(self._live)} "
                f"(states: {[r.state for r in self.replicas]})")
        return self.results

    # ---- internals ------------------------------------------------------

    @property
    def n_alive(self) -> int:
        """Routable replicas: alive and not draining toward
        retirement."""
        return sum(r.state in ("new", "alive")
                   and r.replica_id not in self._retiring
                   for r in self.replicas)

    @property
    def capacity(self) -> int:
        cap = max(1, self.n_alive) * self.cfg.capacity_per_replica
        if self._admission_limit is not None:
            cap = min(cap, self._admission_limit)
        return cap

    @property
    def total_inflight(self) -> int:
        return len(self._live)

    @property
    def load_fraction(self) -> float:
        return self.total_inflight / self.capacity

    @property
    def admission_limit(self) -> Optional[int]:
        return self._admission_limit

    def _retry_after(self) -> float:
        return self.cfg.retry_after_s * max(1.0, self.load_fraction)

    def _reject(self, rid: int, now: float, qos: str,
                tenant: Optional[str], reason: str, *,
                retry_after_s: float) -> Backpressure:
        """Build the structured 429 AND record the refusal in the
        lifecycle stream: a rejected guaranteed request is an SLO miss
        the latency percentiles can never see (they survive only on
        accepted traffic) — the rolling window's per-class done-rate
        is the control signal that sees it (`policy.SLOTarget
        .success_rate`)."""
        self.metrics.event(rid, "queued", now=now, n_prompt=0,
                           qos=qos, tenant=tenant)
        self.metrics.event(rid, "rejected", now=now, reason=reason)
        return Backpressure(reason, queue_depth=self.total_inflight,
                            retry_after_s=retry_after_s)

    def _build_engine(self) -> Engine:
        prof = self.cfg.degrade
        dtype = self.cfg.cache_dtype        # the steady-state tier;
        if self.mode == "degraded" and prof.cache_dtype is not None:
            dtype = prof.cache_dtype        # degraded relief wins
        if dtype is not None and self._takes_cache_dtype:
            return self._make_engine(cache_dtype=dtype)
        return self._make_engine()

    def _alive(self) -> List[ReplicaSupervisor]:
        """Replicas new/routable work may target — a retiring replica
        finishes what it has but takes no new routes."""
        return [r for r in self.replicas if r.state in ("new", "alive")
                and r.replica_id not in self._retiring]

    def _pick_replica(self, max_new_tokens: int,
                      deadline: Optional[float], now: float
                      ) -> Optional[ReplicaSupervisor]:
        """Least-loaded alive replica passing the deadline-feasibility
        estimate; least-loaded overall when the deadline is None or no
        replica has timing history yet."""
        alive = self._alive()
        if not alive:
            return None
        ranked = sorted(alive, key=lambda r: (r.load, r.replica_id))
        if deadline is None:
            return ranked[0]
        left = deadline - now
        for rep in ranked:
            est = (rep.load + 1) * max_new_tokens * rep.step_ewma
            if rep.step_ewma == 0.0 or est <= left:
                return rep
        return None

    def _displace_sheddable(self) -> bool:
        """Shed the YOUNGEST in-flight sheddable request to admit a
        guaranteed one — the QoS contract's teeth: sheddable capacity
        is borrowed, guaranteed capacity is owed. A victim already
        being shed (cancelled, result not yet collected) is skipped —
        it must not 'free' the same unit of capacity twice under a
        guaranteed burst (review finding)."""
        victim = None
        for rid in self._live:
            sub = self._subs[rid]
            if sub.qos != "sheddable" or rid in self._shed_rids:
                continue
            if victim is None or sub.submitted_at > victim.submitted_at:
                victim = sub
        if victim is None:
            return False
        self._shed(victim, "shed (displaced by guaranteed)")
        return True

    def _shed(self, sub: Submission, reason: str):
        self._shed_rids.add(sub.req_id)
        self.metrics.incr("sheds")
        routed = self._route.get(sub.req_id, [])
        if routed:
            self._rep_counters[routed[0]]["sheds"] += 1
        self.metrics.transition("shed", req=sub.req_id, qos=sub.qos,
                                reason=reason)
        for r in routed:
            self.replicas[r].cancel(sub.req_id)

    def _recover_dead(self):
        for rep in self.replicas:
            if rep.state != "dead":
                continue
            if rep.replica_id in self._retiring:
                # a replica that dies while draining is not restarted —
                # it was leaving anyway; its in-flight work fails over
                self._failover(rep)
                rep.state = "stopped"
                rep.engine = None        # release the KV cache: only
                #  restart() clears the engine, and this replica never
                #  restarts
                self._retiring.discard(rep.replica_id)
                self.metrics.transition(
                    "replica_retired", replica=rep.replica_id,
                    note="died while draining")
                continue
            if not rep.restart():
                # budget spent: fail over its in-flight work
                self._failover(rep)

    def _failover(self, rep: ReplicaSupervisor):
        subs = rep.drain_inflight()
        targets = self._alive()
        for sub in subs:
            # a hedge leg may already be running elsewhere —
            # re-routing would double-decode the same id on
            # one engine; dropping the failed leg suffices
            others = [r for r in self._route.get(sub.req_id, [])
                      if r != rep.replica_id
                      and self.replicas[r].state
                      in ("new", "alive")]
            if others:
                continue
            if not targets:
                self._finish_here(sub.req_id, RequestResult(
                    req_id=sub.req_id, status="evicted",
                    tokens=np.zeros((0,), np.int32),
                    reason="no surviving replicas"))
                continue
            tgt = min(targets,
                      key=lambda r: (r.load, r.replica_id))
            self._route.setdefault(sub.req_id, []).append(
                tgt.replica_id)
            tgt.submit_sub(sub)
            self.metrics.incr("retries")
        self.metrics.transition(
            "failover", source=rep.replica_id,
            rerouted=[s.req_id for s in subs])

    def _finish_here(self, rid: int, res: RequestResult):
        """Make a request terminal at the frontend and close its
        lifecycle record (latency/TTFT land in the rolling window)."""
        self._terminal[rid] = res
        self._live.discard(rid)
        self._ttft_marked.discard(rid)
        status = res.status if res.status in TERMINAL else "done"
        self.metrics.event(rid, status, reason=res.reason,
                           n_generated=int(res.tokens.size))

    def _collect(self):
        # sweep settled hedge/cancel races: a loser leg publishes its
        # cancelled result an iteration AFTER the winner was collected —
        # keep draining until every leg has either yielded its result
        # or provably never will (nothing pending in that supervisor),
        # THEN drop the route entry; deleting earlier would strand the
        # late result in the supervisor's dict forever (review finding)
        for rid in [r for r in self._route if r in self._terminal]:
            if all(self.replicas[r].pop_result(rid) is not None
                   or not self.replicas[r].pending(rid)
                   for r in self._route[rid]):
                del self._route[rid]
        for rid in list(self._live):
            for r in self._route.get(rid, []):
                res = self.replicas[r].pop_result(rid)
                if res is None:
                    continue
                if rid in self._shed_rids and res.status == "cancelled":
                    res = dataclasses.replace(
                        res, status="evicted", reason="shed (overload)")
                self._finish_here(rid, res)
                # hedge race settled: cancel the other leg(s)
                for other in self._route.get(rid, []):
                    if other != r:
                        self.replicas[other].cancel(rid)
                        self.replicas[other].pop_result(rid)
                if rid in self._hedged and r != self._route[rid][0]:
                    self.metrics.incr("hedges_won")
                break

    def _observe_first_tokens(self):
        """Stamp each live request's first_token lifecycle event the
        first supervision round any routed replica reports it (the
        `first_token_seen` probe) — pump-granular, which is exactly the
        resolution the control loop samples at anyway."""
        for rid in list(self._live):
            if rid in self._ttft_marked:
                continue
            if any(self.replicas[r].first_token_seen(rid)
                   for r in self._route.get(rid, [])):
                self._ttft_marked.add(rid)
                self.metrics.event(rid, "first_token")

    def _complete_retirements(self):
        """Stop a retiring replica once it has drained (dead retiring
        replicas are handled by `_recover_dead`)."""
        for rep_id in sorted(self._retiring):
            rep = self.replicas[rep_id]
            if rep.state in ("new", "alive") and rep.n_inflight == 0:
                rep.stop()
                rep.engine = None        # a stopped replica never
                #  restarts — drop the engine (and its KV cache) or
                #  every scale-up/scale-down cycle leaks one
                self._retiring.discard(rep_id)
                self.metrics.transition("replica_retired",
                                        replica=rep_id)

    def _hedge_budget_for(self, tenant: Optional[str]
                          ) -> Optional[float]:
        """Per-tenant fitted budget > fitted default (None key) >
        the static config; None = hedging disabled for that tenant."""
        if tenant in self._hedge_budgets:
            return self._hedge_budgets[tenant]
        if None in self._hedge_budgets:
            return self._hedge_budgets[None]
        return self.cfg.hedge_after_s

    def _hedge_blown_budgets(self):
        if self.cfg.hedge_after_s is None and not self._hedge_budgets:
            return
        now = self.clock()
        for rid in list(self._live):
            sub = self._subs[rid]
            if sub.qos != "guaranteed" or rid in self._hedged:
                continue
            budget = self._hedge_budget_for(sub.tenant)
            if budget is None or now - sub.submitted_at <= budget:
                continue
            routed = set(self._route[rid])
            # the budget is a TTFT budget: a primary that has already
            # streamed the first token is slow-but-healthy, and a
            # duplicate full decode would burn the very capacity
            # hedging protects — hedge only while NO leg has produced
            # a first token (review finding)
            if any(self.replicas[r].first_token_seen(rid)
                   for r in routed):
                continue
            # exclude EVERY replica already on the route (a failover
            # may have appended the survivor) — hedging onto a replica
            # that already serves the request would double-decode it
            # (review finding)
            primary = self._route[rid][0]
            others = [r for r in self._alive()
                      if r.replica_id not in routed]
            if not others:
                continue
            tgt = min(others, key=lambda r: (r.load, r.replica_id))
            self._hedged.add(rid)
            self._route[rid].append(tgt.replica_id)
            tgt.submit_sub(sub)
            self.metrics.incr("hedges_fired")
            self._rep_counters[tgt.replica_id]["hedges"] += 1
            self.metrics.transition("hedge", req=rid, primary=primary,
                                    secondary=tgt.replica_id)

    def _update_mode(self):
        """The BUILT-IN overload ladder (``mode_control="load"``).
        Escalation requires the load fraction to hold above the
        threshold for ``sustain_rounds`` consecutive pump rounds (a
        burst is not an overload); de-escalation is symmetric. Every
        flip is banked. With ``mode_control="external"`` this is a
        no-op — `set_mode` (the autopilot's actuator) owns the
        ladder."""
        if self.mode_control != "load":
            return
        frac = self.load_fraction
        cfg = self.cfg
        enter = (cfg.enter_shed if self.mode == "normal"
                 else cfg.enter_degraded)
        if self.mode != "degraded" and frac >= enter:
            self._above += 1
        else:
            self._above = 0
        if self.mode != "normal" and frac <= cfg.exit_overload:
            self._below += 1
        else:
            self._below = 0
        if self._above >= cfg.sustain_rounds:
            nxt = MODES[MODES.index(self.mode) + 1]
            self._flip_mode(nxt, frac)
            self._above = 0
            if nxt == "shedding":
                self._shed_all_sheddable()
        elif self._below >= cfg.sustain_rounds:
            self._flip_mode("normal", frac)
            self._below = 0

    def _shed_all_sheddable(self):
        """First relief valve on entering shedding: sheddable-class
        load goes first."""
        for rid in list(self._live):
            sub = self._subs[rid]
            if sub.qos == "sheddable" and rid not in self._shed_rids:
                self._shed(sub, "shed (overload)")

    def _flip_mode(self, new_mode: str, frac: float, **extra):
        old, self.mode = self.mode, new_mode
        fields = dict(frm=old, to=new_mode, load_fraction=round(frac, 4),
                      inflight=self.total_inflight,
                      capacity=self.capacity, **extra)
        if new_mode == "degraded":
            fields["max_new_tokens_cap"] = \
                self.cfg.degrade.max_new_tokens_cap
            fields["cache_dtype"] = str(self.cfg.degrade.cache_dtype)
        self.metrics.transition("mode", **fields)

    # ---- the actuation surface (docs/autopilot.md) ----------------------

    def set_mode(self, mode: str, *, by: str = "operator", **evidence):
        """Flip the overload mode directly (the external-control
        actuator; also works alongside the load ladder — the ladder
        just keeps walking from the new rung). Entering
        shedding-or-worse from normal sheds sheddable load, same as
        the ladder. Banked with the caller and its evidence."""
        if mode not in MODES:
            raise ValueError(f"unknown mode {mode!r}; one of {MODES}")
        if mode == self.mode:
            return
        was = self.mode
        self._flip_mode(mode, self.load_fraction, by=by, **evidence)
        self._above = self._below = 0
        if MODES.index(mode) >= 1 and MODES.index(was) < 1:
            self._shed_all_sheddable()

    def add_replica(self, *, by: str = "operator", **evidence) -> int:
        """Grow the fleet by one supervised replica (started when the
        frontend is threaded). Returns the new replica id. A replica
        built while degraded rides the degrade profile's cache dtype,
        same as a degraded restart."""
        rep = self._new_replica()
        if self._threaded:
            rep.start()
        self.metrics.transition("replica_added", replica=rep.replica_id,
                                n_replicas=len(self.replicas),
                                n_alive=self.n_alive, by=by, **evidence)
        return rep.replica_id

    def retire_replica(self, replica_id: Optional[int] = None, *,
                       by: str = "operator",
                       **evidence) -> Optional[int]:
        """Begin draining one replica toward retirement (the
        least-loaded alive one when unspecified): it takes no new
        routes, finishes its in-flight work, then stops. Returns the
        retiring id, or None when nothing is retirable (never drains
        the last routable replica). The supervisor object stays in
        ``replicas`` — ids are route indices."""
        if replica_id is None:
            cands = self._alive()
            if len(cands) <= 1:
                return None
            # least-loaded; ties go to the newest (scale-down unwinds
            # scale-up)
            rep = min(cands, key=lambda r: (r.load, -r.replica_id))
        else:
            # an unknown id (stale replay of a banked transition) is
            # "nothing retirable", not a crash; ids are route indices,
            # so a negative index must not alias from the end
            if not 0 <= int(replica_id) < len(self.replicas):
                return None
            rep = self.replicas[replica_id]
            if (rep.state not in ("new", "alive")
                    or rep.replica_id in self._retiring
                    or len(self._alive()) <= 1):
                return None
        self._retiring.add(rep.replica_id)
        self.metrics.transition("replica_retiring",
                                replica=rep.replica_id,
                                inflight=rep.n_inflight,
                                n_alive=self.n_alive, by=by, **evidence)
        return rep.replica_id

    def set_admission_limit(self, limit: Optional[int], *,
                            by: str = "operator", **evidence):
        """Admission setpoint: cap `capacity` below the structural
        ``n_alive * capacity_per_replica``. None clears it."""
        self._admission_limit = (None if limit is None
                                 else max(1, int(limit)))
        self.metrics.transition("admission_limit",
                                limit=self._admission_limit,
                                by=by, **evidence)

    def set_hedge_budget(self, budget_s: Optional[float],
                         tenant: Optional[str] = None, *,
                         by: str = "operator", **evidence):
        """Install a fitted TTFT/hedge budget (None disables hedging)
        for one tenant, or the fitted default when ``tenant`` is None.
        Unfitted tenants keep ``cfg.hedge_after_s``."""
        self._hedge_budgets[tenant] = (None if budget_s is None
                                       else float(budget_s))
        self.metrics.transition("hedge_budget", tenant=tenant,
                                budget_s=self._hedge_budgets[tenant],
                                by=by, **evidence)

    # ---- introspection --------------------------------------------------

    def replica_states(self) -> List[str]:
        return [r.state for r in self.replicas]

    def summary(self) -> dict:
        """ONE structured snapshot: the whole-run + rolling-window
        metrics, the mode-transition history, and per-replica
        supervision/restart/hedge/shed counters — the autopilot's
        input and the drills' assertion surface (schema:
        docs/serving.md § Frontend summary)."""
        s = self.metrics.summary()
        s["mode"] = self.mode
        s["mode_history"] = [t for t in self.metrics.transitions
                             if t["event"] == "mode"]
        s["n_replicas"] = len(self.replicas)
        s["n_alive"] = self.n_alive
        s["capacity"] = self.capacity
        s["inflight"] = self.total_inflight
        s["load_fraction"] = round(self.load_fraction, 4)
        s["admission_limit"] = self._admission_limit
        s["hedge_budgets"] = {("default" if t is None else t): b
                              for t, b in self._hedge_budgets.items()}
        s["replicas"] = {
            r.replica_id: {"state": r.state, "restarts": r.restarts,
                           "generation": r.generation,
                           "engines_built": r.engines_built,
                           "steps": r.steps, "load": r.load,
                           "retiring": r.replica_id in self._retiring,
                           **self._rep_counters[r.replica_id]}
            for r in self.replicas}
        # goodput-multiplier rates, aggregated across the CURRENT
        # replica engines (engine metrics die with their engine — these
        # are live-fleet rates, not all-time; fields-only-when-data,
        # same contract as the percentiles)
        agg = {k: 0 for k in ("prefix_lookups", "prefix_hits",
                              "prefix_saved_tokens", "spec_drafted",
                              "spec_accepted")}
        for r in self.replicas:
            eng = r.engine
            if eng is None:
                continue
            for k in agg:
                agg[k] += eng.metrics.get_counter(k)
        if agg["prefix_lookups"]:
            s["prefix_hit_rate"] = (agg["prefix_hits"]
                                    / agg["prefix_lookups"])
            s["prefix_saved_tokens"] = agg["prefix_saved_tokens"]
        if agg["spec_drafted"]:
            s["accept_rate"] = agg["spec_accepted"] / agg["spec_drafted"]
        return s
