"""Supervised engine replica — the fault boundary of the serving tier.

One `Engine` in one process (PR 1) loses every in-flight stream to a
wedged decode step, a poisoned request, or a killed process. The
supervisor wraps the engine in the same discipline the training loop
got in PR 6: observe progress, declare death loudly, recover to a
bit-exact state.

- **Heartbeat + watchdog**: every completed serve iteration stamps a
  heartbeat. A replica that CRASHES (raises) is dead immediately; one
  that stops making step progress past ``watchdog_s`` is declared dead
  by the watchdog (`check` in threaded mode; in pump mode an
  over-deadline iteration is flagged the moment it finally returns).
  A hung thread cannot be killed in Python — it is ABANDONED, and a
  generation token keeps its late writes from corrupting the restarted
  replica's state.
- **Restart + idempotent resubmission**: a dead replica is torn down
  and restarted with a FRESH engine (its two executables re-traced and
  re-pinned via ``Engine.trace_counts``); every in-flight submission is
  resubmitted keyed on its stable request id. Because the engine
  samples token i of a request as ``fold_in(key(seed), i)`` with the
  seed fixed at submit, the regenerated stream is TOKEN-IDENTICAL to
  the lost one at any temperature — the serving analogue of PR 6's
  bit-exact resume.
- **Poison quarantine**: a request whose ADMISSION kills the replica
  (the chaos `PoisonPill` model: deterministic, at the submit
  boundary) is counted per request id; past ``poison_threshold``
  deaths it is quarantined with an ``evicted``/"poisoned" result
  instead of resubmitted — one bad request must not keep a replica in
  a crash loop forever. Step-time crashes are attributed to the
  REPLICA, not a request (attribution there would be guesswork), so
  innocents are never quarantined for a flaky engine.
- **Restart budget**: past ``max_restarts`` the supervisor enters
  ``failed`` and stops restarting; the frontend drains its in-flight
  submissions (`drain_inflight`) and re-routes them to surviving
  replicas — failover, same idempotency contract.

Two drive modes: ``start()`` spawns the serve thread (production /
bench shape); ``pump()`` runs serve iterations inline on the caller's
thread — single-threaded and fully deterministic, which is what lets
tier-1 assert "kill a replica mid-stream, every token bit-identical"
instead of hoping.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from apex1_tpu.serving.engine import Engine, RequestResult
from apex1_tpu.serving.metrics import ServingMetrics
from apex1_tpu.serving.scheduler import Backpressure, new_request_id


class ReplicaKilled(RuntimeError):
    """A replica's serve loop was killed (chaos `ReplicaKill`, or any
    unexpected engine crash re-raised under supervision)."""


class PoisonedRequest(RuntimeError):
    """A request whose admission deterministically kills the replica
    (the chaos poison-pill model)."""

    def __init__(self, msg: str, req_id: Optional[int] = None):
        super().__init__(msg)
        self.req_id = req_id


@dataclasses.dataclass
class Submission:
    """The frozen resubmission record — everything needed to replay a
    request onto a fresh engine and get the identical stream: stable
    ``req_id`` (metrics identity), pinned ``seed`` (sampling
    identity), and the original shape/deadline/QoS contract."""

    tokens: np.ndarray
    max_new_tokens: int
    req_id: int
    seed: int
    prefix: Optional[tuple] = None
    deadline: Optional[float] = None
    qos: str = "best_effort"
    tenant: Optional[str] = None
    submitted_at: float = 0.0

    def kwargs(self) -> dict:
        return dict(max_new_tokens=self.max_new_tokens,
                    req_id=self.req_id, seed=self.seed,
                    prefix=self.prefix, deadline=self.deadline,
                    qos=self.qos, tenant=self.tenant)


@dataclasses.dataclass
class ReplicaConfig:
    """Supervision knobs.

    ``watchdog_s`` must exceed the replica's worst-case HEALTHY step.
    In pump mode the iteration that builds a fresh engine (and pays
    its first-call XLA compiles) is exempt; in threaded mode there is
    no such grace — size the deadline above the first step's compile
    (what `tools/bench_serving.py` does) or pre-warm before `start`.
    """

    watchdog_s: float = 5.0       # no-progress deadline before declared
    max_restarts: int = 3         #  dead; restarts past this = failed
    poison_threshold: int = 1     # admission-kills tolerated per req_id
    idle_sleep_s: float = 0.001   #  before quarantine
    drain_join_s: float = 2.0     # stop(): max wait for the thread


class ReplicaSupervisor:
    """One supervised engine replica.

    ``make_engine() -> Engine`` is called per (re)start — a fresh
    engine per generation is the teardown contract (no state from the
    dead incarnation survives except the resubmission records).
    ``fault`` is a `testing.chaos.ServingFault` hook (None in
    production). ``metrics`` (shared `ServingMetrics`) receives
    restart counters + transitions.
    """

    def __init__(self, make_engine: Callable[[], Engine],
                 replica_id: int = 0, *,
                 config: Optional[ReplicaConfig] = None,
                 metrics: Optional[ServingMetrics] = None,
                 fault=None, seed: int = 0,
                 clock: Optional[Callable[[], float]] = None):
        self.make_engine = make_engine
        self.replica_id = int(replica_id)
        self.clock = clock or time.monotonic  # injectable so
        #  testing.fleetsim can drive pump-mode supervision on VIRTUAL
        #  time (deterministic replay); threaded mode needs a real
        #  clock — heartbeats race the wall there by design
        self.seed = int(seed)         # base for derived request seeds —
        #  the supervisor pins seeds BEFORE the engine sees a request
        #  (resubmission may land on a fresh engine), so the engine's
        #  own cfg.seed never participates through this path; give
        #  every interchangeable replica the same value (the frontend
        #  passes its FrontendConfig.seed)
        self.cfg = config or ReplicaConfig()
        self.metrics = metrics or ServingMetrics()
        self.fault = fault
        self.engine: Optional[Engine] = None
        self.state = "new"            # new|alive|dead|failed|stopped
        self.generation = 0
        self.restarts = 0
        self.steps = 0
        self.engines_built = 0
        self.step_ewma = 0.0          # smoothed iteration wall time —
        self.heartbeat = self.clock()      # the router's feasibility prior
        self.last_error: Optional[BaseException] = None
        self._inbox: deque = deque()  # ("submit", Submission)|("cancel", rid)
        self._inflight: Dict[int, Submission] = {}
        self._results: Dict[int, RequestResult] = {}
        self._kill_counts: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- the public surface (any thread) --------------------------------

    def submit(self, tokens, max_new_tokens: int, *,
               req_id: Optional[int] = None, seed: Optional[int] = None,
               prefix=None, deadline: Optional[float] = None,
               qos: str = "best_effort",
               tenant: Optional[str] = None) -> int:
        """Queue a request for this replica. The seed is pinned HERE
        (derived from the stable req_id when absent) so any later
        resubmission — this replica restarted, or failover to another —
        regenerates the identical stream."""
        from apex1_tpu.serving.engine import derive_request_seed
        rid = new_request_id() if req_id is None else int(req_id)
        if seed is None:
            seed = derive_request_seed(self.seed, rid)
        sub = Submission(
            tokens=np.asarray(tokens, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens), req_id=rid,
            # int32 counter-key contract: fold oversized seeds here,
            # deterministically, instead of crashing the engine step
            seed=int(seed) & 0x7FFFFFFF, prefix=prefix,
            deadline=deadline, qos=qos,
            tenant=tenant, submitted_at=self.clock())
        self.submit_sub(sub)
        return rid

    def submit_sub(self, sub: Submission) -> None:
        with self._lock:
            self._inflight[sub.req_id] = sub
            self._inbox.append(("submit", sub))

    def cancel(self, req_id: int) -> None:
        """Cancel wherever the request is: still in the inbox (never
        reached the engine — finished as cancelled right here) or
        already submitted (engine cancellation command, processed next
        iteration; the engine releases the KV slot immediately)."""
        with self._lock:
            for i, (kind, payload) in enumerate(self._inbox):
                if kind == "submit" and payload.req_id == req_id:
                    del self._inbox[i]
                    self._inflight.pop(req_id, None)
                    self._results[req_id] = RequestResult(
                        req_id=req_id, status="cancelled",
                        tokens=np.zeros((0,), np.int32),
                        reason="cancelled before admission")
                    return
            self._inbox.append(("cancel", int(req_id)))

    def poll(self, req_id: int) -> Optional[RequestResult]:
        with self._lock:
            return self._results.get(req_id)

    def first_token_seen(self, req_id: int) -> bool:
        """Best-effort TTFT probe: has this replica's CURRENT engine
        sampled the request's first token? (Reads the engine's own
        metrics record; False while the request waits in the inbox or
        the engine queue, or after a death wiped the engine.) The
        frontend's hedge trigger keys on this — a streaming request is
        not 'blown', however long its full decode takes."""
        eng = self.engine
        if eng is None:
            return False
        rec = eng.metrics.records.get(req_id)
        return rec is not None and rec.t_first_token is not None

    def pending(self, req_id: int) -> bool:
        """True while this replica may still PUBLISH a result for the
        request: it is in flight here (inbox or engine) and the replica
        can still make progress. False = nothing will ever land, the
        caller may forget the route."""
        if self.state in ("failed", "stopped"):
            return False
        with self._lock:
            return req_id in self._inflight

    def pop_result(self, req_id: int) -> Optional[RequestResult]:
        with self._lock:
            return self._results.pop(req_id, None)

    @property
    def results(self) -> Dict[int, RequestResult]:
        with self._lock:
            return dict(self._results)

    @property
    def n_inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    @property
    def load(self) -> int:
        """Routing load: requests handed to this replica and not yet
        terminal (queued in the inbox, in the engine's queue, or
        decoding)."""
        return self.n_inflight

    def inflight_subs(self) -> List[Submission]:
        with self._lock:
            return sorted(self._inflight.values(),
                          key=lambda s: s.req_id)

    def drain_inflight(self) -> List[Submission]:
        """Remove and return every in-flight submission — the
        frontend's failover hook once this replica is ``failed``.

        An ACKNOWLEDGED cancel pending in the inbox must not be
        forwarded to the surviving replica: draining its request from
        ``_inflight`` would resurrect work the caller was told is
        cancelled (same hazard ``restart`` guards against; found by
        the APX304 protocol model check)."""
        with self._lock:
            cancelled = [p for k, p in self._inbox if k == "cancel"]
            for rid in cancelled:
                if self._inflight.pop(rid, None) is not None:
                    self._results[rid] = RequestResult(
                        req_id=rid, status="cancelled",
                        tokens=np.zeros((0,), np.int32),
                        reason="cancelled (pending at failover)")
            subs = sorted(self._inflight.values(), key=lambda s: s.req_id)
            self._inflight.clear()
            self._inbox.clear()
            return subs

    # ---- lifecycle ------------------------------------------------------

    def start(self) -> "ReplicaSupervisor":
        """Spawn the serve thread (production mode). `pump` is the
        inline alternative; don't mix the two for one generation."""
        self.state = "alive"
        self.heartbeat = self.clock()
        gen = self.generation
        self._thread = threading.Thread(
            target=self._serve, args=(gen,), daemon=True,
            name=f"replica-{self.replica_id}-gen{gen}")
        self._thread.start()
        return self

    def pump(self, iterations: int = 1) -> int:
        """Run up to ``iterations`` serve iterations INLINE — the
        deterministic drive mode tier-1 drills use. Returns iterations
        completed (0 when dead/failed/stopped). An iteration that
        crashes or overruns the watchdog marks the replica dead."""
        if self.state == "new":
            self.state = "alive"
        if self.state != "alive":
            return 0
        gen = self.generation
        done = 0
        for _ in range(iterations):
            fresh = self.engine is None   # this iteration pays the
            t0 = self.clock()             # engine build + first-call
            try:                          # XLA compiles
                self._ensure_engine()
                self._iterate(gen)
            except BaseException as e:
                self._mark_dead(e)
                return done
            took = self.clock() - t0
            if not fresh:
                self._observe_step(took)
            if not fresh and took > self.cfg.watchdog_s:
                # the iteration DID return, but past the deadline a
                # real watchdog would already have fired mid-flight —
                # same verdict, observed at the boundary (the pump-mode
                # hang model; threaded mode fires via check())
                self._mark_dead(ReplicaKilled(
                    f"watchdog: iteration took {took:.3f}s "
                    f"(> {self.cfg.watchdog_s}s)"))
                return done
            done += 1
        return done

    def check(self, now: Optional[float] = None) -> bool:
        """Watchdog probe (threaded mode): True while healthy. A
        heartbeat older than ``watchdog_s`` on a live replica declares
        it dead — the thread is abandoned (its generation token keeps
        late writes out) and the caller restarts."""
        if self.state != "alive":
            return self.state not in ("dead", "failed")
        if self._thread is None:      # pump mode: liveness is state
            return True
        now = self.clock() if now is None else now
        if now - self.heartbeat > self.cfg.watchdog_s:
            self._mark_dead(ReplicaKilled(
                f"watchdog: no heartbeat for {now - self.heartbeat:.3f}s"))
            return False
        return True

    def restart(self) -> bool:
        """Tear down the dead incarnation and bring up a fresh engine,
        resubmitting every in-flight request (idempotent: stable ids +
        pinned seeds). Returns False once the restart budget is spent
        (state ``failed`` — the frontend's cue to fail over)."""
        if self.state != "dead":
            raise RuntimeError(
                f"restart() on a {self.state} replica (only dead ones)")
        self.restarts += 1
        if self.restarts > self.cfg.max_restarts:
            self.state = "failed"
            self.metrics.transition(
                "replica_failed", replica=self.replica_id,
                restarts=self.restarts - 1,
                error=repr(self.last_error))
            return False
        threaded = self._thread is not None
        self.generation += 1
        self.engine = None            # fresh engine next iteration
        self._thread = None
        quarantined: List[RequestResult] = []
        with self._lock:
            # an ACKNOWLEDGED cancel pending in the inbox must survive
            # the restart — resubmitting its request from _inflight
            # would resurrect work the caller was told is cancelled
            # (review finding)
            cancelled = [p for k, p in self._inbox if k == "cancel"]
            for rid in cancelled:
                if self._inflight.pop(rid, None) is not None:
                    self._results[rid] = RequestResult(
                        req_id=rid, status="cancelled",
                        tokens=np.zeros((0,), np.int32),
                        reason="cancelled (pending at restart)")
            self._inbox.clear()       # stale commands die with the gen
            for sub in sorted(self._inflight.values(),
                              key=lambda s: s.req_id):
                kills = self._kill_counts.get(sub.req_id, 0)
                if kills > self.cfg.poison_threshold:
                    quarantined.append(RequestResult(
                        req_id=sub.req_id, status="evicted",
                        tokens=np.zeros((0,), np.int32),
                        reason=f"poisoned (killed replica {kills}x)"))
                    continue
                self._inbox.append(("submit", sub))
            for res in quarantined:
                self._inflight.pop(res.req_id, None)
                self._results[res.req_id] = res
        self.metrics.incr("replica_restarts")
        self.metrics.incr("retries", self.n_inflight)
        self.metrics.transition(
            "replica_restart", replica=self.replica_id,
            generation=self.generation, resubmitted=self.n_inflight,
            quarantined=[r.req_id for r in quarantined],
            error=repr(self.last_error))
        self.last_error = None
        self.state = "alive"
        self.heartbeat = self.clock()
        if threaded:
            gen = self.generation
            self._thread = threading.Thread(
                target=self._serve, args=(gen,), daemon=True,
                name=f"replica-{self.replica_id}-gen{gen}")
            self._thread.start()
        return True

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.cfg.drain_join_s)
        if self.state in ("alive", "new", "dead"):
            self.state = "stopped"

    @property
    def idle(self) -> bool:
        """No queued work and nothing decoding (alive replicas only)."""
        if self.engine is None:
            return self.n_inflight == 0
        with self._lock:
            inbox = len(self._inbox)
        return (inbox == 0 and self.engine.scheduler.depth == 0
                and self.engine.n_active == 0)

    # ---- the serve loop -------------------------------------------------

    def _ensure_engine(self):
        if self.engine is None:
            self.engine = self.make_engine()
            self.engines_built += 1
        return self.engine

    def _serve(self, gen: int):
        """Thread body: build the engine, iterate until stopped. Any
        exception marks the replica dead; a stale generation (the
        watchdog abandoned us while we slept in a wedged step) exits
        without touching shared state."""
        try:
            self._ensure_engine()
            while not self._stop.is_set():
                if gen != self.generation:
                    return            # abandoned: a new gen owns state
                t0 = self.clock()
                self._iterate(gen)
                if gen == self.generation:
                    self.heartbeat = self.clock()
                    self._observe_step(self.heartbeat - t0)
                if self.idle:
                    time.sleep(self.cfg.idle_sleep_s)
        except BaseException as e:
            if gen == self.generation:
                self._mark_dead(e)

    def _iterate(self, gen: int):
        """One serve iteration: drain the inbox into the engine, run
        one engine step, publish finished results, stamp progress."""
        engine = self.engine
        while True:
            with self._lock:
                if not self._inbox:
                    break
                kind, payload = self._inbox.popleft()
            if kind == "cancel":
                engine.cancel(payload)
                continue
            sub = payload
            try:
                if self.fault is not None:
                    self.fault.on_submit(self.replica_id, sub)
                engine.submit(sub.tokens, **sub.kwargs())
            except Backpressure:
                with self._lock:      # engine queue full: retry next
                    self._inbox.appendleft((kind, sub))  # iteration
                break
            except (PoisonedRequest, ReplicaKilled) as e:
                # admission killed the replica: attribute the death to
                # THIS request so restart() can quarantine a repeat
                # offender instead of crash-looping forever
                with self._lock:
                    self._kill_counts[sub.req_id] = \
                        self._kill_counts.get(sub.req_id, 0) + 1
                raise ReplicaKilled(
                    f"admission of request {sub.req_id} killed "
                    f"replica {self.replica_id}: {e}") from e
            except ValueError as e:
                # contract violation (can never fit): terminal per
                # request, not fatal per replica
                with self._lock:
                    self._inflight.pop(sub.req_id, None)
                    self._results[sub.req_id] = RequestResult(
                        req_id=sub.req_id, status="rejected",
                        tokens=np.zeros((0,), np.int32),
                        reason=f"contract: {e}")
        if self.fault is not None:
            self.fault.on_step(self.replica_id, self.steps)
        engine.step()
        for rid in list(engine.results.keys()):
            res = engine.pop_result(rid)
            with self._lock:
                if gen != self.generation:
                    return
                self._inflight.pop(rid, None)
                self._results[rid] = res
        self.steps += 1

    def _observe_step(self, took: float):
        self.step_ewma = (took if self.step_ewma == 0.0
                          else 0.8 * self.step_ewma + 0.2 * took)

    def _mark_dead(self, err: BaseException):
        if self.state == "alive":
            self.state = "dead"
            self.last_error = err
            self.metrics.transition(
                "replica_dead", replica=self.replica_id,
                generation=self.generation, error=repr(err),
                inflight=self.n_inflight)

    # ---- introspection --------------------------------------------------

    def trace_counts(self) -> Optional[dict]:
        """The CURRENT engine's compile-count hook (None before first
        build) — the drill's exactly-two-executables pin, per
        generation."""
        return None if self.engine is None else dict(
            self.engine.trace_counts)
