"""Per-request lifecycle metrics for the serving engine.

Every request walks the state machine
``queued → prefill → decode → {done | evicted | cancelled}`` (or is
``rejected`` at the door); each transition is an EVENT with a
monotonic timestamp (`obs.spine.monotonic` — the one clock every
subsystem stamps with). Events stream through
`utils.observability.MetricsLogger` as JSON lines when a logger is
supplied (the same sink the training loop uses, so one log carries
both), mirror into the telemetry spine's run file when
``APEX1_OBS_DIR`` is set (``serving.request`` / ``serving.transition``
events — docs/observability.md), and always accumulate in memory for
`summary()` — the
offered-load sweep in ``tools/bench_serving.py`` reads tokens/sec,
p50/p99 time-to-first-token, and mean slot occupancy from it.

Schema (`docs/serving.md` § Engine): every event line is
``{"event", "req", "t", **fields}``; per-step samples are
``{"event": "step", "t", "active", "queue_depth", "occupancy"}``;
SYSTEM transitions (degraded-mode flips, replica restarts — no single
request owns them) are ``{"event", "t", **fields}`` with no ``req``
key, banked through `transition` and kept in ``transitions`` for the
drills to assert on.

Failure-path counters (`incr`) ride `summary()["counters"]`: retries,
hedges fired/won, sheds, evictions, replica restarts — the numbers an
operator pages on, always present (0 when the path never fired).

Two control-loop extensions (docs/autopilot.md):

- **Rolling window**: whole-run aggregates freeze late-run signal under
  early history (an hour of healthy traffic pins p99 no matter what the
  last minute did), so the last ``window`` TERMINAL requests also land
  in a ring buffer and `summary()["window"]` reports per-class /
  per-tenant latency+TTFT percentiles over just that ring — the
  autopilot's control signal. Whole-run fields keep their meaning.
- **Injectable clock**: ``clock`` replaces `obs.spine.monotonic` as the
  timestamp source, so `testing.fleetsim` can stamp every event with
  VIRTUAL time and two replays of one trace produce bit-identical
  event histories.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Callable, Dict, Optional

import numpy as np

from apex1_tpu.obs import spine
from apex1_tpu.utils.observability import MetricsLogger

#: terminal request states
TERMINAL = ("done", "evicted", "cancelled", "rejected")

#: failure-path counters always present in summary()["counters"]
FAILURE_COUNTERS = ("retries", "hedges_fired", "hedges_won", "sheds",
                    "evictions", "replica_restarts",
                    # disaggregated serving (docs/serving.md
                    # § Disaggregated serving): a corrupt/torn KV
                    # handoff caught by the manifest re-digest, and the
                    # re-route that answered it — 0 on a healthy fleet
                    # is an ASSERTED property, not missing data
                    "handoff_failures", "handoff_reroutes")


@dataclasses.dataclass
class RequestRecord:
    """Lifecycle timestamps + counters for one request."""

    req_id: int
    n_prompt: int = 0
    n_generated: int = 0
    t_queued: Optional[float] = None
    t_prefill: Optional[float] = None
    t_first_token: Optional[float] = None
    t_done: Optional[float] = None
    status: str = "queued"
    reason: str = ""
    qos: Optional[str] = None     # set when the queued event carries it
    tenant: Optional[str] = None  #  (frontend lifecycle records do)
    # goodput-multiplier observables (ISSUE 15): radix-cache outcome at
    # admission (None = the engine never looked — prefix cache off or a
    # frontend-level record) and the speculative accept-rate numerators
    # the terminal event banks
    prefix_hit: Optional[bool] = None
    prefix_saved: int = 0         # cached positions the hit skipped
    n_drafted: int = 0
    n_accepted: int = 0

    @property
    def ttft(self) -> Optional[float]:
        """Time-to-first-token: submit → first sampled token. With the
        engine's deferred mode (``eos_id=None``) the first-token event
        marks the prefill chain's DISPATCH under async dispatch — a
        lower bound on availability (the value lands with the step
        chain); with an ``eos_id`` every step blocks on its tokens, so
        the instant is exact."""
        if self.t_queued is None or self.t_first_token is None:
            return None
        return self.t_first_token - self.t_queued

    @property
    def accept_rate(self) -> Optional[float]:
        """Speculative draft accept rate (None when the request never
        ran under speculation — fields-only-when-data, like the
        percentile keys)."""
        if self.n_drafted <= 0:
            return None
        return self.n_accepted / self.n_drafted

    @property
    def tpot(self) -> Optional[float]:
        """Time-per-output-token over the DECODE phase: first token →
        terminal, per generated token past the first (None until both
        stamps exist, or when at most one token was generated). TTFT
        is the prefill phase's pressure signal; this is the decode
        phase's — the pair is the disaggregated pool-ratio actuator's
        input (docs/serving.md § Disaggregated serving)."""
        if (self.t_first_token is None or self.t_done is None
                or self.n_generated < 2):
            return None
        return (self.t_done - self.t_first_token) / (self.n_generated - 1)

    @property
    def latency(self) -> Optional[float]:
        if self.t_queued is None or self.t_done is None:
            return None
        if self.status == "rejected":
            # a refusal is terminal at its queued instant — calling
            # that "0.0s latency" would deflate every percentile the
            # control loop reads (a flood of rejections must read as
            # missing done-rate, not as excellent latency)
            return None
        return self.t_done - self.t_queued


class ServingMetrics:
    """Event sink + aggregator. ``logger`` (a `MetricsLogger`) makes
    every event a JSON line; omit it for in-memory-only collection
    (tests, benches that only want `summary()`)."""

    def __init__(self, logger: Optional[MetricsLogger] = None, *,
                 window: int = 128,
                 clock: Optional[Callable[[], float]] = None):
        self.logger = logger
        self._clock = clock or spine.monotonic
        self.records: Dict[int, RequestRecord] = {}
        self.counters: Dict[str, int] = {}
        self.transitions: list = []
        # the last `window` TERMINAL requests (qos/tenant/status/ttft/
        # latency/prefix_hit/accept_rate) — the rolling control signal
        # summary()["window"] reports; deque drops the oldest, O(window)
        # space forever
        self._window: deque = deque(maxlen=max(1, int(window)))
        # step samples fold into RUNNING aggregates (count / occupancy
        # sum / peak queue) — a long-lived engine steps indefinitely,
        # so per-step dicts would leak host memory (review finding);
        # per-request records are bounded by `drain()` below
        self._step_n = 0
        self._occ_sum = 0.0
        self._peak_queue = 0
        self._event_seq = 0
        self._t0 = self._clock()
        # submit (and its queued/rejected events) may run on an ingest
        # thread (`runtime.RequestFeeder`) while the engine loop logs
        # token/terminal events — same cross-thread pattern the
        # Scheduler locks for; unlocked counters would lose updates
        self._lock = threading.Lock()

    # ---- events ---------------------------------------------------------

    def event(self, req_id: int, name: str, now: Optional[float] = None,
              **fields) -> RequestRecord:
        now = self._clock() if now is None else now
        with self._lock:
            return self._event_locked(req_id, name, now, fields)

    def _event_locked(self, req_id: int, name: str, now: float,
                      fields: dict) -> RequestRecord:
        rec = self.records.setdefault(req_id, RequestRecord(req_id))
        if name == "queued":
            # also on RE-queue: a retried submission (stable req_id
            # after a transient rejection) returns to the queued state
            rec.status = "queued"
            rec.t_queued = now
            rec.n_prompt = int(fields.get("n_prompt", 0))
            if fields.get("qos") is not None:
                rec.qos = str(fields["qos"])
            if fields.get("tenant") is not None:
                rec.tenant = str(fields["tenant"])
        elif name == "prefill":
            rec.status = "prefill"
            rec.t_prefill = now
            if fields.get("prefix_hit") is not None:
                rec.prefix_hit = bool(fields["prefix_hit"])
                rec.prefix_saved = int(fields.get("prefix_saved", 0))
        elif name == "first_token":
            rec.status = "decode"
            rec.t_first_token = now
            rec.n_generated = 1
        elif name == "token":
            rec.n_generated += int(fields.get("n", 1))
        elif name in TERMINAL:
            rec.status = name
            rec.t_done = now
            rec.reason = str(fields.get("reason", ""))
            rec.n_generated = int(fields.get("n_generated",
                                             rec.n_generated))
            rec.n_drafted = int(fields.get("n_drafted", rec.n_drafted))
            rec.n_accepted = int(fields.get("n_accepted",
                                            rec.n_accepted))
            self._window.append(
                (rec.qos or "best_effort", rec.tenant, name,
                 rec.ttft, rec.latency, rec.prefix_hit,
                 rec.accept_rate, rec.tpot))
        else:
            raise ValueError(f"unknown lifecycle event {name!r}")
        if name != "token":
            # per-token lines would dominate the log; counts ride the
            # terminal event instead. Lifecycle events also mirror into
            # the telemetry spine (APEX1_OBS_DIR) so serving joins the
            # same run stream as bench/training/tuning. The spine
            # stamps its own run-relative `t` (ONE time axis across
            # emitters); this object's engine-relative clock rides
            # along as `t_serving` — passing it as `t` would put two
            # unrecorded origins on the shared axis.
            spine.emit("event", "serving.request", event=name,
                       req=int(req_id), t_serving=now - self._t0,
                       **fields)
            if self.logger is not None:
                self._event_seq += 1
                self.logger.log(self._event_seq,
                                {"event": name, "req": int(req_id),
                                 "t": now - self._t0, **{
                                     k: v for k, v in fields.items()}},
                                _obs_name=None)
        return rec

    def incr(self, name: str, n: int = 1) -> None:
        """Bump a failure-path counter (see `FAILURE_COUNTERS`; other
        names are allowed — they appear in the counters dict too)."""
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + int(n)

    def get_counter(self, name: str) -> int:
        """One counter, under the lock — the cheap cross-object read
        (`ServingFrontend.summary` aggregates each replica engine's
        prefix/spec counters through this instead of paying a whole-run
        `summary()` per replica)."""
        with self._lock:
            return int(self.counters.get(name, 0))

    def transition(self, name: str, now: Optional[float] = None,
                   **fields) -> dict:
        """Bank a SYSTEM event (no owning request): degraded-mode
        flips, replica deaths/restarts, hedge dispatches. Every
        transition is a JSON line when a logger is wired AND kept in
        ``transitions`` — the overload drill asserts each degradation
        step left a banked record."""
        now = self._clock() if now is None else now
        rec = {"event": str(name), "t": now - self._t0, **fields}
        # rec's engine-relative "t" must NOT land on spine.emit's `t`
        # parameter (run-relative axis) — same origin rule as above
        spine.emit("event", "serving.transition", event=rec["event"],
                   t_serving=rec["t"],
                   **{k: v for k, v in fields.items() if k != "t"})
        with self._lock:
            self.transitions.append(rec)
            if self.logger is not None:
                self._event_seq += 1
                self.logger.log(self._event_seq, rec, _obs_name=None)
        return rec

    def step_sample(self, active: int, max_slots: int,
                    queue_depth: int) -> None:
        """One engine-step occupancy sample (drives mean occupancy and
        peak queue depth — folded into running aggregates, O(1) space
        for the life of the engine)."""
        with self._lock:
            self._step_n += 1
            self._occ_sum += active / max_slots
            if queue_depth > self._peak_queue:
                self._peak_queue = queue_depth

    def drain(self) -> Dict[int, RequestRecord]:
        """Remove and return all TERMINAL request records — the
        long-running server's pressure valve (ship them to a sink, let
        the dict stay bounded by in-flight work); pair with
        `Engine.pop_result`. The occupancy/step aggregates and the
        wall clock in `summary()` are LIFETIME values and do not reset
        — for a fresh measurement window, swap in a new
        `ServingMetrics` (what `tools/bench_serving.py` does between
        reps)."""
        with self._lock:
            gone = {k: r for k, r in self.records.items()
                    if r.status in TERMINAL}
            for k in gone:
                del self.records[k]
            return gone

    # ---- aggregates -----------------------------------------------------

    def summary(self) -> dict:
        """Aggregate view: counts per terminal status, throughput over
        the engine's wall clock, TTFT percentiles, occupancy — plus
        ``window``: the same percentiles per QoS class / tenant over
        only the last ``window`` terminal requests (the rolling control
        signal; whole-run fields keep their life-of-the-engine
        meaning)."""
        with self._lock:
            recs = list(self.records.values())
            counters = dict(self.counters)
            win = list(self._window)
        done = [r for r in recs if r.status == "done"]
        ttfts = sorted(r.ttft for r in recs if r.ttft is not None)
        lats = sorted(r.latency for r in recs if r.latency is not None)
        gen = sum(r.n_generated for r in recs)
        wall = max(self._clock() - self._t0, 1e-9)
        out = {
            "requests": len(recs),
            "done": len(done),
            "evicted": sum(r.status == "evicted" for r in recs),
            "cancelled": sum(r.status == "cancelled" for r in recs),
            "rejected": sum(r.status == "rejected" for r in recs),
            "generated_tokens": int(gen),
            "tokens_per_sec": gen / wall,
            "steps": self._step_n,
            # the failure-path record: named counters are ALWAYS
            # present (0 = the path never fired — an asserted property,
            # not missing data); ad-hoc incr() names ride along
            "counters": {**{k: 0 for k in FAILURE_COUNTERS}, **counters},
        }
        if ttfts:
            out["ttft_p50_ms"] = 1e3 * float(np.percentile(ttfts, 50))
            out["ttft_p99_ms"] = 1e3 * float(np.percentile(ttfts, 99))
        if lats:
            out["latency_p50_ms"] = 1e3 * float(np.percentile(lats, 50))
            out["latency_p99_ms"] = 1e3 * float(np.percentile(lats, 99))
        tpots = sorted(r.tpot for r in recs if r.tpot is not None)
        if tpots:
            out["tpot_p50_ms"] = 1e3 * float(np.percentile(tpots, 50))
            out["tpot_p99_ms"] = 1e3 * float(np.percentile(tpots, 99))
        if self._step_n:
            out["mean_occupancy"] = self._occ_sum / self._step_n
            out["peak_queue_depth"] = self._peak_queue
        # goodput-multiplier rates (fields-only-when-data, same contract
        # as the percentiles): cumulative over every admission/draft the
        # engine ever made; the rolling view rides window.per_class
        lookups = counters.get("prefix_lookups", 0)
        if lookups:
            out["prefix_hit_rate"] = counters.get("prefix_hits",
                                                  0) / lookups
            out["prefix_saved_tokens"] = counters.get(
                "prefix_saved_tokens", 0)
        drafted = counters.get("spec_drafted", 0)
        if drafted:
            out["accept_rate"] = counters.get("spec_accepted",
                                              0) / drafted
        out["window"] = self._window_summary(win)
        return out

    def window_summary(self) -> dict:
        """Just ``summary()["window"]`` — O(window), no whole-run
        percentile sorts under the lock. The control loop's per-tick
        read (whole-run sorts grow with every request ever served;
        a 10 Hz controller must not pay that, nor stall the ingest
        thread's `event()` calls while it does)."""
        with self._lock:
            win = list(self._window)
        return self._window_summary(win)

    @staticmethod
    def _window_summary(win: list) -> dict:
        """Per-class / per-tenant percentiles over the ring entries
        ``(qos, tenant, status, ttft, latency, prefix_hit,
        accept_rate, tpot)``. Percentile/rate keys only appear when the
        class has data — same contract as the whole-run fields. TTFT
        and TPOT land side by side per QoS class: the per-phase split
        (prefill pressure vs decode pressure) the disaggregated
        pool-ratio actuator consumes."""
        def rates(entries, d):
            hits = [e[5] for e in entries if e[5] is not None]
            if hits:
                d["prefix_hit_rate"] = sum(hits) / len(hits)
            accs = [e[6] for e in entries if e[6] is not None]
            if accs:
                d["accept_rate"] = float(np.mean(accs))
            return d

        def stats(entries, *, with_latency=True):
            d = {"n": len(entries),
                 "done": sum(e[2] == "done" for e in entries)}
            ttfts = sorted(e[3] for e in entries if e[3] is not None)
            lats = sorted(e[4] for e in entries if e[4] is not None)
            if ttfts:
                d["ttft_p50_ms"] = 1e3 * float(np.percentile(ttfts, 50))
                d["ttft_p99_ms"] = 1e3 * float(np.percentile(ttfts, 99))
            if with_latency and lats:
                d["latency_p50_ms"] = 1e3 * float(np.percentile(lats, 50))
                d["latency_p99_ms"] = 1e3 * float(np.percentile(lats, 99))
            tpots = sorted(e[7] for e in entries if e[7] is not None)
            if tpots:
                d["tpot_p50_ms"] = 1e3 * float(np.percentile(tpots, 50))
                d["tpot_p99_ms"] = 1e3 * float(np.percentile(tpots, 99))
            return rates(entries, d)

        by_class: Dict[str, list] = {}
        by_tenant: Dict[str, list] = {}
        for e in win:
            by_class.setdefault(e[0], []).append(e)
            if e[1] is not None:
                by_tenant.setdefault(e[1], []).append(e)
        return rates(win, {
            "size": len(win),
            "per_class": {c: stats(es)
                          for c, es in sorted(by_class.items())},
            # tenants feed the per-tenant hedge/TTFT budget fit, which
            # only needs the TTFT distribution
            "per_tenant": {t: stats(es, with_latency=False)
                           for t, es in sorted(by_tenant.items())},
        })
