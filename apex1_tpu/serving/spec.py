"""Host-side draft proposal for the engine's speculative decode loop.

The engine's speculative mode (``EngineConfig.num_draft > 0``) needs K
proposed tokens per active slot per step. The ZERO-EXTRA-PARAMS default
is prompt-lookup / n-gram self-drafting (`ngram_propose`): the request's
own known history (prefix + prompt + everything emitted so far) is the
draft model — the longest recent n-gram is looked up at its most recent
earlier occurrence and the tokens that followed it are proposed. That
captures the two regimes where speculation pays: extractive
continuations (the answer repeats spans of the prompt) and the
repetition attractors autoregressive decode falls into.

A SMALL DRAFT MODEL rides the same seam: pass
``Engine(draft_propose=fn)`` where ``fn(history, k) -> k ints`` — the
engine does not care how the proposal was made, only that it is a
host-side function of the request's own history (so drafting never
perturbs the verified stream: acceptance is decided by the target's
counter-keyed samples, see docs/serving.md § Speculative decode in the
engine).

Drafts are PURE LATENCY HINTS under the engine's exact-match verify:
a wrong draft costs a wasted lane in one verify dispatch, never a
changed token — so this module needs no seed plumbing and no determinism
contract beyond being a function of its inputs.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

__all__ = ["ngram_propose"]


def ngram_propose(history: Sequence[int], k: int, *,
                  max_ngram: int = 3) -> np.ndarray:
    """Propose ``k`` draft tokens by prompt-lookup over ``history``.

    Tries the longest suffix n-gram first (``max_ngram`` down to 1):
    finds its MOST RECENT earlier occurrence in the history and
    proposes the tokens that followed it, padded by repeating the last
    proposed (or last history) token when the occurrence sits too near
    the end. Falls back to repeating the final token — the cheapest
    guess that wins exactly when decode has entered a fixed point.

    Returns an ``(k,)`` int32 array. ``history`` must be non-empty
    (the engine always has at least the prompt's first token).
    """
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    h = [int(t) for t in history]
    n = len(h)
    if n == 0:
        raise ValueError("ngram_propose needs a non-empty history")
    for g in range(min(int(max_ngram), n - 1), 0, -1):
        pat = h[n - g:]
        # most recent earlier occurrence (recency beats frequency for
        # decode loops: the current cycle is the best predictor)
        for s in range(n - g - 1, -1, -1):
            if h[s:s + g] == pat:
                cont = h[s + g:s + g + k]
                if cont:
                    while len(cont) < k:
                        cont.append(cont[-1])
                    return np.asarray(cont, np.int32)
    return np.full((k,), h[-1], np.int32)
