"""Fixed-slot KV pool + shared-prefix store for the serving engine.

The pool IS the existing cache layout (`models.generate.init_cache`:
``{"layer{i}": {"k","v": (max_slots, Hkv, max_len, D)}}``) — slot s is
lane s of every leaf. TPU-first consequence: the pool's shapes never
change for the life of the engine, so requests joining and leaving
never retrace anything; all slot traffic is ``dynamic_slice`` /
``dynamic_update_slice`` on the leading axis inside the engine's two
jitted executables. This module is the HOST-side bookkeeping around
that device pytree: which lanes are free, and which shared-prefix
K/V snapshots exist.

Prefix sharing is at SLOT granularity (not paged): a common system
prompt's K/V is computed once, snapshotted as a batch-1 lane pytree
("page"), and INSTALLED (one on-device lane copy inside the prefill
executable) into each slot that reuses it — the prefix's attention
FLOPs are paid once per distinct prefix, not once per request. Pages
are refcounted: a page acquired by a live slot can never be evicted
(`test_serving::TestPrefixRefcounts::test_refcount_never_frees_live_page`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class PrefixPage:
    """One shared-prefix K/V snapshot: a batch-1 cache pytree holding
    ``length`` real positions (the tail beyond ``length`` is write-noise
    the attention masks — see `cached_attention`'s chunk mode)."""

    lane: Any                    # batch-1 cache pytree (device arrays)
    length: int                  # real positions held
    refcount: int = 0            # live slots currently built on it
    hits: int = 0                # admissions served (the saved prefills)


class KVPool:
    """Slot allocator + prefix-page store over one pooled cache pytree.

    The device pytree itself is handed back and forth with the engine
    (its jitted calls donate and return it); the pool only tracks lane
    ownership. ``alloc``/``free`` are O(1) against a free list — the
    admission policy (who gets the slot) lives in `serving.scheduler`.
    """

    def __init__(self, make_cache, max_slots: int, max_len: int,
                 dtype=None):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        kw = {} if dtype is None else {"dtype": dtype}
        self.cache = make_cache(self.max_slots, self.max_len, **kw)
        # a zeroed batch-1 lane: installed on admission so a fresh
        # request never attends a retired request's stale K/V through a
        # masking bug — defense in depth, the horizon mask already
        # excludes unwritten positions
        self.zeros_lane = jax.tree_util.tree_map(
            lambda x: jnp.zeros((1,) + x.shape[1:], x.dtype), self.cache)
        self._free: List[int] = list(range(self.max_slots))
        self._slot_prefix: Dict[int, tuple] = {}   # slot -> prefix key
        self._prefixes: Dict[tuple, PrefixPage] = {}

    # ---- slots ----------------------------------------------------------

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> float:
        return 1.0 - len(self._free) / self.max_slots

    def alloc(self) -> Optional[int]:
        """Lowest free slot, or None when the pool is full."""
        return self._free.pop(0) if self._free else None

    def free(self, slot: int) -> None:
        if slot in self._free:
            raise ValueError(f"slot {slot} double-freed")
        if not 0 <= slot < self.max_slots:
            raise ValueError(f"slot {slot} out of range")
        key = self._slot_prefix.pop(slot, None)
        if key is not None:
            self.release_prefix(key)
        self._free.append(slot)
        self._free.sort()

    # ---- prefix pages ---------------------------------------------------

    def has_prefix(self, key: tuple) -> bool:
        return tuple(key) in self._prefixes

    def put_prefix(self, key: tuple, lane, length: int) -> PrefixPage:
        """Register a computed prefix snapshot. ``lane`` is a batch-1
        cache pytree (the engine slices it out of the pool right after
        the prefix chunks complete)."""
        key = tuple(key)
        if key in self._prefixes:
            raise ValueError(f"prefix {key!r} already registered")
        page = PrefixPage(lane=lane, length=int(length))
        self._prefixes[key] = page
        return page

    def acquire_prefix(self, key: tuple, slot: int) -> PrefixPage:
        """Refcount++ on behalf of ``slot`` (released by `free`)."""
        key = tuple(key)
        page = self._prefixes[key]
        page.refcount += 1
        page.hits += 1
        self._slot_prefix[slot] = key
        return page

    def release_prefix(self, key: tuple) -> None:
        page = self._prefixes[tuple(key)]
        if page.refcount <= 0:
            raise ValueError(f"prefix {key!r} released below zero")
        page.refcount -= 1

    def evict_prefix(self, key: tuple, force: bool = False) -> bool:
        """Drop a prefix page (reclaim its host/device memory). A page
        with live references is NEVER freed: returns False (or raises
        with ``force=True`` — force still refuses; it exists so callers
        who believe the page is dead fail loudly instead of silently
        keeping it)."""
        key = tuple(key)
        page = self._prefixes.get(key)
        if page is None:
            return False
        if page.refcount > 0:
            if force:
                raise RuntimeError(
                    f"prefix {key!r} has {page.refcount} live slot(s) — "
                    f"refusing to free a live page")
            return False
        del self._prefixes[key]
        return True

    def prefix_stats(self) -> dict:
        return {repr(k): {"length": p.length, "refcount": p.refcount,
                          "hits": p.hits}
                for k, p in self._prefixes.items()}
