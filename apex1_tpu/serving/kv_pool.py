"""Fixed-slot KV pool + radix-matched shared-prefix store for the
serving engine.

The pool IS the existing cache layout (`models.generate.init_cache`:
``{"layer{i}": {"k","v": (max_slots, Hkv, max_len, D)}}``) — slot s is
lane s of every leaf. TPU-first consequence: the pool's shapes never
change for the life of the engine, so requests joining and leaving
never retrace anything; all slot traffic is ``dynamic_slice`` /
``dynamic_update_slice`` on the leading axis inside the engine's two
jitted executables. This module is the HOST-side bookkeeping around
that device pytree: which lanes are free, and which shared-prefix
K/V snapshots exist.

Prefix sharing is at SLOT granularity (not paged): a common prompt
prefix's K/V is computed once, snapshotted as a batch-1 lane pytree
("page"), and INSTALLED (one on-device lane copy inside the prefill
executable) into each slot that reuses it — the prefix's attention
FLOPs are paid once per distinct prefix, not once per request. Pages
are refcounted: a page acquired by a live slot can never be evicted
(`test_serving::TestPrefixRefcounts::test_refcount_never_frees_live_page`).

CROSS-REQUEST MATCHING (`RadixIndex` + `match`): pages are keyed by
their token tuple and indexed in a token-granular radix trie, so an
arriving request deduplicates against the LONGEST registered prefix of
its full prompt automatically — no caller-passed ``prefix=`` tuple
required (the explicit API registers its page at the caller's stated
length; the engine's auto path registers at chunk-aligned lengths so
requests that split prefix/prompt differently converge on the same
keys). A page installed into a slot is a VALUE copy (the install is a
``jnp.where`` inside the prefill executable), so matching a page
shorter than the snapshot it was cut from is safe: positions past the
matched length hold the donor request's stale K/V, which the engine's
attention horizon (``pos <= idx``) can never reach before the sharer's
own chunk writes overwrite them.

EVICTION is LRU-by-last-hit under page pressure (``max_pages``): a
registration that pushes the store past the bound evicts the
least-recently-hit refcount-0 pages first; live pages are never
touched, so a store full of live pages simply runs over its soft
bound — correctness before memory.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class PrefixPage:
    """One shared-prefix K/V snapshot: a batch-1 cache pytree holding
    ``length`` real positions (the tail beyond ``length`` is write-noise
    the attention masks — see `cached_attention`'s chunk mode)."""

    lane: Any                    # batch-1 cache pytree (device arrays)
    length: int                  # real positions held
    refcount: int = 0            # live slots currently built on it
    hits: int = 0                # admissions served (the saved prefills)
    last_hit: int = 0            # LRU stamp (pool tick at last acquire)


class _Node:
    """One radix-trie node (token-granular; chunk alignment is a
    REGISTRATION policy, not a structural constraint — explicit
    ``prefix=`` pages land at arbitrary lengths in the same index)."""

    __slots__ = ("children", "terminal")

    def __init__(self):
        self.children: Dict[int, "_Node"] = {}
        self.terminal = False


class RadixIndex:
    """Longest-prefix matcher over registered token tuples.

    ``insert``/``remove`` maintain the trie; ``match(tokens, max_len)``
    returns the longest registered key that is a prefix of ``tokens``
    with length <= ``max_len`` (None when nothing matches). All walks
    are O(len(tokens)) dict hops — host-side bookkeeping, never on the
    dispatch path.
    """

    def __init__(self):
        self._root = _Node()
        self._n = 0

    def __len__(self) -> int:
        return self._n

    def insert(self, key: Tuple[int, ...]) -> None:
        node = self._root
        for t in key:
            node = node.children.setdefault(int(t), _Node())
        if not node.terminal:
            node.terminal = True
            self._n += 1

    def remove(self, key: Tuple[int, ...]) -> None:
        path = [self._root]
        for t in key:
            node = path[-1].children.get(int(t))
            if node is None:
                return
            path.append(node)
        if not path[-1].terminal:
            return
        path[-1].terminal = False
        self._n -= 1
        # prune now-empty suffix nodes so dead keys cost no memory
        for depth in range(len(key), 0, -1):
            node = path[depth]
            if node.children or node.terminal:
                break
            del path[depth - 1].children[int(key[depth - 1])]

    def match(self, tokens, max_len: int) -> Optional[Tuple[int, ...]]:
        node = self._root
        best = 0
        for depth, t in enumerate(tokens):
            if depth >= max_len:
                break
            node = node.children.get(int(t))
            if node is None:
                break
            if node.terminal:
                best = depth + 1
        if best == 0:
            return None
        return tuple(int(t) for t in tokens[:best])


class KVPool:
    """Slot allocator + radix-matched prefix-page store over one pooled
    cache pytree.

    The device pytree itself is handed back and forth with the engine
    (its jitted calls donate and return it); the pool only tracks lane
    ownership. ``alloc``/``free`` are O(1) against a free list — the
    admission policy (who gets the slot) lives in `serving.scheduler`.
    """

    def __init__(self, make_cache, max_slots: int, max_len: int,
                 dtype=None, max_pages: Optional[int] = None):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.max_pages = None if max_pages is None else int(max_pages)
        kw = {} if dtype is None else {"dtype": dtype}
        self.cache = make_cache(self.max_slots, self.max_len, **kw)
        # a zeroed batch-1 lane: installed on admission so a fresh
        # request never attends a retired request's stale K/V through a
        # masking bug — defense in depth, the horizon mask already
        # excludes unwritten positions
        self.zeros_lane = jax.tree_util.tree_map(
            lambda x: jnp.zeros((1,) + x.shape[1:], x.dtype), self.cache)
        self._free: List[int] = list(range(self.max_slots))
        # slot -> prefix keys it holds refs on (a slot that MATCHED one
        # page and REGISTERED a longer one holds two)
        self._slot_prefix: Dict[int, List[tuple]] = {}
        self._prefixes: Dict[tuple, PrefixPage] = {}
        self._radix = RadixIndex()
        self._tick = 0               # LRU clock (acquires only)
        self._version = 0            # bumps on register/evict — lets
        #                              match() consumers cache probes

    # ---- slots ----------------------------------------------------------

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> float:
        return 1.0 - len(self._free) / self.max_slots

    def alloc(self) -> Optional[int]:
        """Lowest free slot, or None when the pool is full."""
        return self._free.pop(0) if self._free else None

    def free(self, slot: int) -> None:
        if slot in self._free:
            raise ValueError(f"slot {slot} double-freed")
        if not 0 <= slot < self.max_slots:
            raise ValueError(f"slot {slot} out of range")
        for key in self._slot_prefix.pop(slot, []):
            self.release_prefix(key)
        self._free.append(slot)
        self._free.sort()

    @property
    def store_version(self) -> int:
        """Monotonic page-store version (bumped by register/evict) —
        the invalidation token for consumers caching `match` probes
        (the engine's prefix-aware admission)."""
        return self._version

    def lane_bytes(self) -> int:
        """HBM bytes of ONE slot's lane (the unit the int8 capacity
        tier halves — `perf_model.kv_cache_bytes` is the analytic
        mirror)."""
        return sum(x.nbytes for x in
                   jax.tree_util.tree_leaves(self.zeros_lane))

    def pool_bytes(self) -> int:
        """HBM bytes of the whole pooled cache pytree."""
        return sum(x.nbytes for x in
                   jax.tree_util.tree_leaves(self.cache))

    # ---- prefix pages ---------------------------------------------------

    def has_prefix(self, key: tuple) -> bool:
        return tuple(key) in self._prefixes

    def get_prefix(self, key: tuple) -> Optional[PrefixPage]:
        """Exact-tuple page lookup (no radix walk) — the engine's
        explicit-``prefix=`` path when the radix matcher is disabled."""
        return self._prefixes.get(tuple(key))

    def match(self, tokens, max_len: int
              ) -> Tuple[Optional[tuple], Optional[PrefixPage]]:
        """Longest registered prefix of ``tokens`` not exceeding
        ``max_len`` positions (the engine caps at ``len(tokens) - 1``
        so a full-prompt hit still leaves one real token to sample
        from). Returns ``(key, page)`` or ``(None, None)``."""
        key = self._radix.match(tokens, int(max_len))
        if key is None:
            return None, None
        return key, self._prefixes[key]

    def put_prefix(self, key: tuple, lane, length: int) -> PrefixPage:
        """Register a computed prefix snapshot. ``lane`` is a batch-1
        cache pytree (the engine slices it out of the pool right after
        the prefix chunks complete). Registration may evict
        least-recently-hit refcount-0 pages past ``max_pages``."""
        key = tuple(key)
        if key in self._prefixes:
            raise ValueError(f"prefix {key!r} already registered")
        page = PrefixPage(lane=lane, length=int(length),
                          last_hit=self._tick)
        self._prefixes[key] = page
        self._radix.insert(key)
        self._version += 1
        # the page being registered is refcount-0 until its owner
        # acquires it — excluding it here keeps put-then-acquire (the
        # engine's _register_page) from evicting its own page when
        # every OTHER page is live (review finding)
        self.evict_lru(exclude=key)
        return page

    def acquire_prefix(self, key: tuple, slot: int) -> PrefixPage:
        """Refcount++ on behalf of ``slot`` (released by `free`)."""
        key = tuple(key)
        page = self._prefixes[key]
        page.refcount += 1
        page.hits += 1
        self._tick += 1
        page.last_hit = self._tick
        self._slot_prefix.setdefault(slot, []).append(key)
        return page

    def release_prefix(self, key: tuple) -> None:
        page = self._prefixes[tuple(key)]
        if page.refcount <= 0:
            raise ValueError(f"prefix {key!r} released below zero")
        page.refcount -= 1

    def evict_prefix(self, key: tuple, force: bool = False) -> bool:
        """Drop a prefix page (reclaim its host/device memory). A page
        with live references is NEVER freed: returns False (or raises
        with ``force=True`` — force still refuses; it exists so callers
        who believe the page is dead fail loudly instead of silently
        keeping it)."""
        key = tuple(key)
        page = self._prefixes.get(key)
        if page is None:
            return False
        if page.refcount > 0:
            if force:
                raise RuntimeError(
                    f"prefix {key!r} has {page.refcount} live slot(s) — "
                    f"refusing to free a live page")
            return False
        del self._prefixes[key]
        self._radix.remove(key)
        self._version += 1
        return True

    def evict_lru(self, exclude: Optional[tuple] = None) -> int:
        """Walk the store back under ``max_pages``: evict refcount-0
        pages least-recently-hit first. Live pages are skipped (never
        freed), so the bound is soft under all-live pressure; so is a
        page named by ``exclude`` (a just-registered page whose owner
        has not acquired it yet). Returns pages evicted."""
        if self.max_pages is None:
            return 0
        evicted = 0
        while len(self._prefixes) > self.max_pages:
            dead = [(p.last_hit, k) for k, p in self._prefixes.items()
                    if p.refcount == 0 and k != exclude]
            if not dead:
                break                      # all live: soft bound
            _, dead_key = min(dead)
            self.evict_prefix(dead_key)
            evicted += 1
        return evicted

    def prefix_stats(self) -> dict:
        return {repr(k): {"length": p.length, "refcount": p.refcount,
                          "hits": p.hits, "last_hit": p.last_hit}
                for k, p in self._prefixes.items()}


@dataclasses.dataclass
class PagedPrefix:
    """One shared prefix in the PAGED store: not a K/V snapshot but a
    tuple of page ids into the pool — sharers attend the SAME pages the
    donor wrote (reference sharing; the dense store's copy-on-admit
    install is gone). ``length`` is page-aligned by construction."""

    page_ids: Tuple[int, ...]
    length: int
    refcount: int = 0            # live slots currently built on it
    hits: int = 0
    last_hit: int = 0


class PageAllocator:
    """Refcounted free-list over a fixed page pool — the page-granular
    alloc core shared by `PagedKVPool` (K/V pages) and
    `serving.lora.LoraAdapterStore` (adapter pages).  Page 0 is reserved
    (the trash/zero page): it is never handed out, and unref of it is a
    no-op, so all-zero block-table rows are always safe."""

    def __init__(self, num_pages: int):
        self.num_pages = int(num_pages)
        self.refs = [0] * self.num_pages
        self.free_list: List[int] = list(range(1, self.num_pages))

    def take(self) -> int:
        """Pop the lowest free page with refcount 1."""
        if not self.free_list:
            raise RuntimeError(
                "page pool out of pages — sizing invariant broken")
        pid = self.free_list.pop(0)
        self.refs[pid] = 1
        return pid

    def ref(self, pid: int) -> None:
        self.refs[pid] += 1

    def unref(self, pid: int) -> None:
        if pid == 0:
            return
        self.refs[pid] -= 1
        if self.refs[pid] < 0:
            raise ValueError(f"page {pid} refcount below zero")
        if self.refs[pid] == 0:
            self.free_list.append(pid)
            self.free_list.sort()

    @property
    def n_free(self) -> int:
        return len(self.free_list)


class PagedKVPool:
    """Page-granular slot allocator + radix-matched prefix store.

    The device pytree is ``{"layer{i}": {"k","v": (num_pages, Hkv,
    page_size, D)}}`` — one POOL of pages shared by every slot, wired
    through per-slot block tables (host numpy here; the engine patches
    a device mirror at admission/retire boundaries only, so the decode
    dispatch path stays host-free). Page 0 is the TRASH page: freed
    slots' block-table rows point at it, inactive decode rows scatter
    their garbage there, and nothing ever attends it.

    Differences from the dense `KVPool`, by design:

    - ``alloc`` hands out a slot AND populates its block-table row with
      freshly owned pages for the full lane (sizing in ``__init__``
      guarantees this never fails — no per-step page faults, the
      steady-state decode loop stays dispatch-only).
    - prefix pages are SHARED by id, not installed by value:
      ``acquire_prefix`` swaps the shared ids into the slot's row
      (releasing the owned pages they displace) — admission pays zero
      K/V copies for a hit, and ``register_prefix`` simply pins the
      registrant's own pages (zero copies there too).
    - every page carries a refcount = block-table rows + registry
      entries holding it; a shared page is freed only when BOTH the
      last sharing slot retires and the registry entry is evicted
      (`test_paged_decode::TestPagedPool`).

    The prefix-entry API (match/has/get/acquire/release/evict/stats,
    ``store_version``) mirrors the dense pool so the engine's admission
    logic is pool-agnostic.
    """

    #: paged mode has no install step (sharing is by page id, recycled
    #: garbage sits past the horizon mask) — the engine's pool-agnostic
    #: admission passes this through and the paged prefill ignores it
    zeros_lane = None

    def __init__(self, make_cache, max_slots: int, lane_len: int,
                 page_size: int, dtype=None,
                 max_pages: Optional[int] = None):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.pages_per_lane = -(-int(lane_len) // self.page_size)
        self.lane_len = self.pages_per_lane * self.page_size
        self.max_pages = None if max_pages is None else int(max_pages)
        entries_cap = (self.max_slots if self.max_pages is None
                       else self.max_pages)
        # worst case: every slot owns a full lane AND every registry
        # entry pins a full lane of retired-donor pages (+1 trash) —
        # sized so page allocation can NEVER fail mid-admission
        self.num_pages = 1 + (self.max_slots + entries_cap
                              ) * self.pages_per_lane
        kw = {} if dtype is None else {"dtype": dtype}
        self.pages = make_cache(self.num_pages, self.page_size, **kw)
        self.block_tables = [[0] * self.pages_per_lane
                             for _ in range(self.max_slots)]
        self._alloc = PageAllocator(self.num_pages)
        self._free: List[int] = list(range(self.max_slots))
        self._slot_prefix: Dict[int, List[tuple]] = {}
        self._prefixes: Dict[tuple, PagedPrefix] = {}
        self._radix = RadixIndex()
        self._tick = 0
        self._version = 0

    # ---- pages ----------------------------------------------------------

    # page alloc delegates to the shared PageAllocator core (also used
    # by serving.lora.LoraAdapterStore); the legacy private names stay
    # as views so existing tests/introspection keep working

    def _take_page(self) -> int:
        return self._alloc.take()

    def _ref_page(self, pid: int) -> None:
        self._alloc.ref(pid)

    def _unref_page(self, pid: int) -> None:
        self._alloc.unref(pid)

    def page_refcount(self, pid: int) -> int:
        return self._alloc.refs[pid]

    @property
    def _page_refs(self) -> List[int]:
        return self._alloc.refs

    @property
    def _free_pages(self) -> List[int]:
        return self._alloc.free_list

    @property
    def n_free_pages(self) -> int:
        return self._alloc.n_free

    # ---- slots ----------------------------------------------------------

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> float:
        return 1.0 - len(self._free) / self.max_slots

    def alloc(self) -> Optional[int]:
        """Lowest free slot, its block-table row populated with a full
        lane of freshly owned pages."""
        if not self._free:
            return None
        slot = self._free.pop(0)
        self.block_tables[slot] = [self._take_page()
                                   for _ in range(self.pages_per_lane)]
        return slot

    def free(self, slot: int) -> None:
        if slot in self._free:
            raise ValueError(f"slot {slot} double-freed")
        if not 0 <= slot < self.max_slots:
            raise ValueError(f"slot {slot} out of range")
        for key in self._slot_prefix.pop(slot, []):
            self.release_prefix(key)
        for pid in self.block_tables[slot]:
            self._unref_page(pid)
        self.block_tables[slot] = [0] * self.pages_per_lane
        self._free.append(slot)
        self._free.sort()

    @property
    def store_version(self) -> int:
        return self._version

    def lane_bytes(self) -> int:
        """HBM bytes of one slot's worth of pages (`pool_bytes` /
        physical pages × pages-per-lane)."""
        total = sum(x.nbytes for x in
                    jax.tree_util.tree_leaves(self.pages))
        return total // self.num_pages * self.pages_per_lane

    def pool_bytes(self) -> int:
        return sum(x.nbytes for x in
                   jax.tree_util.tree_leaves(self.pages))

    # ---- prefix pages ---------------------------------------------------

    def has_prefix(self, key: tuple) -> bool:
        return tuple(key) in self._prefixes

    def get_prefix(self, key: tuple) -> Optional[PagedPrefix]:
        return self._prefixes.get(tuple(key))

    def match(self, tokens, max_len: int
              ) -> Tuple[Optional[tuple], Optional[PagedPrefix]]:
        key = self._radix.match(tokens, int(max_len))
        if key is None:
            return None, None
        return key, self._prefixes[key]

    def register_prefix(self, slot: int, key: tuple,
                        length: int) -> Optional[PagedPrefix]:
        """Pin ``slot``'s first pages as a shared prefix — the paged
        analog of the dense pool's ``put_prefix``, with NO copy: the
        registry entry takes a reference on the registrant's own pages
        (they outlive the slot). ``length`` floors to a page multiple
        (sub-page tails hold registrant-specific tokens sharers must
        re-compute); returns None when nothing page-aligned remains."""
        key = tuple(key)
        if key in self._prefixes:
            raise ValueError(f"prefix {key!r} already registered")
        n = int(length) // self.page_size
        if n == 0:
            return None
        ids = tuple(self.block_tables[slot][:n])
        for pid in ids:
            self._ref_page(pid)
        page = PagedPrefix(page_ids=ids, length=n * self.page_size,
                           last_hit=self._tick)
        self._prefixes[key] = page
        self._radix.insert(key)
        self._version += 1
        self.evict_lru(exclude=key)
        return page

    def acquire_prefix(self, key: tuple, slot: int) -> PagedPrefix:
        """Build ``slot`` on a shared prefix: swap the entry's page ids
        into the slot's block-table row (releasing the owned pages they
        displace) and take the usual entry refcount. For the slot that
        just registered its OWN pages this is a pure bookkeeping no-op
        (the ids already match) — one code path for donor and sharers."""
        key = tuple(key)
        page = self._prefixes[key]
        row = self.block_tables[slot]
        for i, pid in enumerate(page.page_ids):
            if row[i] != pid:
                self._unref_page(row[i])
                row[i] = pid
                self._ref_page(pid)
        page.refcount += 1
        page.hits += 1
        self._tick += 1
        page.last_hit = self._tick
        self._slot_prefix.setdefault(slot, []).append(key)
        return page

    def release_prefix(self, key: tuple) -> None:
        page = self._prefixes[tuple(key)]
        if page.refcount <= 0:
            raise ValueError(f"prefix {key!r} released below zero")
        page.refcount -= 1

    def evict_prefix(self, key: tuple, force: bool = False) -> bool:
        """Drop a registry entry and its page references; the pages
        themselves are freed only if no slot still shares them (the
        refcount test's central property). Same live-entry refusal
        semantics as the dense pool."""
        key = tuple(key)
        page = self._prefixes.get(key)
        if page is None:
            return False
        if page.refcount > 0:
            if force:
                raise RuntimeError(
                    f"prefix {key!r} has {page.refcount} live slot(s) — "
                    f"refusing to free a live page")
            return False
        del self._prefixes[key]
        self._radix.remove(key)
        for pid in page.page_ids:
            self._unref_page(pid)
        self._version += 1
        return True

    def evict_lru(self, exclude: Optional[tuple] = None) -> int:
        if self.max_pages is None:
            return 0
        evicted = 0
        while len(self._prefixes) > self.max_pages:
            dead = [(p.last_hit, k) for k, p in self._prefixes.items()
                    if p.refcount == 0 and k != exclude]
            if not dead:
                break
            _, dead_key = min(dead)
            self.evict_prefix(dead_key)
            evicted += 1
        return evicted

    def prefix_stats(self) -> dict:
        return {repr(k): {"length": p.length, "refcount": p.refcount,
                          "hits": p.hits, "last_hit": p.last_hit,
                          "pages": list(p.page_ids)}
                for k, p in self._prefixes.items()}
