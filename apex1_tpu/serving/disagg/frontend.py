"""Disaggregated prefill/decode serving — two `ServingFrontend` pools
behind one submit/poll surface (ROADMAP item 1's last serving rung;
docs/serving.md § Disaggregated serving).

Why split: long-prompt prefills head-of-line-block decode steady state
when every replica serves both phases — one 8-chunk prefill stalls
every resident decode stream on that replica for 8 rounds. The split
gives each phase its own pool:

- **Phase-aware routing**: an admission goes to the PREFILL pool
  unless (a) the decode pool's pool-local radix index already holds
  the prompt's full chunk-aligned share point (a full-prompt hit — the
  prefill pool is skipped entirely), or (b) the prompt is too short to
  ever produce a transferable page (share point < one chunk), in which
  case its prefill is a single chunk and rides the decode admission
  round harmlessly.
- **KV handoff**: the prefill leg runs the prompt as a
  ``max_new_tokens=1`` request — it samples token 0 and retires at
  prefill, leaving the chunk-aligned prefix page in its engine's
  store. The page moves to the decode pool through
  `kv_transfer` (departure digest → transfer → ARRIVAL re-digest →
  install), then the request is resubmitted to the decode pool with
  its ORIGINAL budget, same id, same pinned seed: the decode engine
  radix-hits the installed page, prefills only the remainder, and
  regenerates token 0 bit-identically (counter-keyed sampling, PR 7) —
  so the handed-off stream equals solo generate at any temperature,
  and the router asserts token 0 agreement per handoff as a tripwire.
- **Failure = re-route, never strand**: a corrupt/torn page
  (`HandoffError`) or a source replica dying in the handoff window
  (`ReplicaKilled` from a chaos `on_handoff` hook) re-routes the
  request — radix-hit skip if the page already landed, re-prefill on a
  survivor otherwise, decode-pool full re-prefill as the last resort —
  bounded by ``max_handoff_attempts`` (then a LOUD eviction, not a
  hang). `handoff_failures` / `handoff_reroutes` ride the always-
  present 0-counters contract.

QoS/hedging/failover carry over verbatim because each pool IS a full
`ServingFrontend`: displacement, hedged dispatch, watchdog restarts
and failover all run per pool, per leg. The disagg layer adds its own
end-to-end lifecycle record per request (queued at admission,
first_token when the prefill leg lands = TTFT is prefill-pool
pressure; terminal at the decode result = TPOT is decode-pool
pressure) — the windowed TTFT/TPOT split per QoS class that drives the
autopilot's pool-ratio actuator (`shift_pool`, docs/autopilot.md).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from apex1_tpu.serving.disagg.kv_transfer import (HandoffError, KVPage,
                                                  extract_page,
                                                  install_page)
from apex1_tpu.serving.engine import (Engine, RequestResult,
                                      derive_request_seed)
from apex1_tpu.serving.frontend import (MODES, FrontendConfig,
                                        ServingFrontend)
from apex1_tpu.serving.metrics import TERMINAL, ServingMetrics
from apex1_tpu.serving.replica import ReplicaKilled, Submission
from apex1_tpu.serving.scheduler import (Backpressure, new_request_id,
                                         qos_rank)


@dataclasses.dataclass
class DisaggConfig:
    """Two pool configs + the handoff knobs. Both pools MUST be built
    from the same ``make_engine`` (same geometry, same params) — the
    page lane's shapes/dtypes are part of the handoff's manifest
    contract and a geometry mismatch is a typed arrival failure, not a
    supported mode."""

    prefill: FrontendConfig = dataclasses.field(
        default_factory=lambda: FrontendConfig(n_replicas=1))
    decode: FrontendConfig = dataclasses.field(
        default_factory=lambda: FrontendConfig(n_replicas=1))
    prefill_chunk: int = 16        # the ENGINE's chunk size — the
    #  router computes the chunk-aligned share point with it, so it
    #  must match EngineConfig.prefill_chunk
    handoff_latency_s: float = 0.0  # simulated/expected transfer time:
    #  a completed prefill's page is held this long (virtual clock in
    #  fleetsim) before arrival verification + decode admission
    max_handoff_attempts: int = 5  # re-routes per request before a
    #  loud eviction (the anti-crash-loop bound, same idea as the
    #  supervisor's poison threshold)
    seed: int = 0                  # base for derived per-request seeds
    metrics_window: int = 128      # disagg-level rolling ring (the
    #                                pool-ratio actuator's signal)


class DisaggFrontend:
    """Prefill pool + decode pool behind the `ServingFrontend` call
    surface (submit / poll / pop_result / pump / run_until_drained /
    cancel / summary / actuation knobs). ``fault`` sees both pools'
    replica hooks AND the handoff window (`ServingFault.on_handoff`).
    """

    def __init__(self, make_engine: Callable[..., Engine],
                 config: Optional[DisaggConfig] = None, *,
                 fault=None,
                 clock: Optional[Callable[[], float]] = None):
        self.cfg = cfg = config or DisaggConfig()
        if cfg.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.clock = clock or time.monotonic
        self.metrics = ServingMetrics(window=cfg.metrics_window,
                                      clock=self.clock)
        self._fault = fault
        self.prefill = ServingFrontend(make_engine, cfg.prefill,
                                       fault=fault, clock=clock)
        self.decode = ServingFrontend(make_engine, cfg.decode,
                                      fault=fault, clock=clock)
        self._subs: Dict[int, Submission] = {}     # original contracts
        self._live: set = set()
        self._phase: Dict[int, str] = {}   # prefill | handoff | decode
        self._direct: set = set()          # routed straight to decode
        self._tok0: Dict[int, int] = {}    # prefill leg's token 0
        self._attempts: Dict[int, int] = {}
        self._pending: List[Tuple[float, int, KVPage]] = []  # in transit
        self._deferred: List[Tuple[str, int]] = []  # backpressured legs
        self._ttft_marked: set = set()
        self._terminal: Dict[int, RequestResult] = {}
        self._admission_limit: Optional[int] = None
        self.mode_control = "load"         # property: fans to pools

    # ---- lifecycle ------------------------------------------------------

    def start(self) -> "DisaggFrontend":
        self.prefill.start()
        self.decode.start()
        return self

    def stop(self) -> None:
        self.prefill.stop()
        self.decode.stop()

    # ---- submission -----------------------------------------------------

    def submit(self, tokens, max_new_tokens: int, *,
               qos: str = "best_effort", tenant: Optional[str] = None,
               deadline: Optional[float] = None, prefix=None,
               seed: Optional[int] = None,
               req_id: Optional[int] = None) -> int:
        """Admit + phase-route one request. The seed is pinned HERE
        (disagg level) so the prefill leg, the decode leg, and every
        re-route regenerate the identical stream. Raises `Backpressure`
        on the disagg admission limit or from the target pool."""
        qos_rank(qos)
        now = self.clock()
        rid = new_request_id() if req_id is None else int(req_id)
        if seed is None:
            seed = derive_request_seed(self.cfg.seed, rid)
        seed = int(seed) & 0x7FFFFFFF
        if (self._admission_limit is not None
                and self.total_inflight >= self._admission_limit):
            raise self._reject(
                rid, now, qos, tenant,
                f"admission limit ({self._admission_limit})",
                retry_after_s=0.05 * max(1.0, self.load_fraction))
        sub = Submission(
            tokens=np.asarray(tokens, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens), req_id=rid,
            seed=int(seed), prefix=prefix, deadline=deadline, qos=qos,
            tenant=tenant, submitted_at=now)
        route = self._route_for(sub)
        # the disagg-level lifecycle record: END-TO-END TTFT/TPOT per
        # class, surviving every pool-internal restart/failover/reroute
        self.metrics.event(rid, "queued", now=now,
                           n_prompt=int(sub.tokens.size), qos=qos,
                           tenant=tenant)
        try:
            if route == "decode":
                self.decode.submit(
                    sub.tokens, max_new_tokens=sub.max_new_tokens,
                    qos=qos, tenant=tenant, deadline=deadline,
                    prefix=prefix, seed=seed, req_id=rid)
            else:
                # the prefill LEG: sample token 0, retire at prefill,
                # leave the page behind for the handoff
                self.prefill.submit(
                    sub.tokens, max_new_tokens=1, qos=qos,
                    tenant=tenant, deadline=deadline, prefix=prefix,
                    seed=seed, req_id=rid)
        except Backpressure:
            self.metrics.event(rid, "rejected", now=now,
                               reason=f"{route} pool backpressure")
            raise
        self._subs[rid] = sub
        self._live.add(rid)
        self._phase[rid] = route if route == "prefill" else "decode"
        if route == "decode":
            self._direct.add(rid)
        self.metrics.event(rid, "prefill", now=now, route=route)
        return rid

    def cancel(self, req_id: int) -> bool:
        if req_id in self._terminal or req_id not in self._live:
            return False
        ph = self._phase.get(req_id)
        if ph == "prefill":
            return self.prefill.cancel(req_id)
        if ph == "decode":
            return self.decode.cancel(req_id)
        # parked in the handoff window: no pool owns it — settle here
        self._pending = [p for p in self._pending if p[1] != req_id]
        self._deferred = [d for d in self._deferred if d[1] != req_id]
        self._finish(req_id, RequestResult(
            req_id=req_id, status="cancelled",
            tokens=np.zeros((0,), np.int32),
            reason="cancelled in handoff window"))
        return True

    # ---- results --------------------------------------------------------

    def poll(self, req_id: int) -> Optional[RequestResult]:
        return self._terminal.get(req_id)

    def pop_result(self, req_id: int) -> Optional[RequestResult]:
        res = self._terminal.pop(req_id, None)
        if res is not None:
            self._subs.pop(req_id, None)
            self._tok0.pop(req_id, None)
            self._attempts.pop(req_id, None)
            self._direct.discard(req_id)
        return res

    @property
    def results(self) -> Dict[int, RequestResult]:
        return dict(self._terminal)

    # ---- the supervision tick -------------------------------------------

    def pump(self, rounds: int = 1) -> None:
        """One supervision round x ``rounds``: pump both pools (their
        own watchdogs/restarts/hedges/ladders), then run the handoff
        state machine — collect finished prefill legs, deliver pages
        whose transfer latency elapsed, retry backpressured legs, stamp
        TTFTs, collect decode results."""
        for _ in range(rounds):
            self.prefill.pump(1)
            self.decode.pump(1)
            now = self.clock()
            self._collect_prefill(now)
            self._process_pending(now)
            self._retry_deferred(now)
            self._observe_first_tokens()
            self._collect_decode()

    def run_until_drained(self, *, timeout_s: float = 60.0,
                          max_rounds: int = 100_000
                          ) -> Dict[int, RequestResult]:
        t0 = time.monotonic()
        for _ in range(max_rounds):
            if not self._live:
                return self.results
            if time.monotonic() - t0 > timeout_s:
                break
            self.pump()
        if self._live:
            raise TimeoutError(
                f"undrained after {time.monotonic() - t0:.1f}s "
                f"(budget {timeout_s}s/{max_rounds} rounds): "
                f"{sorted(self._live)} "
                f"(phases: { {r: self._phase.get(r) for r in sorted(self._live)} }, "
                f"states: {self.replica_states()})")
        return self.results

    # ---- routing --------------------------------------------------------

    def _full(self, sub: Submission) -> np.ndarray:
        if sub.prefix:
            return np.concatenate([
                np.asarray(sub.prefix, np.int32).reshape(-1),
                sub.tokens])
        return sub.tokens

    def _handoff_key(self, sub: Submission
                     ) -> Tuple[Optional[tuple], int]:
        """The page key the prefill leg leaves behind: the explicit
        ``prefix`` when one was given (the engine's PR-7 exact-tuple
        contract), else the chunk-aligned share point of the full
        prompt. ``(None, 0)`` when the prompt is too short to produce
        a page."""
        if sub.prefix:
            return (tuple(int(t) for t in sub.prefix),
                    len(tuple(sub.prefix)))
        full = sub.tokens
        C = self.cfg.prefill_chunk
        lstar = ((int(full.size) - 1) // C) * C
        if lstar < C:
            return None, 0
        return tuple(int(t) for t in full[:lstar]), lstar

    def _decode_has(self, key: tuple) -> bool:
        """Pool-local radix probe: does ANY routable decode engine
        already hold ``key``?"""
        for rep in self.decode.replicas:
            if rep.state not in ("new", "alive") or rep.engine is None:
                continue
            if rep.engine.kv.has_prefix(key):
                return True
        return False

    def _route_for(self, sub: Submission) -> str:
        """'decode' on a full-prompt radix hit (prefill pool skipped
        entirely) or a prompt too short to produce a page; 'prefill'
        otherwise."""
        key, _length = self._handoff_key(sub)
        if key is None:
            return "decode"
        if self._decode_has(key):
            return "decode"
        return "prefill"

    # ---- the handoff state machine --------------------------------------

    def _collect_prefill(self, now: float):
        for rid in [r for r in list(self._live)
                    if self._phase.get(r) == "prefill"]:
            res = self.prefill.pop_result(rid)
            if res is None:
                continue
            if res.status != "done" or res.tokens.size < 1:
                # shed / evicted / rejected at the prefill pool: the
                # pool's verdict is the request's verdict
                self._finish(rid, res)
                continue
            self._tok0[rid] = int(res.tokens[0])
            if rid not in self._ttft_marked:
                # TTFT == prefill-pool pressure: token 0 exists the
                # moment the prefill leg lands
                self._ttft_marked.add(rid)
                self.metrics.event(rid, "first_token", now=now)
            self._start_handoff(rid, now)

    def _start_handoff(self, rid: int, now: float):
        sub = self._subs[rid]
        key, _length = self._handoff_key(sub)
        if key is None:                    # defensive: routed direct
            self._submit_decode(rid, now)
            return
        src = None
        for rep in self.prefill.replicas:
            if (rep.state in ("new", "alive") and rep.engine is not None
                    and rep.engine.kv.has_prefix(key)):
                src = rep
                break
        try:
            if src is None:
                raise HandoffError(
                    f"request {rid}: page ({len(key)} tokens) on no "
                    f"alive prefill replica")
            page = extract_page(src.engine, key)
            if self._fault is not None:
                # the handoff WINDOW: prefill completed, decode has not
                # acknowledged — chaos kills/corruption land here
                self._fault.on_handoff(src.replica_id, rid, page)
        except ReplicaKilled as e:
            # source died mid-transfer: its pool supervisor restarts
            # it next pump; THIS request re-routes, never strands
            src._mark_dead(e)
            self._handoff_failed(rid, now, "window_kill", repr(e),
                                 replica=src.replica_id)
            return
        except HandoffError as e:
            self._handoff_failed(rid, now, "integrity", str(e))
            return
        if self.cfg.handoff_latency_s > 0:
            self._phase[rid] = "handoff"
            self._pending.append(
                (now + self.cfg.handoff_latency_s, rid, page))
        else:
            self._deliver(rid, page, now)

    def _process_pending(self, now: float):
        ready = [p for p in self._pending if p[0] <= now]
        if not ready:
            return
        self._pending = [p for p in self._pending if p[0] > now]
        for _t, rid, page in ready:
            if rid in self._live:
                self._deliver(rid, page, now)

    def _deliver(self, rid: int, page: KVPage, now: float):
        """Arrival: re-digest, install into the decode replica the
        router predicts will take the request (same least-loaded pick
        `submit` makes), resubmit with the original budget."""
        sub = self._subs[rid]
        tgt = self.decode._pick_replica(sub.max_new_tokens,
                                        sub.deadline, now)
        try:
            if tgt is not None and tgt.engine is not None:
                installed = install_page(tgt.engine, page)
            else:
                # nothing to install into yet (replica engine not
                # built / no feasible target): the arrival gate still
                # runs — a corrupt page must fail HERE, typed
                from apex1_tpu.serving.disagg.kv_transfer import \
                    verify_page
                verify_page(page)
                installed = False
        except HandoffError as e:
            self._handoff_failed(rid, now, "integrity", str(e))
            return
        self.metrics.incr("handoffs")
        self.metrics.transition(
            "handoff", req=rid, page_tokens=page.length,
            to_replica=(None if tgt is None else tgt.replica_id),
            installed=bool(installed),
            attempt=self._attempts.get(rid, 0))
        self._submit_decode(rid, now)

    def _handoff_failed(self, rid: int, now: float, kind: str,
                        why: str, **fields):
        self.metrics.incr("handoff_failures")
        # field named `failure`, not `kind` — the obs spine reserves
        # `kind` for the record type
        self.metrics.transition("handoff_failure", req=rid,
                                failure=kind, reason=why, **fields)
        self._reroute(rid, now, why)

    def _reroute(self, rid: int, now: float, why: str):
        """The never-strand contract: radix-hit skip if the page
        already lives in the decode pool, re-prefill on a survivor
        otherwise, decode-pool full re-prefill when the prefill pool
        has no routable replica — bounded by ``max_handoff_attempts``,
        then a loud eviction."""
        n = self._attempts.get(rid, 0) + 1
        self._attempts[rid] = n
        if n > self.cfg.max_handoff_attempts:
            self._finish(rid, RequestResult(
                req_id=rid, status="evicted",
                tokens=np.zeros((0,), np.int32),
                reason=f"handoff failed after {n - 1} attempts: {why}"))
            return
        self.metrics.incr("handoff_reroutes")
        self.metrics.transition("handoff_reroute", req=rid, attempt=n,
                                reason=why)
        sub = self._subs[rid]
        key, _length = self._handoff_key(sub)
        if key is not None and self._decode_has(key):
            # an earlier attempt's page landed: radix-hit skip
            self._submit_decode(rid, now)
        elif self.prefill._alive():
            self._resubmit_prefill(rid, now)
        else:
            # no prefill survivor THIS round: the decode pool
            # re-prefills the whole prompt — slower, never stranded
            self._submit_decode(rid, now)

    def _resubmit_prefill(self, rid: int, now: float):
        sub = self._subs[rid]
        if sub.deadline is not None and now > sub.deadline:
            self._finish(rid, RequestResult(
                req_id=rid, status="evicted",
                tokens=np.zeros((0,), np.int32),
                reason="deadline passed during handoff re-route"))
            return
        try:
            self.prefill.submit(
                sub.tokens, max_new_tokens=1, qos=sub.qos,
                tenant=sub.tenant, deadline=sub.deadline,
                prefix=sub.prefix, seed=sub.seed, req_id=rid)
            self._phase[rid] = "prefill"
        except Backpressure:
            self._phase[rid] = "handoff"
            self._deferred.append(("prefill", rid))

    def _submit_decode(self, rid: int, now: float):
        sub = self._subs[rid]
        if sub.deadline is not None and now > sub.deadline:
            self._finish(rid, RequestResult(
                req_id=rid, status="evicted",
                tokens=np.zeros((0,), np.int32),
                reason="deadline passed awaiting decode admission"))
            return
        try:
            self.decode.submit(
                sub.tokens, max_new_tokens=sub.max_new_tokens,
                qos=sub.qos, tenant=sub.tenant, deadline=sub.deadline,
                prefix=sub.prefix, seed=sub.seed, req_id=rid)
            self._phase[rid] = "decode"
        except Backpressure:
            self._phase[rid] = "handoff"
            self._deferred.append(("decode", rid))

    def _retry_deferred(self, now: float):
        pending, self._deferred = self._deferred, []
        for stage, rid in pending:
            if rid not in self._live:
                continue
            if stage == "decode":
                self._submit_decode(rid, now)
            else:
                self._resubmit_prefill(rid, now)

    def _observe_first_tokens(self):
        """Direct-decode routes never pass through the prefill-leg
        collection: stamp their TTFT from the decode pool's own
        lifecycle record (exact pool timestamp)."""
        for rid in list(self._direct):
            if rid in self._ttft_marked or rid not in self._live:
                continue
            rec = self.decode.metrics.records.get(rid)
            if rec is not None and rec.t_first_token is not None:
                self._ttft_marked.add(rid)
                self.metrics.event(rid, "first_token",
                                   now=rec.t_first_token)

    def _collect_decode(self):
        for rid in [r for r in list(self._live)
                    if self._phase.get(r) == "decode"]:
            res = self.decode.pop_result(rid)
            if res is None:
                continue
            tok0 = self._tok0.get(rid)
            if (tok0 is not None and res.status == "done"
                    and res.tokens.size
                    and int(res.tokens[0]) != tok0):
                # the per-handoff parity tripwire: counter-keyed
                # sampling makes the decode pool regenerate the
                # prefill leg's token 0 — a mismatch means the stream
                # diverged and must be LOUD, not a quiet wrong answer
                self.metrics.incr("handoff_parity_mismatches")
                self.metrics.transition(
                    "handoff_parity_mismatch", req=rid,
                    prefill_tok0=tok0,
                    decode_tok0=int(res.tokens[0]))
            self._finish(rid, res)

    def _finish(self, rid: int, res: RequestResult):
        self._terminal[rid] = res
        self._live.discard(rid)
        self._phase.pop(rid, None)
        self._ttft_marked.discard(rid)
        status = res.status if res.status in TERMINAL else "done"
        self.metrics.event(rid, status, reason=res.reason,
                           n_generated=int(res.tokens.size))

    def _reject(self, rid: int, now: float, qos: str,
                tenant: Optional[str], reason: str, *,
                retry_after_s: float) -> Backpressure:
        self.metrics.event(rid, "queued", now=now, n_prompt=0,
                           qos=qos, tenant=tenant)
        self.metrics.event(rid, "rejected", now=now, reason=reason)
        return Backpressure(reason, queue_depth=self.total_inflight,
                            retry_after_s=retry_after_s)

    # ---- aggregates / introspection -------------------------------------

    @property
    def total_inflight(self) -> int:
        return len(self._live)

    @property
    def capacity(self) -> int:
        cap = self.prefill.capacity + self.decode.capacity
        if self._admission_limit is not None:
            cap = min(cap, self._admission_limit)
        return cap

    @property
    def load_fraction(self) -> float:
        return self.total_inflight / self.capacity

    @property
    def admission_limit(self) -> Optional[int]:
        return self._admission_limit

    @property
    def n_alive(self) -> int:
        return self.prefill.n_alive + self.decode.n_alive

    @property
    def replicas(self) -> list:
        """Both pools' supervisors (read-only aggregate view — ids are
        only unique per pool; pool-level actuation goes through
        `shift_pool` / the per-pool frontends)."""
        return list(self.prefill.replicas) + list(self.decode.replicas)

    @property
    def mode(self) -> str:
        """The worse of the two pools' overload modes."""
        return MODES[max(MODES.index(self.prefill.mode),
                         MODES.index(self.decode.mode))]

    @property
    def mode_control(self) -> str:
        return self._mode_control

    @mode_control.setter
    def mode_control(self, value: str):
        # attaching an Autopilot flips the DISAGG frontend to external
        # control; both pools' built-in load ladders go quiet with it
        self._mode_control = value
        if hasattr(self, "prefill"):
            self.prefill.mode_control = value
            self.decode.mode_control = value

    def replica_states(self) -> dict:
        return {"prefill": self.prefill.replica_states(),
                "decode": self.decode.replica_states()}

    def pool_view(self) -> dict:
        """Per-pool guardrail snapshot for the pool-ratio actuator
        (the PRESSURE signal — windowed TTFT vs TPOT — rides the
        disagg metrics window; this carries liveness and occupancy)."""
        return {
            "prefill": {
                "n_replicas": len(self.prefill.replicas),
                "n_alive": self.prefill.n_alive,
                "inflight": self.prefill.total_inflight,
                "load_fraction": round(self.prefill.load_fraction, 4)},
            "decode": {
                "n_replicas": len(self.decode.replicas),
                "n_alive": self.decode.n_alive,
                "inflight": self.decode.total_inflight,
                "load_fraction": round(self.decode.load_fraction, 4)},
        }

    def summary(self) -> dict:
        """The disagg snapshot: end-to-end metrics (window carries the
        per-class TTFT/TPOT split), handoff counters (0-present), both
        pool summaries under ``pools``, and goodput rates aggregated
        across BOTH pools' current engines — one surface for the
        autopilot and the drills."""
        s = self.metrics.summary()
        s["mode"] = self.mode
        s["mode_history"] = [t for t in self.metrics.transitions
                             if t["event"] == "mode"]
        s["n_replicas"] = len(self.replicas)
        s["n_alive"] = self.n_alive
        s["capacity"] = self.capacity
        s["inflight"] = self.total_inflight
        s["load_fraction"] = round(self.load_fraction, 4)
        s["admission_limit"] = self._admission_limit
        s["pool_view"] = self.pool_view()
        s["pools"] = {"prefill": self.prefill.summary(),
                      "decode": self.decode.summary()}
        agg = {k: 0 for k in ("prefix_lookups", "prefix_hits",
                              "prefix_saved_tokens", "spec_drafted",
                              "spec_accepted")}
        for rep in self.replicas:
            eng = rep.engine
            if eng is None:
                continue
            for k in agg:
                agg[k] += eng.metrics.get_counter(k)
        if agg["prefix_lookups"]:
            s["prefix_hit_rate"] = (agg["prefix_hits"]
                                    / agg["prefix_lookups"])
            s["prefix_saved_tokens"] = agg["prefix_saved_tokens"]
        if agg["spec_drafted"]:
            s["accept_rate"] = agg["spec_accepted"] / agg["spec_drafted"]
        return s

    # ---- the actuation surface (docs/autopilot.md) ----------------------

    def set_mode(self, mode: str, *, by: str = "operator", **evidence):
        """Flip BOTH pools' overload mode (each pool banks its own
        transition; the disagg level banks the aggregate flip)."""
        if mode not in MODES:
            raise ValueError(f"unknown mode {mode!r}; one of {MODES}")
        if mode == self.mode and (self.prefill.mode == mode
                                  and self.decode.mode == mode):
            return
        self.metrics.transition(
            "mode", frm=self.mode, to=mode, by=by,
            load_fraction=round(self.load_fraction, 4), **evidence)
        self.prefill.set_mode(mode, by=by, **evidence)
        self.decode.set_mode(mode, by=by, **evidence)

    def add_replica(self, pool: str = "decode", *,
                    by: str = "operator", **evidence) -> int:
        """Grow one pool by a replica (decode by default — the
        capacity-relief rung; the RATIO actuator is `shift_pool`)."""
        f = self.decode if pool == "decode" else self.prefill
        rid = f.add_replica(by=by, **evidence)
        self.metrics.transition("replica_added", pool=pool,
                                replica=rid, by=by, **evidence)
        return rid

    def retire_replica(self, replica_id: Optional[int] = None,
                       pool: str = "decode", *, by: str = "operator",
                       **evidence) -> Optional[int]:
        f = self.decode if pool == "decode" else self.prefill
        out = f.retire_replica(replica_id, by=by, **evidence)
        if out is not None:
            self.metrics.transition("replica_retiring", pool=pool,
                                    replica=out, by=by, **evidence)
        return out

    def shift_pool(self, to: str, *, by: str = "operator",
                   **evidence) -> Optional[dict]:
        """The pool-RATIO actuator: retire one replica from the donor
        pool, add one to ``to`` — total capacity conserved, the
        TTFT/TPOT balance moves. No-op (None, banked) when the donor
        would drop below one routable replica — each phase always
        keeps a pool."""
        if to not in ("prefill", "decode"):
            raise ValueError(f"to must be 'prefill' or 'decode', "
                             f"got {to!r}")
        frm = "decode" if to == "prefill" else "prefill"
        donor = self.decode if to == "prefill" else self.prefill
        grow = self.prefill if to == "prefill" else self.decode
        retired = donor.retire_replica(by=by, **evidence)
        if retired is None:
            self.metrics.transition("pool_shift", to=to, frm=frm,
                                    result="noop",
                                    reason="donor pool at minimum",
                                    by=by, **evidence)
            return None
        added = grow.add_replica(by=by, **evidence)
        self.metrics.transition("pool_shift", to=to, frm=frm,
                                retired=retired, added=added, by=by,
                                **evidence)
        return {"to": to, "frm": frm, "retired": retired,
                "added": added}

    def set_admission_limit(self, limit: Optional[int], *,
                            by: str = "operator", **evidence):
        """End-to-end admission setpoint (checked at the disagg door —
        each pool keeps its own structural capacity)."""
        self._admission_limit = (None if limit is None
                                 else max(1, int(limit)))
        self.metrics.transition("admission_limit",
                                limit=self._admission_limit,
                                by=by, **evidence)

    def set_hedge_budget(self, budget_s: Optional[float],
                         tenant: Optional[str] = None, *,
                         by: str = "operator", **evidence):
        """Install a fitted TTFT/hedge budget on BOTH pools (each leg
        hedges its own phase against its own pool's budget clock)."""
        self.prefill.set_hedge_budget(budget_s, tenant, by=by,
                                      **evidence)
        self.decode.set_hedge_budget(budget_s, tenant, by=by,
                                     **evidence)
        self.metrics.transition(
            "hedge_budget", tenant=tenant,
            budget_s=None if budget_s is None else float(budget_s),
            by=by, **evidence)
