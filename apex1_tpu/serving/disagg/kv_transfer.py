"""Manifest-verified KV page transfer — the handoff leg of
disaggregated serving (docs/serving.md § Disaggregated serving).

A prefill-pool engine finishes a prompt and leaves its chunk-aligned
prefix page in the pool-local radix store; this module moves that page
to a decode-pool engine with the SAME integrity contract
`resilience.manifest` gives checkpoints:

- `extract_page` copies the page's lane (a batch-1 cache pytree) to
  host and digests every leaf at DEPARTURE (`manifest.tree_entries`:
  path, shape, dtype, sha256 over C-contiguous little-endian bytes).
- `verify_page` re-digests the SAME leaves at ARRIVAL and compares
  entry-by-entry. A torn or corrupt transfer is a typed
  `HandoffError` — the router re-routes (re-prefill on a survivor or
  radix-hit skip), it never installs silent garbage.
- `install_page` is verify + `KVPool.put_prefix` into the destination
  engine: the decode-side admission then radix-hits the installed page
  and prefills only the page-to-prompt remainder (>= 1 token — the
  engine keeps the last prompt token uncached by contract).

Token parity across the handoff is NOT this module's job — it falls
out of the counter-keyed seed contract (PR 7): position ``i`` samples
with ``fold_in(key(seed), i)`` on whichever engine holds the stream,
so the decode pool regenerates the prefill pool's first token
bit-identically at any temperature. The drills assert it per handoff.

On the CPU proxy the "transfer" is a device→host→device round trip;
on TPU the same page moves over ICI/DCN (the fused
computation-collective shape of PAPERS.md 2305.06942) — the digest
contract is transport-agnostic, which is the point.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Tuple

import numpy as np

from apex1_tpu.resilience.manifest import tree_entries


class HandoffError(RuntimeError):
    """A KV handoff failed integrity or availability checks (corrupt/
    torn page, page evicted before transfer, no live source). TYPED so
    the router's answer is a re-route, never silent garbage tokens —
    the serving-tier sibling of `resilience.manifest.IntegrityError`."""


@dataclasses.dataclass
class KVPage:
    """One in-flight KV transfer: the page's radix key, its length in
    cached positions, the HOST copy of its batch-1 cache lane (this
    buffer IS the simulated wire), and its departure-time manifest
    entries."""

    key: Tuple[int, ...]
    length: int
    lane: Any                       # host (numpy-leaf) cache pytree
    entries: List[dict]             # manifest.tree_entries at departure

    def nbytes(self) -> int:
        import jax

        return sum(np.asarray(x).nbytes
                   for x in jax.tree_util.tree_leaves(self.lane))


def extract_page(engine, key: tuple) -> KVPage:
    """Copy ``key``'s prefix page out of ``engine``'s pool to host and
    digest it at departure. Raises `HandoffError` when the page is
    gone (LRU-evicted between prefill completion and transfer — the
    caller re-routes)."""
    import jax

    key = tuple(int(t) for t in key)
    page = engine.kv.get_prefix(key)
    if page is None:
        raise HandoffError(
            f"prefix page ({len(key)} tokens) not in the source "
            f"engine's store (evicted before transfer?)")
    lane = jax.tree_util.tree_map(np.asarray, page.lane)
    return KVPage(key=key, length=int(page.length), lane=lane,
                  entries=tree_entries(lane))


def verify_page(page: KVPage) -> None:
    """Re-digest ``page.lane`` and compare against the departure
    entries — the ARRIVAL gate. Any structure/shape/dtype/content
    mismatch is a `HandoffError` naming the first divergent leaf."""
    got = tree_entries(page.lane)
    want = page.entries
    if len(got) != len(want):
        raise HandoffError(
            f"page ({page.length} tokens): {len(got)} leaves on "
            f"arrival, {len(want)} at departure")
    for g, w in zip(got, want):
        for field in ("path", "shape", "dtype", "sha256"):
            if g[field] != w[field]:
                raise HandoffError(
                    f"page ({page.length} tokens): leaf {w['path']} "
                    f"{field} mismatch on arrival "
                    f"({g[field]!r} != departed {w[field]!r})")


def install_page(engine, page: KVPage) -> bool:
    """Verify ``page`` at arrival, then register it in ``engine``'s
    pool so the decode-side admission radix-hits it. Returns False
    (page dropped, nothing installed) when the destination already
    holds the key — `KVPool.put_prefix` treats duplicate keys as a
    contract violation, and an already-present page serves the same
    hit. Raises `HandoffError` on an integrity mismatch (BEFORE
    touching the destination pool)."""
    import jax.numpy as jnp
    import jax

    verify_page(page)
    if engine.kv.has_prefix(page.key):
        return False
    lane = jax.tree_util.tree_map(jnp.asarray, page.lane)
    engine.kv.put_prefix(page.key, lane, page.length)
    return True
