"""Disaggregated-serving smoke — toy decoder, CPU, 1+1 pools, <10 s:

(1) **Handoff parity**: every prompt long enough to produce a page
    routes prefill → manifest-verified handoff → decode, and finishes
    TOKEN-IDENTICAL to an uninterrupted single-engine run at
    temperature > 0 (the counter-keyed per-request seed, not greedy
    luck); zero handoff failures, every handoff banked.
(2) **Hit skips prefill**: resubmitting a served prompt never touches
    the prefill pool — the decode pool's radix index already holds the
    page, and the stream still matches solo generate.
(3) **Handoff-window kill**: a chaos fault kills the only prefill
    replica between prefill completion and handoff acknowledgment —
    the request re-routes (decode-pool re-prefill) and completes with
    parity; the typed failure and the re-route are counted; the
    supervisor restarts the replica. Never stranded.

Run: ``JAX_PLATFORMS=cpu python -m apex1_tpu.serving.disagg --smoke``
(wired into tools/check_all.sh as the ``disagg smoke`` step).
"""

from __future__ import annotations

import sys

import numpy as np


def _smoke() -> int:
    from apex1_tpu.testing import (enable_persistent_compilation_cache,
                                   force_virtual_cpu_devices)

    force_virtual_cpu_devices(1)
    enable_persistent_compilation_cache()

    from apex1_tpu.serving import Engine, EngineConfig, FrontendConfig
    from apex1_tpu.serving.disagg import DisaggConfig, DisaggFrontend
    from apex1_tpu.testing.chaos import HandoffWindowKill, toy_decoder

    apply_fn, make_cache, params = toy_decoder()
    ecfg = EngineConfig(max_slots=3, max_len=48, prefill_chunk=4,
                        vocab_size=61, temperature=0.8, seed=7)

    def make_engine():
        return Engine(apply_fn, make_cache, params, ecfg)

    def make_front(fault=None):
        return DisaggFrontend(
            make_engine,
            DisaggConfig(
                prefill=FrontendConfig(n_replicas=1,
                                       capacity_per_replica=8,
                                       hedge_after_s=None),
                decode=FrontendConfig(n_replicas=1,
                                      capacity_per_replica=8,
                                      hedge_after_s=None),
                prefill_chunk=ecfg.prefill_chunk),
            fault=fault)

    def assert_parity(front, prompts, rids):
        ref = make_engine()
        for p, rid in zip(prompts, rids):
            res = front.poll(rid)
            assert res is not None and res.status == "done", (rid, res)
            sub = front._subs[rid]
            rr = ref.submit(p, max_new_tokens=sub.max_new_tokens,
                            seed=sub.seed)
            ref.run(max_steps=200)
            got, want = res.tokens, ref.results[rr].tokens
            assert np.array_equal(got, want), \
                f"req {rid}: {got} != solo {want}"

    rng = np.random.default_rng(0)
    # len 3 -> share point < chunk -> direct decode; the rest route
    # through the prefill pool and hand their page off
    lens = (3, 5, 9, 7, 6)
    prompts = [rng.integers(0, 61, (n,)).astype(np.int32)
               for n in lens]

    # (1) handoff parity ------------------------------------------------
    front = make_front()
    rids = [front.submit(p, max_new_tokens=6 + i % 4)
            for i, p in enumerate(prompts)]
    front.run_until_drained(timeout_s=60.0)
    assert_parity(front, prompts, rids)
    s = front.summary()
    handoffs = [t for t in front.metrics.transitions
                if t["event"] == "handoff"]
    assert len(handoffs) == len(lens) - 1, handoffs
    assert s["counters"]["handoff_failures"] == 0, s["counters"]
    assert s["counters"]["handoff_reroutes"] == 0, s["counters"]
    assert "handoff_parity_mismatches" not in s["counters"]
    assert rids[0] not in front.prefill.metrics.records  # short: direct
    w = s["window"]["per_class"]["best_effort"]
    assert "ttft_p99_ms" in w and "tpot_p99_ms" in w, w
    print(f"disagg smoke [1/3] OK: {len(handoffs)} manifest-verified "
          f"handoffs, all {len(lens)} streams token-identical to solo "
          f"generate @ T={ecfg.temperature}, per-phase TTFT/TPOT in "
          f"window, 0 handoff failures")

    # (2) full-prompt hit skips the prefill pool ------------------------
    p = prompts[1]
    rid2 = front.submit(p, max_new_tokens=8)
    front.run_until_drained(timeout_s=60.0)
    assert rid2 not in front.prefill.metrics.records, \
        "resubmission touched the prefill pool despite a radix hit"
    assert_parity(front, [p], [rid2])
    eng = front.decode.replicas[0].engine
    assert eng.metrics.get_counter("prefix_hits") >= 1
    print("disagg smoke [2/3] OK: full-prompt radix hit routed "
          "straight to the decode pool (prefill pool untouched), "
          "stream still solo-identical")

    # (3) handoff-window kill -> re-route, never strand -----------------
    kill = HandoffWindowKill(at_handoff=0)
    front = make_front(fault=kill)
    p = prompts[2]
    rid3 = front.submit(p, max_new_tokens=7)
    front.run_until_drained(timeout_s=60.0)
    assert kill.fired == 1, kill.fired
    assert_parity(front, [p], [rid3])
    c = front.summary()["counters"]
    assert c["handoff_failures"] == 1, c
    assert c["handoff_reroutes"] == 1, c
    fails = [t for t in front.metrics.transitions
             if t["event"] == "handoff_failure"]
    assert fails and fails[0]["failure"] == "window_kill", fails
    front.prefill.pump(1)                 # let the supervisor recover
    assert front.prefill.replica_states() == ["alive"], \
        front.prefill.replica_states()
    print("disagg smoke [3/3] OK: prefill replica killed in the "
          "handoff window -> typed failure banked, request re-routed "
          "and completed with solo parity, replica restarted")
    return 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m apex1_tpu.serving.disagg",
        description="disaggregated prefill/decode serving drills")
    ap.add_argument("--smoke", action="store_true",
                    help="1+1 pool toy-decoder drill: handoff parity, "
                         "hit-skips-prefill, handoff-window kill")
    args = ap.parse_args(argv)
    if args.smoke:
        return _smoke()
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
