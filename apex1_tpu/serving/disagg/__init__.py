"""Disaggregated prefill/decode serving: phase-aware pools with
manifest-verified KV handoff (docs/serving.md § Disaggregated
serving). `DisaggFrontend` is the drop-in two-pool frontend;
`kv_transfer` is the digest-gated page transport it rides."""

from apex1_tpu.serving.disagg.frontend import DisaggConfig, DisaggFrontend
from apex1_tpu.serving.disagg.kv_transfer import (HandoffError, KVPage,
                                                  extract_page,
                                                  install_page,
                                                  verify_page)

__all__ = [
    "DisaggConfig",
    "DisaggFrontend",
    "HandoffError",
    "KVPage",
    "extract_page",
    "install_page",
    "verify_page",
]
