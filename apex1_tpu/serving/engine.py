"""Continuous-batching inference engine over the chunk-decode spine.

`models.generate` runs ONE batch, assembled by the caller, start to
finish; the TPU idles while the host builds the next batch, and a long
request holds the whole batch hostage. This engine serves a STREAM:
requests join a fixed pool of KV slots the moment one frees, and leave
on EOS / length / deadline — the decode step never stops for them.

TPU-first shape discipline (PAPER.md: static shapes, one dispatch —
the serving corollary of the training thesis): the pool is a fixed
``(max_slots, max_len)`` cache pytree, and the whole engine compiles
EXACTLY TWO executables, traced once each for the life of the engine:

- **prefill** — one ``(1, prefill_chunk)`` chunk-decode forward against
  one slot's lane. Every prompt, of any length, is fed as right-padded
  fixed-width chunks at a traced ``cache_index`` (the chunk mode of
  `cached_attention` subsumes prefill — an empty cache at index 0 is
  its degenerate case), so admission never retraces. The slot id, the
  install-this-lane flag (zeros for a fresh request, a shared-prefix
  page for a sharer), and the real-token count are all traced operands.
- **decode** — one step for ALL slots: a ``vmap`` of the batch-1 cached
  forward over the pool's leading axis, each row carrying its OWN
  traced cache index (rows are at different depths — that is the whole
  point). Inactive lanes compute masked garbage into their free slot;
  retirement and admission change only ARRAY VALUES, never shapes.

``Engine.trace_counts`` is the compilation-count hook: the counter
increments inside each traced Python body, so a retrace — the thing
this design forbids — is observable as a count > 1 (`test_serving::
TestContinuousBatching::
test_staggered_join_leave_token_identical_two_executables`).

SAMPLING is counter-based and PER REQUEST: every request carries a
seed (explicit, or derived from its stable request id), and token i of
a request is sampled with ``fold_in(key(seed), i)`` — a pure function
of (params, prompt, seed), independent of batch composition, engine
step number, or which engine instance runs it. That is the serving
analogue of PR 6's bit-exact resume: a supervisor that loses a replica
mid-stream resubmits the request (same id, same seed) to a fresh
engine and the regenerated stream is token-identical to the lost one,
at ANY temperature — idempotent resubmission as a sampling property,
not a greedy-only accident.

ASYNC DISPATCH: the decode control vectors (token/index/active/seed/
output-position per slot) live on DEVICE and are patched in place at
join/leave boundaries, so the step chain is dispatch-only from the
host's side.
With ``eos_id=None`` retirement is purely length-based (known at
admission) and the engine NEVER reads a step's tokens back before
dispatching the next — per-step outputs accumulate in a device-side
log and are materialized once, at retirement. With an ``eos_id`` the
engine must observe each step's tokens to retire rows (one small
blocking readback per step) — the latency cost of data-dependent
control, paid only when asked for.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu.models.generate import last_real_logits, sample_token
from apex1_tpu.resilience.retry import _mix32
from apex1_tpu.serving.kv_pool import KVPool
from apex1_tpu.serving.metrics import ServingMetrics
from apex1_tpu.serving.scheduler import Backpressure, Request, Scheduler
from apex1_tpu.utils.observability import MetricsLogger, annotate


def derive_request_seed(engine_seed: int, req_id: int) -> int:
    """The per-request sampling seed when the caller supplies none:
    a deterministic avalanche of (engine seed, request id). Stable
    request ids (`scheduler.new_request_id`) therefore give stable
    seeds — the property replica failover's idempotent resubmission
    rides (same id on a fresh engine ⇒ bit-identical stream)."""
    return _mix32(int(engine_seed) ^ _mix32(int(req_id) + 0x5EED)) \
        & 0x7FFFFFFF


@dataclasses.dataclass
class EngineConfig:
    """Engine shape/sampling/admission knobs. Everything here is STATIC
    for the life of the engine (baked into the two executables); all
    per-request variation rides traced operands."""

    max_slots: int = 8           # concurrent requests (pool batch)
    max_len: int = 256           # cache positions per slot
    prefill_chunk: int = 16      # prompt tokens per prefill call
    temperature: float = 0.0     # 0 = greedy (engine-global; a per-
    top_k: Optional[int] = None  # request temperature would retrace)
    eos_id: Optional[int] = None
    pad_id: int = 0
    vocab_size: Optional[int] = None
    seed: int = 0                # base for derived PER-REQUEST seeds
                                 # (see derive_request_seed)
    max_queue: int = 64          # admission backpressure bound
    policy: str = "fifo"         # or "sjf" (see serving.scheduler)

    def __post_init__(self):
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if self.max_len < 2:
            raise ValueError("max_len must be >= 2")


@dataclasses.dataclass
class RequestResult:
    """Terminal outcome. ``tokens`` holds whatever was generated before
    the terminal event (full output for "done", a prefix for evictions
    and cancellations)."""

    req_id: int
    status: str                  # done | evicted | cancelled
    tokens: np.ndarray
    reason: str = ""


@dataclasses.dataclass
class _Slot:
    """Host-side state of one occupied pool lane."""

    req: Request
    first_tok: object            # device scalar (or int once read)
    start_step: int              # engine step its first DECODE lands at
    n_out: int = 1               # tokens emitted so far (first included)
    in_batch: bool = False       # joined the decode batch (not retired
    eos_seen: bool = False       #  at prefill)
    produced: List[int] = dataclasses.field(default_factory=list)


class Engine:
    """Continuous-batching engine over a ``(apply_fn, make_cache)``
    decoder pair (`models.generate.gpt2_decoder` / `llama_decoder`).

    Drive it with `submit` + `step`/`run`; finished requests appear in
    `results`. One `step()` = retire (deadline/cancel) → admit queued
    requests into free slots (chunked prefill) → one pooled decode
    step. ``metrics`` collects the full lifecycle (`ServingMetrics`).
    """

    def __init__(self, apply_fn: Callable, make_cache: Callable, params,
                 config: Optional[EngineConfig] = None, *,
                 metrics_logger: Optional[MetricsLogger] = None,
                 cache_dtype=None):
        self.cfg = cfg = config or EngineConfig()
        self.params = params
        self._apply_fn = apply_fn
        # the pool carries prefill_chunk-1 slack positions past the
        # usable max_len: the FINAL prefill chunk is right-padded to the
        # full chunk width, so its write can extend up to that far past
        # the last real token — without the slack,
        # `dynamic_update_slice` would CLAMP the start index and
        # silently shift the whole chunk onto earlier K/V (the same
        # hazard generate()'s capacity check guards). The pad K/V in
        # the slack is masked (never attended) and overwritten by later
        # writes; max_len itself stays the admission contract.
        self.kv = KVPool(make_cache, cfg.max_slots,
                         cfg.max_len + cfg.prefill_chunk - 1,
                         dtype=cache_dtype)
        self.scheduler = Scheduler(max_queue=cfg.max_queue,
                                   policy=cfg.policy)
        self.metrics = ServingMetrics(metrics_logger)
        self.results: Dict[int, RequestResult] = {}
        self.trace_counts = {"prefill": 0, "decode": 0}
        self._slots: List[Optional[_Slot]] = [None] * cfg.max_slots
        # device-resident control vectors, patched in place at
        # join/leave boundaries — the steady-state step chain re-feeds
        # the previous step's outputs without ever touching the host.
        # seeds/pos drive the per-request counter-based sampling keys:
        # token i of a request is fold_in(key(seed), i), whatever slot,
        # step, or engine instance computes it
        self._d_toks = jnp.zeros((cfg.max_slots,), jnp.int32)
        self._d_idxs = jnp.zeros((cfg.max_slots,), jnp.int32)
        self._d_active = jnp.zeros((cfg.max_slots,), bool)
        self._d_seeds = jnp.zeros((cfg.max_slots,), jnp.int32)
        self._d_pos = jnp.zeros((cfg.max_slots,), jnp.int32)
        self._n_active = 0
        # eos_id=None: retirement is length-based, so step tokens are
        # only READ at retirement — the log keeps each step's (N,)
        # output (device array until first fetch memoizes it as numpy)
        self._defer = cfg.eos_id is None
        self._tok_log: Dict[int, object] = {}
        self._step_no = 0
        self._build_executables()

    # ---- the two executables -------------------------------------------

    def _build_executables(self):
        cfg = self.cfg
        apply_fn = self._apply_fn
        C = cfg.prefill_chunk
        sample_kw = dict(temperature=cfg.temperature, top_k=cfg.top_k,
                         vocab_size=cfg.vocab_size)

        def prefill(params, pool, slot, init_lane, install, tokens, idx,
                    n_real, seed):
            self.trace_counts["prefill"] += 1   # the compile-count hook
            lane = jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_slice_in_dim(x, slot, 1, 0),
                pool)
            lane = jax.tree_util.tree_map(
                lambda cur, ini: jnp.where(install, ini, cur), lane,
                init_lane)
            positions = (jnp.asarray(idx, jnp.int32)
                         + jnp.arange(C, dtype=jnp.int32))[None]
            logits, lane = apply_fn(params, tokens, lane, idx,
                                    positions=positions,
                                    chunk_decode=True)
            pool = jax.tree_util.tree_map(
                lambda p, l: jax.lax.dynamic_update_slice_in_dim(
                    p, l.astype(p.dtype), slot, 0), pool, lane)
            # output token 0's counter-based key (re-seeding per draw
            # is the counter-PRNG contract — see ops.stochastic)
            key = jax.random.fold_in(jax.random.key(seed), 0)
            tok = sample_token(last_real_logits(logits, n_real[None]),
                               key, **sample_kw)[0]
            return tok, pool

        def decode(params, pool, toks, idxs, active, seeds, pos):
            self.trace_counts["decode"] += 1    # the compile-count hook

            def row(tok, lane, idx, seed, p):
                lane = jax.tree_util.tree_map(lambda x: x[None], lane)
                logits, lane = apply_fn(params, tok.reshape(1, 1), lane,
                                        idx)
                key = jax.random.fold_in(jax.random.key(seed), p)
                nxt = sample_token(logits[:, -1], key, **sample_kw)[0]
                return nxt, jax.tree_util.tree_map(lambda x: x[0], lane)

            nxt, pool = jax.vmap(row)(toks, pool, idxs, seeds, pos)
            nxt = jnp.where(active, nxt, cfg.pad_id)
            adv = active.astype(jnp.int32)
            return nxt, idxs + adv, pos + adv, pool

        # donate the pool so XLA updates the cache in place; CPU lacks
        # input/output aliasing for some buffers — skip there to avoid
        # per-call warnings (semantics identical, one extra copy)
        donate = () if jax.default_backend() == "cpu" else (1,)
        self._prefill = jax.jit(prefill, donate_argnums=donate)
        self._decode = jax.jit(decode, donate_argnums=donate)

    # ---- submission -----------------------------------------------------

    def submit(self, tokens, max_new_tokens: int, *, prefix=None,
               deadline: Optional[float] = None,
               req_id: Optional[int] = None,
               qos: str = "best_effort", tenant: Optional[str] = None,
               seed: Optional[int] = None) -> int:
        """Enqueue a request. Raises `Backpressure` when the queue is
        full and holds no weaker-class victim to shed (the caller's
        429, with ``retry_after_s``/``queue_depth`` attached) and
        `ValueError` when the request can NEVER fit (prefix + prompt +
        max_new_tokens - 1 > max_len — not backpressure, a contract
        violation). ``seed`` pins the request's sampling stream; None
        derives one from the request id (stable across resubmission)."""
        req = Request(tokens=tokens, max_new_tokens=max_new_tokens,
                      prefix=prefix, deadline=deadline, req_id=req_id,
                      qos=qos, tenant=tenant, seed=seed)
        if req.seed is None:
            req.seed = derive_request_seed(self.cfg.seed, req.req_id)
        if req.total_len > self.cfg.max_len:
            raise ValueError(
                f"request needs {req.total_len} cache positions but "
                f"slots hold max_len={self.cfg.max_len}")
        try:
            rid = self.scheduler.submit(req)
        except Backpressure as e:
            self.metrics.event(req.req_id, "queued",
                               n_prompt=req.tokens.size)
            self.metrics.event(req.req_id, "rejected", reason=e.reason)
            raise
        # a weaker-class request may have been shed to admit this one
        for victim in self.scheduler.drain_shed():
            self.metrics.incr("sheds")
            self._finish(victim.req_id, "evicted",
                         f"shed ({victim.qos})", [])
        self.metrics.event(rid, "queued", n_prompt=req.tokens.size)
        return rid

    def cancel(self, req_id: int) -> bool:
        """Cancel a queued OR running request. A running request is
        retired IMMEDIATELY: its KV slot and any refcounted
        shared-prefix page are released before this returns, not at
        the next step boundary — a frontend cancelling a hedge loser
        (or shedding load) must get the capacity back now, and an idle
        engine that is never stepped again must not leak the slot."""
        if self.scheduler.cancel(req_id):
            self._finish(req_id, "cancelled", "cancelled queued", [])
            return True
        for i, slot in enumerate(self._slots):
            if slot is not None and slot.req.req_id == req_id:
                self._retire(i, "cancelled", "cancelled running")
                return True
        return False

    # ---- the engine loop ------------------------------------------------

    def step(self) -> int:
        """One engine iteration: retire (deadline/cancel) → admit → one
        decode step over every occupied slot. Returns the number of
        active slots that decoded (0 = idle)."""
        now = time.monotonic()
        for req in self.scheduler.expire(now):
            self._finish(req.req_id, "evicted", "deadline (queued)", [])
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            if (slot.req.deadline is not None
                    and slot.req.deadline <= now):
                self._retire(i, "evicted", "deadline")
        self._admit_all()
        n_active = self._n_active
        if n_active == 0:
            self.metrics.step_sample(0, self.cfg.max_slots,
                                     self.scheduler.depth)
            return 0
        with annotate("serving/decode_step"):
            nxt, idxs, pos, self.kv.cache = self._decode(
                self.params, self.kv.cache, self._d_toks, self._d_idxs,
                self._d_active, self._d_seeds, self._d_pos)
        self._d_toks, self._d_idxs, self._d_pos = nxt, idxs, pos
        if self._defer:
            self._tok_log[self._step_no] = nxt     # fetched at retire
            toks = None
        else:
            toks = np.asarray(nxt)                 # eos needs the values
        self._step_no += 1
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            slot.n_out += 1
            self.metrics.event(slot.req.req_id, "token")
            if toks is not None:
                tok = int(toks[i])
                slot.produced.append(tok)
                if tok == self.cfg.eos_id:
                    slot.eos_seen = True
                    self._retire(i, "done", "eos")
                    continue
            if slot.n_out >= slot.req.max_new_tokens:
                self._retire(i, "done", "length")
        self.metrics.step_sample(n_active, self.cfg.max_slots,
                                 self.scheduler.depth)
        return n_active

    def run(self, max_steps: Optional[int] = None) -> Dict[int,
                                                           RequestResult]:
        """Step until queue and slots drain (or ``max_steps``)."""
        steps = 0
        while self.scheduler.depth > 0 or any(
                s is not None for s in self._slots):
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self.results

    # ---- admission ------------------------------------------------------

    def _admit_all(self):
        while self.kv.n_free > 0:
            batch = self.scheduler.pop(1)
            if not batch:
                return
            self._admit(batch[0])

    def _admit(self, req: Request):
        cfg = self.cfg
        slot = self.kv.alloc()
        assert slot is not None
        self.metrics.event(req.req_id, "prefill")
        with annotate("serving/prefill"):
            idx0 = 0
            install_lane = self.kv.zeros_lane
            if req.prefix:
                if self.kv.has_prefix(req.prefix):
                    page = self.kv.acquire_prefix(req.prefix, slot)
                    install_lane, idx0 = page.lane, page.length
                else:
                    # first sharer pays: run the prefix's own chunks,
                    # snapshot the lane as the page, keep going
                    self._run_chunks(slot, np.asarray(req.prefix,
                                                      np.int32),
                                     0, self.kv.zeros_lane, req.seed)
                    lane = jax.tree_util.tree_map(
                        lambda x: x[slot:slot + 1], self.kv.cache)
                    self.kv.put_prefix(req.prefix, lane,
                                       len(req.prefix))
                    self.kv.acquire_prefix(req.prefix, slot)
                    install_lane, idx0 = None, len(req.prefix)
            tok0 = self._run_chunks(slot, req.tokens, idx0, install_lane,
                                    req.seed)
        self.metrics.event(req.req_id, "first_token")
        idx = idx0 + int(req.tokens.size)
        st = _Slot(req=req, first_tok=tok0, start_step=self._step_no)
        self._slots[slot] = st
        if not self._defer:
            first = int(np.asarray(tok0))
            st.produced.append(first)
            st.first_tok = first
            if first == cfg.eos_id:
                st.eos_seen = True
                self._retire(slot, "done", "eos")
                return
        if req.max_new_tokens == 1:
            # finished at prefill: never occupies a decode step
            self._retire(slot, "done", "length")
            return
        # device-side boundary patch: the slot joins the decode batch
        # (pos=1: the next sampled token is the request's output #1 —
        # prefill already drew #0 from the same per-request stream)
        self._d_toks = self._d_toks.at[slot].set(
            jnp.asarray(tok0, jnp.int32))
        self._d_idxs = self._d_idxs.at[slot].set(idx)
        self._d_active = self._d_active.at[slot].set(True)
        self._d_seeds = self._d_seeds.at[slot].set(int(req.seed))
        self._d_pos = self._d_pos.at[slot].set(1)
        st.in_batch = True
        self._n_active += 1

    def _run_chunks(self, slot: int, tokens: np.ndarray, idx0: int,
                    install_lane, seed: int):
        """Feed ``tokens`` through the prefill executable in fixed-width
        right-padded chunks starting at cache position ``idx0``.
        ``install_lane``: batch-1 pytree written over the slot's lane
        before the FIRST chunk (zeros, or a shared-prefix page); None
        continues on the lane as-is. Returns the (device) token sampled
        after the final chunk (drawn from the request's own counter
        stream at output position 0)."""
        C = self.cfg.prefill_chunk
        n = int(tokens.size)
        tok = None
        for c in range(math.ceil(n / C)):
            seg = tokens[c * C:(c + 1) * C]
            buf = np.zeros((1, C), np.int32)
            buf[0, :seg.size] = seg
            install = np.bool_(c == 0 and install_lane is not None)
            lane_arg = (install_lane if install
                        else self.kv.zeros_lane)
            tok, self.kv.cache = self._prefill(
                self.params, self.kv.cache, np.int32(slot), lane_arg,
                install, buf, np.int32(idx0 + c * C),
                np.int32(seg.size), np.int32(seed))
        return tok

    # ---- retirement -----------------------------------------------------

    def _materialize(self, st: _Slot, slot_idx: int) -> List[int]:
        """Collect a deferred-mode slot's tokens from the step log (the
        only point the engine blocks on decode outputs)."""
        out = [int(np.asarray(st.first_tok))]
        for s in range(st.start_step,
                       st.start_step + max(st.n_out - 1, 0)):
            buf = self._tok_log[s]
            if not isinstance(buf, np.ndarray):     # memoize the fetch
                buf = np.asarray(buf)
                self._tok_log[s] = buf
            out.append(int(buf[slot_idx]))
        return out

    def _prune_log(self):
        if not self._tok_log:
            return
        live = [s.start_step for s in self._slots if s is not None]
        floor = min(live) if live else self._step_no
        for s in [s for s in self._tok_log if s < floor]:
            del self._tok_log[s]

    def _retire(self, slot_idx: int, status: str, reason: str):
        slot = self._slots[slot_idx]
        self._slots[slot_idx] = None
        if self._defer:
            produced = self._materialize(slot, slot_idx)
            self._prune_log()
        else:
            produced = slot.produced
        if slot.in_batch:
            # boundary patch: drop the lane from the decode batch (the
            # freed lane keeps computing masked garbage — values only)
            self._d_active = self._d_active.at[slot_idx].set(False)
            self._n_active -= 1
        self.kv.free(slot_idx)
        self._finish(slot.req.req_id, status, reason, produced)

    def _finish(self, req_id: int, status: str, reason: str,
                produced: List[int]):
        if status == "evicted" and not reason.startswith("shed"):
            self.metrics.incr("evictions")  # sheds counted separately
        self.metrics.event(req_id, status, reason=reason,
                           n_generated=len(produced))
        self.results[req_id] = RequestResult(
            req_id=req_id, status=status,
            tokens=np.asarray(produced, np.int32), reason=reason)

    # ---- introspection --------------------------------------------------

    def pop_result(self, req_id: int) -> Optional[RequestResult]:
        """Remove and return a finished request's result — the
        long-running server's pressure valve (`results` is otherwise
        bounded only by the number of requests ever served; pair with
        `metrics.drain()`)."""
        return self.results.pop(req_id, None)

    @property
    def n_active(self) -> int:
        return self._n_active

    def slot_view(self) -> List[Optional[int]]:
        """req_id per slot (None = free) — the occupancy diagram."""
        return [None if s is None else s.req.req_id for s in self._slots]
