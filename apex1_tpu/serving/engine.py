"""Continuous-batching inference engine over the chunk-decode spine.

`models.generate` runs ONE batch, assembled by the caller, start to
finish; the TPU idles while the host builds the next batch, and a long
request holds the whole batch hostage. This engine serves a STREAM:
requests join a fixed pool of KV slots the moment one frees, and leave
on EOS / length / deadline — the decode step never stops for them.

TPU-first shape discipline (PAPER.md: static shapes, one dispatch —
the serving corollary of the training thesis): the pool is a fixed
``(max_slots, max_len)`` cache pytree, and the whole engine compiles
EXACTLY TWO executables, traced once each for the life of the engine:

- **prefill** — one ``(1, prefill_chunk)`` chunk-decode forward against
  one slot's lane. Every prompt, of any length, is fed as right-padded
  fixed-width chunks at a traced ``cache_index`` (the chunk mode of
  `cached_attention` subsumes prefill — an empty cache at index 0 is
  its degenerate case), so admission never retraces. The slot id, the
  install-this-lane flag (zeros for a fresh request, a shared-prefix
  page for a sharer), and the real-token count are all traced operands.
- **decode** — one step for ALL slots: a ``vmap`` of the batch-1 cached
  forward over the pool's leading axis, each row carrying its OWN
  traced cache index (rows are at different depths — that is the whole
  point). Inactive lanes compute masked garbage into their free slot;
  retirement and admission change only ARRAY VALUES, never shapes.

With ``num_draft > 0`` the decode executable is replaced by **verify**
— same two-executable discipline, different second executable: a
``vmap`` of a ``(1, num_draft + 1)`` chunk-decode forward that scores
the previous token plus K host-proposed draft tokens in ONE dispatch
and accepts the longest prefix matching the target's own counter-keyed
samples (see SPECULATIVE DECODE below).

``Engine.trace_counts`` is the compilation-count hook: the counter
increments inside each traced Python body, so a retrace — the thing
this design forbids — is observable as a count > 1 (`test_serving::
TestContinuousBatching::
test_staggered_join_leave_token_identical_two_executables`).

SAMPLING is counter-based and PER REQUEST: every request carries a
seed (explicit, or derived from its stable request id), and token i of
a request is sampled with ``fold_in(key(seed), i)`` — a pure function
of (params, prompt, seed), independent of batch composition, engine
step number, or which engine instance runs it. That is the serving
analogue of PR 6's bit-exact resume: a supervisor that loses a replica
mid-stream resubmits the request (same id, same seed) to a fresh
engine and the regenerated stream is token-identical to the lost one,
at ANY temperature — idempotent resubmission as a sampling property,
not a greedy-only accident.

RADIX PREFIX CACHE (``prefix_cache=True``, the default): admission
consults the pool's radix matcher (`kv_pool.RadixIndex`) with the
request's FULL prompt (explicit ``prefix=`` tuple, if any, simply
concatenated in front — the explicit API is a thin wrapper that also
pins the page's registration length), installs the longest registered
page, and prefills only the remainder. Requests WITHOUT an explicit
prefix auto-register a page at the chunk-aligned share point
``((len - 1) // prefill_chunk) * prefill_chunk`` — canonical lengths,
so requests that split prefix/prompt differently still converge on one
key. Token parity is untouched by a hit: chunked prefill computes the
same K/V whatever boundary it resumes from (fp32/toy exact; same bf16
near-tie caveat as chained `generate`). Near capacity (queue deeper
than free slots) admission becomes prefix-aware: within a QoS class,
requests that would HIT are dequeued first — a hit turns a slot over
sooner, which is the scarce resource under pressure.

SPECULATIVE DECODE (``num_draft=K``): each step, the host proposes K
tokens per active slot (`spec.ngram_propose` self-drafting by default;
``draft_propose=`` plugs in a small draft model) and ONE verify
dispatch scores all slots' chunks. Acceptance is EXACT-MATCH against
the target's counter-keyed stream (`generate.counter_sample`): draft j
is accepted iff it equals the token the engine would have sampled at
that output position anyway. The emitted stream is therefore
BIT-IDENTICAL to the non-speculative engine — and to solo `generate` —
at ANY temperature; drafts are pure latency hints, and the counter-seed
contract (resubmission idempotency, hedging, failover) survives
verbatim. What speculation changes is DISPATCH COUNT: ~(1 + accepted)
tokens land per verify instead of 1 per decode step — decode is
weight-streaming-bound on TPU, so fewer dispatches ≈ proportionally
fewer HBM weight streams. Accept rate is banked per request
(`RequestRecord.n_drafted/n_accepted`) and per class (the metrics
window), and the verify step reads back its per-slot accept counts —
the one host sync speculation's variable-rate emission costs.

ASYNC DISPATCH: the decode control vectors (token/index/active/seed/
output-position per slot) live on DEVICE and are patched in place at
join/leave boundaries, so the step chain is dispatch-only from the
host's side.
With ``eos_id=None`` retirement is purely length-based (known at
admission) and the engine NEVER reads a step's tokens back before
dispatching the next — per-step outputs accumulate in a device-side
log and are materialized once, at retirement. With an ``eos_id`` the
engine must observe each step's tokens to retire rows (one small
blocking readback per step) — the latency cost of data-dependent
control, paid only when asked for. Speculative mode always reads back
(drafting needs the history; accept counts gate retirement).
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu.models.generate import (counter_sample, last_real_logits,
                                       sample_token)
from apex1_tpu.ops._common import use_pallas
from apex1_tpu.ops.paged_decode import (PagedCache, fused_sample,
                                        gather_pages, scatter_pages)
from apex1_tpu.resilience.retry import _mix32
from apex1_tpu.serving.kv_pool import KVPool, PagedKVPool
from apex1_tpu.serving.metrics import ServingMetrics
from apex1_tpu.serving.scheduler import Backpressure, Request, Scheduler
from apex1_tpu.serving.spec import ngram_propose
from apex1_tpu.utils.observability import MetricsLogger, annotate


def derive_request_seed(engine_seed: int, req_id: int) -> int:
    """The per-request sampling seed when the caller supplies none:
    a deterministic avalanche of (engine seed, request id). Stable
    request ids (`scheduler.new_request_id`) therefore give stable
    seeds — the property replica failover's idempotent resubmission
    rides (same id on a fresh engine ⇒ bit-identical stream)."""
    return _mix32(int(engine_seed) ^ _mix32(int(req_id) + 0x5EED)) \
        & 0x7FFFFFFF


@dataclasses.dataclass
class EngineConfig:
    """Engine shape/sampling/admission knobs. Everything here is STATIC
    for the life of the engine (baked into the two executables); all
    per-request variation rides traced operands."""

    max_slots: int = 8           # concurrent requests (pool batch)
    max_len: int = 256           # cache positions per slot
    prefill_chunk: int = 16      # prompt tokens per prefill call
    temperature: float = 0.0     # 0 = greedy (engine-global; a per-
    top_k: Optional[int] = None  # request temperature would retrace)
    eos_id: Optional[int] = None
    pad_id: int = 0
    vocab_size: Optional[int] = None
    seed: int = 0                # base for derived PER-REQUEST seeds
                                 # (see derive_request_seed)
    max_queue: int = 64          # admission backpressure bound
    policy: str = "fifo"         # or "sjf" (see serving.scheduler)
    prefix_cache: bool = True    # radix cross-request prefix matching
    max_prefix_pages: int = 32   # LRU-by-last-hit page bound
    num_draft: int = 0           # >0: speculative decode, K drafts per
                                 # verify (the second executable becomes
                                 # the (1, K+1) chunk-verify)
    max_ngram: int = 3           # self-draft prompt-lookup n-gram cap
    cache_dtype: Optional[object] = None  # e.g. jnp.int8 — the KV pool's
    # steady-state capacity tier (half the bytes/slot ⇒ ~2x max_slots
    # for the same HBM; perf_model.kv_cache_bytes is the sizing model).
    # The Engine(cache_dtype=) kwarg still overrides (degraded-mode
    # restarts use it); None = the decoder's compute dtype.
    paged: bool = False          # route decode/verify through the paged
    # KV pool (`ops.paged_decode`): block-table page addressing, prefix
    # pages shared by REFERENCE (no copy-on-admit), the Pallas ragged
    # kernel + fused sampling epilogue on TPU. False keeps the dense
    # XLA-composed path — the parity reference (the paged CPU proxy is
    # pinned token-identical to it in tier-1).
    page_size: Optional[int] = None  # KV positions per page. None
    # resolves tuning-table winner > ceil8(prefill_chunk) heuristic;
    # the Pallas kernel path requires a multiple of 8 (sublane tiling)
    # and `check_paged_geometry` fails loudly otherwise.
    lora_rank: int = 0           # >0: multi-tenant LoRA adapter pages —
    # each slot carries a rank-length adapter block-table row and the
    # decode executables add the `ops.lora_epilogue` delta to the head
    # logits. tenant= on submit names the adapter (serving.lora);
    # requires Engine(lora_head=) — the model's (V, H) LM-head param.
    lora_max_adapters: int = 4   # adapter-page pool sizing (pages =
    #                              1 + max_adapters * rank)

    def __post_init__(self):
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if self.max_len < 2:
            raise ValueError("max_len must be >= 2")
        if self.num_draft < 0:
            raise ValueError(
                f"num_draft must be >= 0, got {self.num_draft}")
        if self.max_ngram < 1:
            raise ValueError(f"max_ngram must be >= 1, got {self.max_ngram}")
        if self.max_prefix_pages < 1:
            raise ValueError("max_prefix_pages must be >= 1")
        if self.page_size is not None and self.page_size < 1:
            raise ValueError(
                f"page_size must be >= 1, got {self.page_size}")
        if self.lora_rank < 0:
            raise ValueError(
                f"lora_rank must be >= 0, got {self.lora_rank}")
        if self.lora_rank > 0 and self.lora_max_adapters < 1:
            raise ValueError(
                f"lora_max_adapters must be >= 1, "
                f"got {self.lora_max_adapters}")


@dataclasses.dataclass
class RequestResult:
    """Terminal outcome. ``tokens`` holds whatever was generated before
    the terminal event (full output for "done", a prefix for evictions
    and cancellations)."""

    req_id: int
    status: str                  # done | evicted | cancelled
    tokens: np.ndarray
    reason: str = ""


@dataclasses.dataclass
class _Slot:
    """Host-side state of one occupied pool lane."""

    req: Request
    first_tok: object            # device scalar (or int once read)
    start_step: int              # engine step its first DECODE lands at
    n_out: int = 1               # tokens emitted so far (first included)
    in_batch: bool = False       # joined the decode batch (not retired
    eos_seen: bool = False       #  at prefill)
    produced: List[int] = dataclasses.field(default_factory=list)
    # speculative bookkeeping: the request's full known token history
    # (prefix + prompt + emitted — the self-draft corpus) and the
    # per-request accept-rate numerators the terminal event banks
    history: List[int] = dataclasses.field(default_factory=list)
    drafted: int = 0
    accepted: int = 0


class Engine:
    """Continuous-batching engine over a ``(apply_fn, make_cache)``
    decoder pair (`models.generate.gpt2_decoder` / `llama_decoder`).

    Drive it with `submit` + `step`/`run`; finished requests appear in
    `results`. One `step()` = retire (deadline/cancel) → admit queued
    requests into free slots (chunked prefill) → one pooled decode (or
    speculative verify) step. ``metrics`` collects the full lifecycle
    (`ServingMetrics`). ``draft_propose(history, k) -> k ints`` plugs a
    custom draft source into speculative mode (default: n-gram
    prompt-lookup self-drafting, zero extra params).
    """

    def __init__(self, apply_fn: Callable, make_cache: Callable, params,
                 config: Optional[EngineConfig] = None, *,
                 metrics_logger: Optional[MetricsLogger] = None,
                 cache_dtype=None,
                 draft_propose: Optional[Callable] = None,
                 lora_head=None):
        self.cfg = cfg = config or EngineConfig()
        self.params = params
        self._apply_fn = apply_fn
        self._spec = cfg.num_draft > 0
        # multi-tenant LoRA (cfg.lora_rank > 0): the adapter-page store
        # rides beside the KV pool, and the executables recompute the
        # head matmul from the decoder's HIDDEN states (apply_fn must
        # accept return_hidden=True — llama_decoder does) so the paged
        # adapter delta fuses into the logits epilogue. lora_head is
        # the model's OWN (V, H) LM-head param (e.g. params["output"]);
        # the executable applies the model's exact einsum to it, so a
        # LoRA-off slot's logits are the model's logits verbatim.
        self._lora = self._lora_head = None
        if cfg.lora_rank > 0:
            if lora_head is None:
                raise ValueError(
                    "lora_rank > 0 requires lora_head= (the model's "
                    "(vocab, hidden) LM-head weight)")
            from apex1_tpu.serving.lora import LoraAdapterStore
            V, H = lora_head.shape
            self._lora = LoraAdapterStore(H, V, cfg.lora_rank,
                                          cfg.lora_max_adapters)
            self._lora_head = lora_head
        # the pool carries slack positions past the usable max_len: the
        # FINAL prefill chunk is right-padded to the full chunk width,
        # so its write can extend up to prefill_chunk-1 past the last
        # real token — without the slack, `dynamic_update_slice` would
        # CLAMP the start index and silently shift the whole chunk onto
        # earlier K/V (the same hazard generate()'s capacity check
        # guards). A speculative verify writes num_draft+1 entries at
        # the current index the same way, so the slack is the max of
        # the two write widths minus one. The pad/rejected K/V in the
        # slack is masked (never attended) and overwritten by later
        # writes; max_len itself stays the admission contract.
        slack = max(cfg.prefill_chunk, cfg.num_draft + 1) - 1
        if cache_dtype is None:
            cache_dtype = cfg.cache_dtype    # kwarg (degraded-mode
        #                                      restarts) beats config
        self._paged = bool(cfg.paged)
        if self._paged:
            self.kv = PagedKVPool(
                make_cache, cfg.max_slots, cfg.max_len + slack,
                page_size=self._resolve_page_size(make_cache,
                                                  cache_dtype),
                dtype=cache_dtype, max_pages=cfg.max_prefix_pages)
            # device mirror of the host block tables, patched at
            # admission/retire boundaries only (like the control
            # vectors below) — the steady-state decode chain feeds it
            # back without host traffic. Freed rows reset to the trash
            # page so an inactive lane's masked-garbage scatter can
            # never land on a page a NEW request now owns.
            self._d_bt = jnp.zeros(
                (cfg.max_slots, self.kv.pages_per_lane), jnp.int32)
        else:
            self.kv = KVPool(make_cache, cfg.max_slots,
                             cfg.max_len + slack, dtype=cache_dtype,
                             max_pages=cfg.max_prefix_pages)
        self.scheduler = Scheduler(max_queue=cfg.max_queue,
                                   policy=cfg.policy)
        self.metrics = ServingMetrics(metrics_logger)
        self.results: Dict[int, RequestResult] = {}
        self.trace_counts = ({"prefill": 0, "verify": 0} if self._spec
                             else {"prefill": 0, "decode": 0})
        self._slots: List[Optional[_Slot]] = [None] * cfg.max_slots
        self._draft_propose = draft_propose or (
            lambda hist, k: ngram_propose(hist, k,
                                          max_ngram=cfg.max_ngram))
        # device-resident control vectors, patched in place at
        # join/leave boundaries — the steady-state step chain re-feeds
        # the previous step's outputs without ever touching the host.
        # seeds/pos drive the per-request counter-based sampling keys:
        # token i of a request is fold_in(key(seed), i), whatever slot,
        # step, or engine instance computes it
        self._d_toks = jnp.zeros((cfg.max_slots,), jnp.int32)
        self._d_idxs = jnp.zeros((cfg.max_slots,), jnp.int32)
        self._d_active = jnp.zeros((cfg.max_slots,), bool)
        self._d_seeds = jnp.zeros((cfg.max_slots,), jnp.int32)
        self._d_pos = jnp.zeros((cfg.max_slots,), jnp.int32)
        if self._lora is not None:
            # per-slot adapter block-table row + on-flag, patched at the
            # same join/leave boundaries as the control vectors. All-
            # zero rows name the zero page (exact 0.0 delta), so the
            # flag only guards the `logits + delta` add against -0.0
            # drift on adapterless rows — one executable either way.
            self._d_lora_bt = jnp.zeros(
                (cfg.max_slots, cfg.lora_rank), jnp.int32)
            self._d_lora_on = jnp.zeros((cfg.max_slots,), bool)
        self._n_active = 0
        # eos_id=None: retirement is length-based, so step tokens are
        # only READ at retirement — the log keeps each step's (N,)
        # output (device array until first fetch memoizes it as numpy).
        # Speculative mode always reads back (drafting needs history).
        self._defer = cfg.eos_id is None and not self._spec
        self._tok_log: Dict[int, object] = {}
        self._step_no = 0
        # the mid-admission cancel window: `cancel` from an ingest
        # thread while `_admit` runs this request's prefill chain. The
        # lock serializes the flag handshake (check+add vs clear+read)
        # — without it a cancel that passed the _mid_admit check could
        # land its _cancel_mid entry just after _admit drained the set,
        # returning True for a cancel that never happens (review
        # finding)
        self._mid_admit: Optional[int] = None
        self._cancel_mid: set = set()
        self._admit_lock = threading.Lock()
        # prefix-aware admission probe memo, invalidated whenever the
        # page store changes (bounded by the queue: one bool per
        # queued request per store version)
        self._probe_cache: Dict[int, bool] = {}
        self._probe_cache_ver = -1
        self._build_executables()

    def _resolve_page_size(self, make_cache, cache_dtype) -> int:
        """Page-size precedence: explicit config > tuning-table winner
        (keyed on the decoder's padded head dim at the S=1 decode row
        class) > chunk-width heuristic (sublane-aligned, and one
        prefill chunk never spans more than two pages)."""
        cfg = self.cfg
        if cfg.page_size is not None:
            return int(cfg.page_size)
        from apex1_tpu import tuning
        kw = {} if cache_dtype is None else {"dtype": cache_dtype}
        probe = jax.tree_util.tree_leaves(make_cache(1, 1, **kw))[0]
        tuned = tuning.lookup(
            "paged_decode",
            {"Dp": tuning.padded_lanes(probe.shape[-1]), "Rq": 8},
            probe.dtype)
        if tuned is not None:
            return int(tuned["page_p"])
        return max(8, -(-cfg.prefill_chunk // 8) * 8)

    def _sync_bt(self, slot: int) -> None:
        """Push one slot's host block-table row to the device mirror —
        called wherever the host row changes (alloc, prefix acquire,
        free), never on the step path."""
        self._d_bt = self._d_bt.at[slot].set(
            jnp.asarray(self.kv.block_tables[slot], jnp.int32))

    # ---- the two executables -------------------------------------------

    def _build_executables(self):
        if self._paged:
            return self._build_paged_executables()
        cfg = self.cfg
        apply_fn = self._apply_fn
        C = cfg.prefill_chunk
        K = cfg.num_draft
        lora = self._lora is not None
        head = self._lora_head
        sample_kw = dict(temperature=cfg.temperature, top_k=cfg.top_k,
                         vocab_size=cfg.vocab_size)

        # LoRA epilogue leg (static — baked at build time like the
        # paged kernel_path): the forward returns HIDDEN states, the
        # body replays the model's exact head einsum, and the paged
        # adapter delta lands before sampling. `jnp.where(on, ...)`
        # rather than an unconditional add: the zero page makes an off
        # row's delta exactly 0.0, but `x + 0.0` can still flip -0.0
        # logits, and off rows must be BITWISE the base model's.
        def head_logits(h):
            return jnp.einsum("bsh,vh->bsv", h, head.astype(h.dtype),
                              preferred_element_type=jnp.float32)

        def forward(params, tokens, lane, idx, **kw):
            if not lora:
                return apply_fn(params, tokens, lane, idx, **kw)
            h, lane = apply_fn(params, tokens, lane, idx,
                               return_hidden=True, **kw)
            return head_logits(h), h, lane

        def lora_row(logits, h, a_pg, b_pg, lrow, on):
            from apex1_tpu.ops.lora_epilogue import _lora_delta_ref
            bt = jnp.broadcast_to(lrow[None, :],
                                  (h.shape[0], lrow.shape[0]))
            delta = _lora_delta_ref(h, a_pg, b_pg, bt)
            return jnp.where(on, logits + delta.astype(logits.dtype),
                             logits)

        def prefill(params, pool, slot, init_lane, install, tokens, idx,
                    n_real, seed, a_pg=None, b_pg=None, lbt=None,
                    lon=None):
            self.trace_counts["prefill"] += 1   # the compile-count hook
            lane = jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_slice_in_dim(x, slot, 1, 0),
                pool)
            lane = jax.tree_util.tree_map(
                lambda cur, ini: jnp.where(install, ini, cur), lane,
                init_lane)
            positions = (jnp.asarray(idx, jnp.int32)
                         + jnp.arange(C, dtype=jnp.int32))[None]
            if lora:
                logits, h, lane = forward(params, tokens, lane, idx,
                                          positions=positions,
                                          chunk_decode=True)
            else:
                logits, lane = apply_fn(params, tokens, lane, idx,
                                        positions=positions,
                                        chunk_decode=True)
            pool = jax.tree_util.tree_map(
                lambda p, l: jax.lax.dynamic_update_slice_in_dim(
                    p, l.astype(p.dtype), slot, 0), pool, lane)
            lg = last_real_logits(logits, n_real[None])
            if lora:
                # the slot's adapter row, gathered at the same traced
                # index discipline as everything else in this body
                lrow = jax.lax.dynamic_slice_in_dim(lbt, slot, 1, 0)[0]
                on = jax.lax.dynamic_slice_in_dim(lon, slot, 1, 0)[0]
                lg = lora_row(lg, last_real_logits(h, n_real[None]),
                              a_pg, b_pg, lrow, on)
            # output token 0's counter-based key (re-seeding per draw
            # is the counter-PRNG contract — see ops.stochastic)
            key = jax.random.fold_in(jax.random.key(seed), 0)
            tok = sample_token(lg, key, **sample_kw)[0]
            return tok, pool

        def decode(params, pool, toks, idxs, active, seeds, pos,
                   a_pg=None, b_pg=None, lbt=None, lon=None):
            self.trace_counts["decode"] += 1    # the compile-count hook

            def row(tok, lane, idx, seed, p, lrow, on):
                lane = jax.tree_util.tree_map(lambda x: x[None], lane)
                if lora:
                    logits, h, lane = forward(params, tok.reshape(1, 1),
                                              lane, idx)
                    lg = lora_row(logits[:, -1], h[:, -1], a_pg, b_pg,
                                  lrow, on)
                else:
                    logits, lane = apply_fn(params, tok.reshape(1, 1),
                                            lane, idx)
                    lg = logits[:, -1]
                key = jax.random.fold_in(jax.random.key(seed), p)
                nxt = sample_token(lg, key, **sample_kw)[0]
                return nxt, jax.tree_util.tree_map(lambda x: x[0], lane)

            if lora:
                nxt, pool = jax.vmap(
                    row, in_axes=(0, 0, 0, 0, 0, 0, 0))(
                        toks, pool, idxs, seeds, pos, lbt, lon)
            else:
                nxt, pool = jax.vmap(
                    row, in_axes=(0, 0, 0, 0, 0, None, None))(
                        toks, pool, idxs, seeds, pos, None, None)
            nxt = jnp.where(active, nxt, cfg.pad_id)
            adv = active.astype(jnp.int32)
            return nxt, idxs + adv, pos + adv, pool

        def verify(params, pool, toks, idxs, active, seeds, pos,
                   drafts, a_pg=None, b_pg=None, lbt=None, lon=None):
            self.trace_counts["verify"] += 1    # the compile-count hook

            def row(tok, lane, idx, seed, p, dr, lrow, on):
                lane = jax.tree_util.tree_map(lambda x: x[None], lane)
                chunk = jnp.concatenate([tok[None], dr])      # (K+1,)
                if lora:
                    logits, h, lane = forward(params, chunk[None], lane,
                                              idx, chunk_decode=True)
                    lg = lora_row(logits[0], h[0], a_pg, b_pg, lrow, on)
                else:
                    logits, lane = apply_fn(params, chunk[None], lane,
                                            idx, chunk_decode=True)
                    lg = logits[0]
                # the target's CANONICAL stream at positions p..p+K —
                # exact-match acceptance means emitted tokens are these
                # samples verbatim, so speculation cannot perturb the
                # (params, prompt, seed) purity resubmission rides
                tgt = counter_sample(
                    lg, seed, p + jnp.arange(K + 1, dtype=jnp.int32),
                    **sample_kw)
                a = jnp.sum(jnp.cumprod(
                    (tgt[:K] == dr).astype(jnp.int32)))
                return tgt, a, jax.tree_util.tree_map(
                    lambda x: x[0], lane)

            if lora:
                tgt, acc, pool = jax.vmap(
                    row, in_axes=(0, 0, 0, 0, 0, 0, 0, 0))(
                        toks, pool, idxs, seeds, pos, drafts, lbt, lon)
            else:
                tgt, acc, pool = jax.vmap(
                    row, in_axes=(0, 0, 0, 0, 0, 0, None, None))(
                        toks, pool, idxs, seeds, pos, drafts, None,
                        None)
            acc = jnp.where(active, acc, 0)
            adv = jnp.where(active, acc + 1, 0)
            nxt = jnp.where(
                active,
                jnp.take_along_axis(tgt, acc[:, None], 1)[:, 0],
                cfg.pad_id)
            return tgt, acc, nxt, idxs + adv, pos + adv, pool

        # donate the pool so XLA updates the cache in place; CPU lacks
        # input/output aliasing for some buffers — skip there to avoid
        # per-call warnings (semantics identical, one extra copy)
        donate = () if jax.default_backend() == "cpu" else (1,)
        self._prefill = jax.jit(prefill, donate_argnums=donate)
        if self._spec:
            self._verify = jax.jit(verify, donate_argnums=donate)
        else:
            self._decode = jax.jit(decode, donate_argnums=donate)

    def _build_paged_executables(self):
        """The paged-mode executables. Two shapes of the same contract:

        - **off-TPU (the parity gold)**: gather each slot's dense lane
          from its pages, run the UNCHANGED reference bodies (the same
          vmap-of-batch-1 rows, the same in-row sampling ops as the
          dense executables), scatter only the written window back.
          Every position the reference attends or writes is
          bit-identical to the dense pool's lane — garbage beyond a
          row's horizon is masked to an exact zero either way — so
          token streams match the dense engine BITWISE, at any
          temperature, by construction (pinned in
          ``tests/test_paged_decode.py``).
        - **TPU / forced-pallas**: thread :class:`PagedCache` entries
          through ONE batch-N forward — the model's attention routes to
          the `ops.paged_decode.paged_attend` kernel (block-table page
          streaming, fused int8 dequant, per-row ragged horizons) and
          sampling collapses into the `fused_sample` epilogue kernel,
          so one token id per slot is all that crosses back per step.
          The path is selected at BUILD time (``use_pallas()``), so a
          forced-impl test must construct the engine inside
          ``ops.force_impl("pallas")``.
        """
        cfg = self.cfg
        apply_fn = self._apply_fn
        C = cfg.prefill_chunk
        K = cfg.num_draft
        L = self.kv.lane_len
        lora = self._lora is not None
        head = self._lora_head
        sample_kw = dict(temperature=cfg.temperature, top_k=cfg.top_k,
                         vocab_size=cfg.vocab_size)
        tree_map = jax.tree_util.tree_map
        kernel_path = use_pallas()

        def head_logits(h):
            return jnp.einsum("bsh,vh->bsv", h, head.astype(h.dtype),
                              preferred_element_type=jnp.float32)

        def forward(params, tokens, cache, idx, **kw):
            if not lora:
                return apply_fn(params, tokens, cache, idx, **kw)
            h, cache = apply_fn(params, tokens, cache, idx,
                                return_hidden=True, **kw)
            return head_logits(h), h, cache

        def lora_batch(logits, h, a_pg, b_pg, lbt, lon):
            # (N, V) logits + (N, H) hidden rows -> epilogue delta via
            # the scalar-prefetched page-gather kernel (composite gold
            # off-TPU); rows are independent, so mixed-tenant batches
            # stay bitwise equal to solo runs
            from apex1_tpu.ops.lora_epilogue import lora_delta
            delta = lora_delta(h, a_pg, b_pg, lbt)
            return jnp.where(lon[:, None],
                             logits + delta.astype(logits.dtype),
                             logits)

        def lora_rowwise(logits, h, a_pg, b_pg, lrow, on):
            from apex1_tpu.ops.lora_epilogue import _lora_delta_ref
            bt = jnp.broadcast_to(lrow[None, :],
                                  (h.shape[0], lrow.shape[0]))
            delta = _lora_delta_ref(h, a_pg, b_pg, bt)
            return jnp.where(on, logits + delta.astype(logits.dtype),
                             logits)

        def window(lane, start, width):
            # the (N, Hkv, width, D) block the model just wrote at each
            # row's index — the only slice scatter-back needs
            pos = (start[:, None]
                   + jnp.arange(width, dtype=jnp.int32))[:, None, :,
                                                         None]
            return jnp.take_along_axis(lane, pos, axis=2)

        def paged_cache(pages, bt):
            return {layer: PagedCache(entry["k"], entry["v"], bt, L)
                    for layer, entry in pages.items()}

        def unpack_cache(cache):
            return {layer: {"k": pc.k_pages, "v": pc.v_pages}
                    for layer, pc in cache.items()}

        def prefill(params, pages, bt, slot, tokens, idx, n_real, seed,
                    a_pg=None, b_pg=None, lbt=None, lon=None):
            self.trace_counts["prefill"] += 1   # the compile-count hook
            bt_row = jax.lax.dynamic_slice_in_dim(bt, slot, 1, 0)
            positions = (jnp.asarray(idx, jnp.int32)
                         + jnp.arange(C, dtype=jnp.int32))[None]
            h = None
            if kernel_path:
                cache = paged_cache(pages, bt_row)
                if lora:
                    logits, h, cache = forward(params, tokens, cache,
                                               idx, positions=positions,
                                               chunk_decode=True)
                else:
                    logits, cache = apply_fn(params, tokens, cache, idx,
                                             positions=positions,
                                             chunk_decode=True)
                pages = unpack_cache(cache)
            else:
                lane = tree_map(lambda p: gather_pages(p, bt_row, L),
                                pages)
                if lora:
                    logits, h, lane = forward(params, tokens, lane, idx,
                                              positions=positions,
                                              chunk_decode=True)
                else:
                    logits, lane = apply_fn(params, tokens, lane, idx,
                                            positions=positions,
                                            chunk_decode=True)
                idx_v = jnp.asarray(idx, jnp.int32)[None]
                pages = tree_map(
                    lambda pg, ln: scatter_pages(
                        pg, bt_row, window(ln, idx_v, C), idx_v),
                    pages, lane)
            # there is no install step: a prefix hit ARRIVES as shared
            # page ids in the block table (reference, not copy), and a
            # fresh slot's recycled-page garbage sits beyond the
            # attention horizon — exactly like the dense pool's masked
            # slack
            lg = last_real_logits(logits, n_real[None])
            if lora:
                lrow = jax.lax.dynamic_slice_in_dim(lbt, slot, 1, 0)[0]
                on = jax.lax.dynamic_slice_in_dim(lon, slot, 1, 0)[0]
                lg = lora_rowwise(lg, last_real_logits(h, n_real[None]),
                                  a_pg, b_pg, lrow, on)
            tok = fused_sample(lg, jnp.asarray(seed, jnp.int32)[None],
                               jnp.zeros((1,), jnp.int32),
                               **sample_kw)[0]
            return tok, pages

        def decode(params, pages, bt, toks, idxs, active, seeds, pos,
                   a_pg=None, b_pg=None, lbt=None, lon=None):
            self.trace_counts["decode"] += 1    # the compile-count hook
            if kernel_path:
                cache = paged_cache(pages, bt)
                if lora:
                    logits, h, cache = forward(params, toks[:, None],
                                               cache, idxs,
                                               positions=idxs[:, None])
                    lg = lora_batch(logits[:, -1], h[:, -1], a_pg,
                                    b_pg, lbt, lon)
                else:
                    logits, cache = apply_fn(params, toks[:, None],
                                             cache, idxs,
                                             positions=idxs[:, None])
                    lg = logits[:, -1]
                pages = unpack_cache(cache)
                nxt = fused_sample(lg, seeds, pos, **sample_kw)
            else:
                lanes = tree_map(lambda p: gather_pages(p, bt, L),
                                 pages)

                def row(tok, lane, idx, seed, p, lrow, on):
                    lane = tree_map(lambda x: x[None], lane)
                    if lora:
                        logits, h, lane = forward(params,
                                                  tok.reshape(1, 1),
                                                  lane, idx)
                        lg = lora_rowwise(logits[:, -1], h[:, -1],
                                          a_pg, b_pg, lrow, on)
                    else:
                        logits, lane = apply_fn(params,
                                                tok.reshape(1, 1),
                                                lane, idx)
                        lg = logits[:, -1]
                    key = jax.random.fold_in(jax.random.key(seed), p)
                    nxt = sample_token(lg, key, **sample_kw)[0]
                    return nxt, tree_map(lambda x: x[0], lane)

                if lora:
                    nxt, lanes = jax.vmap(
                        row, in_axes=(0, 0, 0, 0, 0, 0, 0))(
                            toks, lanes, idxs, seeds, pos, lbt, lon)
                else:
                    nxt, lanes = jax.vmap(
                        row, in_axes=(0, 0, 0, 0, 0, None, None))(
                            toks, lanes, idxs, seeds, pos, None, None)
                # inactive rows (block-table = trash page) scatter
                # their masked garbage into page 0 — harmless, never
                # attended, never owned
                pages = tree_map(
                    lambda pg, ln: scatter_pages(
                        pg, bt, window(ln, idxs, 1), idxs),
                    pages, lanes)
            nxt = jnp.where(active, nxt, cfg.pad_id)
            adv = active.astype(jnp.int32)
            return nxt, idxs + adv, pos + adv, pages

        def verify(params, pages, bt, toks, idxs, active, seeds, pos,
                   drafts, a_pg=None, b_pg=None, lbt=None, lon=None):
            self.trace_counts["verify"] += 1    # the compile-count hook
            if kernel_path:
                cache = paged_cache(pages, bt)
                chunks = jnp.concatenate([toks[:, None], drafts], 1)
                positions = (idxs[:, None]
                             + jnp.arange(K + 1, dtype=jnp.int32)[None])
                if lora:
                    logits, h, cache = forward(params, chunks, cache,
                                               idxs,
                                               positions=positions,
                                               chunk_decode=True)
                    # flatten the (N, K+1) verify rows into the batch
                    # axis the paged delta kernel streams — each row
                    # repeats its slot's adapter block-table entry
                    Hd = h.shape[-1]
                    btr = jnp.repeat(lbt, K + 1, axis=0)
                    onr = jnp.repeat(lon, K + 1, axis=0)
                    logits = lora_batch(
                        logits.reshape(-1, logits.shape[-1]),
                        h.reshape(-1, Hd), a_pg, b_pg, btr, onr
                    ).reshape(logits.shape)
                else:
                    logits, cache = apply_fn(params, chunks, cache,
                                             idxs, positions=positions,
                                             chunk_decode=True)
                pages = unpack_cache(cache)
                posm = (pos[:, None]
                        + jnp.arange(K + 1, dtype=jnp.int32)[None])
                seedm = jnp.broadcast_to(seeds[:, None], posm.shape)
                V = logits.shape[-1]
                tgt = fused_sample(
                    logits.reshape(-1, V), seedm.reshape(-1),
                    posm.reshape(-1),
                    **sample_kw).reshape(-1, K + 1)
                acc = jnp.sum(jnp.cumprod(
                    (tgt[:, :K] == drafts).astype(jnp.int32), axis=1),
                    axis=1)
            else:
                lanes = tree_map(lambda p: gather_pages(p, bt, L),
                                 pages)

                def row(tok, lane, idx, seed, p, dr, lrow, on):
                    lane = tree_map(lambda x: x[None], lane)
                    chunk = jnp.concatenate([tok[None], dr])  # (K+1,)
                    if lora:
                        logits, h, lane = forward(params, chunk[None],
                                                  lane, idx,
                                                  chunk_decode=True)
                        lg = lora_rowwise(logits[0], h[0], a_pg, b_pg,
                                          lrow, on)
                    else:
                        logits, lane = apply_fn(params, chunk[None],
                                                lane, idx,
                                                chunk_decode=True)
                        lg = logits[0]
                    tgt = counter_sample(
                        lg, seed,
                        p + jnp.arange(K + 1, dtype=jnp.int32),
                        **sample_kw)
                    a = jnp.sum(jnp.cumprod(
                        (tgt[:K] == dr).astype(jnp.int32)))
                    return tgt, a, tree_map(lambda x: x[0], lane)

                if lora:
                    tgt, acc, lanes = jax.vmap(
                        row, in_axes=(0, 0, 0, 0, 0, 0, 0, 0))(
                            toks, lanes, idxs, seeds, pos, drafts,
                            lbt, lon)
                else:
                    tgt, acc, lanes = jax.vmap(
                        row, in_axes=(0, 0, 0, 0, 0, 0, None, None))(
                            toks, lanes, idxs, seeds, pos, drafts,
                            None, None)
                pages = tree_map(
                    lambda pg, ln: scatter_pages(
                        pg, bt, window(ln, idxs, K + 1), idxs),
                    pages, lanes)
            acc = jnp.where(active, acc, 0)
            adv = jnp.where(active, acc + 1, 0)
            nxt = jnp.where(
                active,
                jnp.take_along_axis(tgt, acc[:, None], 1)[:, 0],
                cfg.pad_id)
            return tgt, acc, nxt, idxs + adv, pos + adv, pages

        donate = () if jax.default_backend() == "cpu" else (1,)
        self._prefill = jax.jit(prefill, donate_argnums=donate)
        if self._spec:
            self._verify = jax.jit(verify, donate_argnums=donate)
        else:
            self._decode = jax.jit(decode, donate_argnums=donate)

    # ---- multi-tenant LoRA adapters -------------------------------------

    def register_adapter(self, tenant: str, A, B, *,
                         scale: float = 1.0):
        """Install ``tenant``'s LM-head adapter (``A`` (H, r), ``B``
        (r, V)); subsequent ``submit(tenant=...)`` requests decode
        through it. Two-phase page publish (`serving.lora`) — safe to
        call while the engine is serving."""
        if self._lora is None:
            raise RuntimeError(
                "register_adapter requires EngineConfig(lora_rank > 0)")
        return self._lora.register(tenant, A, B, scale=scale)

    def unregister_adapter(self, tenant: str) -> None:
        """Retire ``tenant``'s adapter. In-flight requests keep their
        pinned pages until retirement; new submits with this tenant
        decode adapterless (zero row)."""
        if self._lora is None:
            raise RuntimeError(
                "unregister_adapter requires "
                "EngineConfig(lora_rank > 0)")
        self._lora.unregister(tenant)

    def _lora_release(self, slot: int) -> None:
        """Unpin a slot's adapter pages and zero its device row (the
        LoRA analogue of the trash-page reset: the freed lane keeps
        computing, so its row must stop naming live adapter pages)."""
        if self._lora is None:
            return
        self._lora.release(slot)
        self._d_lora_bt = self._d_lora_bt.at[slot].set(
            jnp.zeros((self.cfg.lora_rank,), jnp.int32))
        self._d_lora_on = self._d_lora_on.at[slot].set(False)

    # ---- submission -----------------------------------------------------

    def submit(self, tokens, max_new_tokens: int, *, prefix=None,
               deadline: Optional[float] = None,
               req_id: Optional[int] = None,
               qos: str = "best_effort", tenant: Optional[str] = None,
               seed: Optional[int] = None) -> int:
        """Enqueue a request. Raises `Backpressure` when the queue is
        full and holds no weaker-class victim to shed (the caller's
        429, with ``retry_after_s``/``queue_depth`` attached) and
        `ValueError` when the request can NEVER fit (prefix + prompt +
        max_new_tokens - 1 > max_len — not backpressure, a contract
        violation). ``seed`` pins the request's sampling stream; None
        derives one from the request id (stable across resubmission)."""
        req = Request(tokens=tokens, max_new_tokens=max_new_tokens,
                      prefix=prefix, deadline=deadline, req_id=req_id,
                      qos=qos, tenant=tenant, seed=seed)
        if req.seed is None:
            req.seed = derive_request_seed(self.cfg.seed, req.req_id)
        if req.total_len > self.cfg.max_len:
            raise ValueError(
                f"request needs {req.total_len} cache positions but "
                f"slots hold max_len={self.cfg.max_len}")
        try:
            rid = self.scheduler.submit(req)
        except Backpressure as e:
            self.metrics.event(req.req_id, "queued",
                               n_prompt=req.tokens.size)
            self.metrics.event(req.req_id, "rejected", reason=e.reason)
            raise
        # a weaker-class request may have been shed to admit this one
        for victim in self.scheduler.drain_shed():
            self.metrics.incr("sheds")
            self._finish(victim.req_id, "evicted",
                         f"shed ({victim.qos})", [])
        self.metrics.event(rid, "queued", n_prompt=req.tokens.size)
        return rid

    def cancel(self, req_id: int) -> bool:
        """Cancel a queued OR running request. A running request is
        retired IMMEDIATELY: its KV slot and any refcounted
        shared-prefix page are released before this returns, not at
        the next step boundary — a frontend cancelling a hedge loser
        (or shedding load) must get the capacity back now, and an idle
        engine that is never stepped again must not leak the slot. A
        request whose ADMISSION is being built right now (an ingest
        thread racing the engine loop's prefill chain) is flagged and
        retired the moment the chain completes."""
        if self.scheduler.cancel(req_id):
            self._finish(req_id, "cancelled", "cancelled queued", [])
            return True
        with self._admit_lock:
            if req_id == self._mid_admit:
                self._cancel_mid.add(req_id)
                return True
        for i, slot in enumerate(self._slots):
            if slot is not None and slot.req.req_id == req_id:
                self._retire(i, "cancelled", "cancelled running")
                return True
        return False

    # ---- the engine loop ------------------------------------------------

    def step(self) -> int:
        """One engine iteration: retire (deadline/cancel) → admit → one
        decode (or speculative verify) step over every occupied slot.
        Returns the number of active slots that decoded (0 = idle)."""
        now = time.monotonic()
        for req in self.scheduler.expire(now):
            self._finish(req.req_id, "evicted", "deadline (queued)", [])
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            if (slot.req.deadline is not None
                    and slot.req.deadline <= now):
                self._retire(i, "evicted", "deadline")
        self._admit_all()
        n_active = self._n_active
        if n_active == 0:
            self.metrics.step_sample(0, self.cfg.max_slots,
                                     self.scheduler.depth)
            return 0
        if self._spec:
            self._spec_step()
        else:
            self._decode_step()
        self.metrics.step_sample(n_active, self.cfg.max_slots,
                                 self.scheduler.depth)
        return n_active

    def _lora_args(self) -> tuple:
        """The adapter-page operands appended to every executable call
        when LoRA is enabled — page pools + per-slot block-table rows,
        all device-resident (the step path stays host-free)."""
        if self._lora is None:
            return ()
        return (self._lora.a_pages, self._lora.b_pages,
                self._d_lora_bt, self._d_lora_on)

    def _decode_step(self):
        with annotate("serving/decode_step"):
            if self._paged:
                nxt, idxs, pos, self.kv.pages = self._decode(
                    self.params, self.kv.pages, self._d_bt,
                    self._d_toks, self._d_idxs, self._d_active,
                    self._d_seeds, self._d_pos, *self._lora_args())
            else:
                nxt, idxs, pos, self.kv.cache = self._decode(
                    self.params, self.kv.cache, self._d_toks,
                    self._d_idxs, self._d_active, self._d_seeds,
                    self._d_pos, *self._lora_args())
        self._d_toks, self._d_idxs, self._d_pos = nxt, idxs, pos
        if self._defer:
            self._tok_log[self._step_no] = nxt     # fetched at retire
            toks = None
        else:
            toks = np.asarray(nxt)                 # eos needs the values
        self._step_no += 1
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            slot.n_out += 1
            self.metrics.event(slot.req.req_id, "token")
            if toks is not None:
                tok = int(toks[i])
                slot.produced.append(tok)
                slot.history.append(tok)
                if tok == self.cfg.eos_id:
                    slot.eos_seen = True
                    self._retire(i, "done", "eos")
                    continue
            if slot.n_out >= slot.req.max_new_tokens:
                self._retire(i, "done", "length")

    def _spec_step(self):
        """One draft → verify round for every occupied slot: the host
        proposes K tokens per slot from its own history, ONE verify
        dispatch scores all slots, and each slot emits its accepted
        prefix + the correction token (1..K+1 tokens per round). The
        per-slot accept counts gate retirement, so this path always
        reads the (small) verify outputs back."""
        cfg = self.cfg
        K = cfg.num_draft
        drafts = np.zeros((cfg.max_slots, K), np.int32)
        for i, st in enumerate(self._slots):
            if st is not None and st.in_batch:
                drafts[i] = np.asarray(
                    self._draft_propose(st.history, K),
                    np.int32).reshape(K)
        with annotate("serving/verify_step"):
            if self._paged:
                tgt, acc, nxt, idxs, pos, self.kv.pages = self._verify(
                    self.params, self.kv.pages, self._d_bt,
                    self._d_toks, self._d_idxs, self._d_active,
                    self._d_seeds, self._d_pos, jnp.asarray(drafts),
                    *self._lora_args())
            else:
                tgt, acc, nxt, idxs, pos, self.kv.cache = self._verify(
                    self.params, self.kv.cache, self._d_toks,
                    self._d_idxs, self._d_active, self._d_seeds,
                    self._d_pos, jnp.asarray(drafts),
                    *self._lora_args())
        self._d_toks, self._d_idxs, self._d_pos = nxt, idxs, pos
        tgt_np = np.asarray(tgt)
        acc_np = np.asarray(acc)
        self._step_no += 1
        for i, st in enumerate(self._slots):
            if st is None or not st.in_batch:
                continue
            a = int(acc_np[i])
            remaining = st.req.max_new_tokens - st.n_out
            emitted = [int(t) for t in tgt_np[i, :a + 1][:remaining]]
            # accept-rate accounting clamps to the EMISSION window:
            # only `remaining` draft positions could ever land, so a
            # truncated final round must not credit drafts past it —
            # uncapped counts systematically overstate draft quality
            # on short completions (review finding)
            d_used = min(K, remaining)
            a_used = min(a, d_used)
            st.drafted += d_used
            st.accepted += a_used
            self.metrics.incr("spec_drafted", d_used)
            self.metrics.incr("spec_accepted", a_used)
            done_reason = None
            n_emit = 0
            for t in emitted:
                st.produced.append(t)
                st.history.append(t)
                st.n_out += 1
                n_emit += 1
                if cfg.eos_id is not None and t == cfg.eos_id:
                    st.eos_seen = True
                    done_reason = "eos"
                    break
            self.metrics.event(st.req.req_id, "token", n=n_emit)
            if done_reason is None and st.n_out >= st.req.max_new_tokens:
                done_reason = "length"
            if done_reason is not None:
                self._retire(i, "done", done_reason)

    def run(self, max_steps: Optional[int] = None) -> Dict[int,
                                                           RequestResult]:
        """Step until queue and slots drain (or ``max_steps``)."""
        steps = 0
        while self.scheduler.depth > 0 or any(
                s is not None for s in self._slots):
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self.results

    # ---- admission ------------------------------------------------------

    def _admit_all(self):
        while self.kv.n_free > 0:
            prefer = None
            if (self.cfg.prefix_cache
                    and self.scheduler.depth > self.kv.n_free):
                # near capacity: slots are the scarce resource, and a
                # radix hit turns one over sooner — prefer hits WITHIN
                # a class (the scheduler never lets this cross the
                # QoS lattice)
                prefer = self._would_hit
            batch = self.scheduler.pop(1, prefer=prefer)
            if not batch:
                return
            self._admit(batch[0])

    def _full_prompt(self, req: Request) -> np.ndarray:
        if req.prefix:
            return np.concatenate([np.asarray(req.prefix, np.int32),
                                   req.tokens])
        return req.tokens

    def _would_hit(self, req: Request) -> bool:
        """Prefix-aware admission probe: would this queued request hit
        a registered page right now? A host-side radix walk — never a
        device op — and memoized per (request, page-store version):
        `pop(prefer=)` evaluates it for every queued request on every
        admission, so an uncached probe would cost O(depth x prompt)
        host work per freed slot while the queue stays deep (review
        finding)."""
        ver = self.kv.store_version
        if self._probe_cache_ver != ver:
            self._probe_cache_ver = ver
            self._probe_cache.clear()
        hit = self._probe_cache.get(req.req_id)
        if hit is None:
            full = self._full_prompt(req)
            hit = self.kv.match(full, int(full.size) - 1)[1] is not None
            if len(self._probe_cache) >= 2 * self.cfg.max_queue:
                # entries for long-departed requests only die on a
                # store-version bump; an all-hit steady state never
                # bumps, so cap the memo outright (a wholesale clear
                # just re-probes the <= max_queue live entries) —
                # review finding
                self._probe_cache.clear()
            self._probe_cache[req.req_id] = hit
        return hit

    def _admit(self, req: Request):
        cfg = self.cfg
        if (req.deadline is not None
                and req.deadline <= time.monotonic()):
            # expired between the step's expire() sweep and this
            # admission (e.g. while an earlier admission's prefill ran)
            # — evict before paying prefill or touching the pool
            self._finish(req.req_id, "evicted", "deadline (queued)", [])
            return
        slot = self.kv.alloc()
        assert slot is not None
        if self._paged:
            # the freshly-owned page row must be on device before any
            # prefill chunk gathers/scatters through it
            self._sync_bt(slot)
        if self._lora is not None:
            # pin the tenant's adapter pages and patch the slot's row
            # BEFORE the prefill chain — token 0 already samples
            # through the fused epilogue. An unregistered (or None)
            # tenant gets the zero row: same executable, exact-zero
            # delta, flag off.
            lrow, lora_on = self._lora.acquire(req.tenant, slot)
            self._d_lora_bt = self._d_lora_bt.at[slot].set(
                jnp.asarray(lrow, jnp.int32))
            self._d_lora_on = self._d_lora_on.at[slot].set(
                bool(lora_on))
        prefix = tuple(req.prefix) if req.prefix else ()
        full = self._full_prompt(req)
        key = page = None
        if cfg.prefix_cache:
            # cap at len-1: a full-prompt hit must still leave >= 1
            # real token to prefill (the logit the first token samples
            # from)
            key, page = self.kv.match(full, int(full.size) - 1)
            self.metrics.incr("prefix_lookups")
            if page is not None:
                self.metrics.incr("prefix_hits")
                self.metrics.incr("prefix_saved_tokens", page.length)
        elif prefix:
            # radix matching off: the PR-7 exact-tuple contract still
            # holds — a second sharer of the same explicit prefix must
            # reuse (not re-register: put_prefix would raise) the page
            # (review finding)
            page = self.kv.get_prefix(prefix)
            key = prefix if page is not None else None
        hit = page is not None
        self.metrics.event(
            req.req_id, "prefill",
            prefix_hit=(hit if cfg.prefix_cache else None),
            prefix_saved=(page.length if hit else 0))
        with self._admit_lock:
            self._mid_admit = req.req_id
        try:
            with annotate("serving/prefill"):
                if hit:
                    self.kv.acquire_prefix(key, slot)
                    if self._paged:
                        # the acquire REWIRED the slot's block table
                        # onto the shared pages — no lane copy exists
                        # to install, the pages themselves are the hit
                        self._sync_bt(slot)
                        install_lane, idx0 = None, page.length
                    else:
                        install_lane, idx0 = page.lane, page.length
                    if (prefix and idx0 < len(prefix)
                            and not self.kv.has_prefix(prefix)):
                        # partial hit below the caller's stated share
                        # point: pay the prefix remainder, then pin the
                        # explicit page at its stated length so later
                        # sharers hit in full
                        self._run_chunks(slot, full[idx0:len(prefix)],
                                         idx0, install_lane, req.seed)
                        self._register_page(slot, prefix, len(prefix))
                        install_lane, idx0 = None, len(prefix)
                    tok0 = self._run_chunks(slot, full[idx0:], idx0,
                                            install_lane, req.seed)
                elif prefix:
                    # first sharer pays: run the prefix's own chunks,
                    # snapshot the lane as the page, keep going
                    self._run_chunks(slot, full[:len(prefix)], 0,
                                     self.kv.zeros_lane, req.seed)
                    self._register_page(slot, prefix, len(prefix))
                    tok0 = self._run_chunks(slot, full[len(prefix):],
                                            len(prefix), None, req.seed)
                else:
                    tok0 = self._run_chunks(slot, full, 0,
                                            self.kv.zeros_lane, req.seed)
            if cfg.prefix_cache and not prefix:
                # auto-registration at the CHUNK-ALIGNED share point:
                # canonical lengths, so requests that split the same
                # prompt differently converge on one key. The last
                # token stays uncached (a future identical prompt must
                # still prefill >= 1 token).
                C = cfg.prefill_chunk
                lstar = ((int(full.size) - 1) // C) * C
                if lstar >= C and lstar > (page.length if hit else 0):
                    akey = tuple(int(t) for t in full[:lstar])
                    if not self.kv.has_prefix(akey):
                        self._register_page(slot, akey, lstar)
        except BaseException:
            # the first-sharer stranding window (ISSUE 15 satellite): a
            # prefill chain that dies mid-flight (chaos kill, XLA
            # error) must not leak the allocated slot or any acquired
            # page refs — free() releases both, fully-registered pages
            # stay (their snapshots completed), and the request's
            # verdict belongs to the caller's supervision (re-raise)
            self.kv.free(slot)
            if self._paged:
                self._sync_bt(slot)     # row back to the trash page
            self._lora_release(slot)
            with self._admit_lock:
                self._mid_admit = None
                self._cancel_mid.discard(req.req_id)
            raise
        self.metrics.event(req.req_id, "first_token")
        idx = int(full.size)
        st = _Slot(req=req, first_tok=tok0, start_step=self._step_no,
                   history=[int(t) for t in full])
        self._slots[slot] = st
        # close the mid-admission window only AFTER the slot is
        # published (a cancel arriving from here on routes to the
        # _slots scan), then drain any cancel that landed during the
        # chain under the handshake lock — clearing before publication
        # left a gap where a concurrent cancel found neither
        # _mid_admit nor _slots and returned a false False (review
        # finding)
        with self._admit_lock:
            self._mid_admit = None
            cancelled = req.req_id in self._cancel_mid
            self._cancel_mid.discard(req.req_id)
        first = None
        if not self._defer:
            first = int(np.asarray(tok0))
            st.produced.append(first)
            st.history.append(first)
            st.first_tok = first
        if cancelled:
            # the cancel preceded any published result, so it wins
            # over an eos/length completion in this same admission —
            # the caller already holds cancel()'s True (review
            # finding: this used to lose to the eos retire and leak
            # the _cancel_mid entry)
            self._retire(slot, "cancelled", "cancelled running")
            return
        if (not self._defer and cfg.eos_id is not None
                and first == cfg.eos_id):
            st.eos_seen = True
            self._retire(slot, "done", "eos")
            return
        if req.max_new_tokens == 1:
            # finished at prefill: never occupies a decode step
            self._retire(slot, "done", "length")
            return
        # device-side boundary patch: the slot joins the decode batch
        # (pos=1: the next sampled token is the request's output #1 —
        # prefill already drew #0 from the same per-request stream)
        self._d_toks = self._d_toks.at[slot].set(
            jnp.asarray(tok0, jnp.int32))
        self._d_idxs = self._d_idxs.at[slot].set(idx)
        self._d_active = self._d_active.at[slot].set(True)
        self._d_seeds = self._d_seeds.at[slot].set(int(req.seed))
        self._d_pos = self._d_pos.at[slot].set(1)
        st.in_batch = True
        self._n_active += 1

    def _register_page(self, slot: int, pkey: tuple, length: int):
        """Snapshot ``slot``'s lane (which holds ``length`` completed
        positions) as a refcounted prefix page — put + acquire as one
        step, so no exception window can leave a registered page
        without its owner's ref. Paged mode registers by REFERENCE: the
        registry pins the slot's own pages (no device copy at all —
        copy-on-register is gone along with copy-on-admit); the stored
        length floors to a page multiple, so sub-page tails simply stay
        private and sharers re-prefill them."""
        if self._paged:
            if self.kv.register_prefix(slot, pkey, length) is not None:
                self.kv.acquire_prefix(pkey, slot)
            return
        lane = jax.tree_util.tree_map(lambda x: x[slot:slot + 1],
                                      self.kv.cache)
        self.kv.put_prefix(pkey, lane, length)
        self.kv.acquire_prefix(pkey, slot)

    def _run_chunks(self, slot: int, tokens: np.ndarray, idx0: int,
                    install_lane, seed: int):
        """Feed ``tokens`` through the prefill executable in fixed-width
        right-padded chunks starting at cache position ``idx0``.
        ``install_lane``: batch-1 pytree written over the slot's lane
        before the FIRST chunk (zeros, or a shared-prefix page); None
        continues on the lane as-is. Returns the (device) token sampled
        after the final chunk (drawn from the request's own counter
        stream at output position 0)."""
        C = self.cfg.prefill_chunk
        n = int(tokens.size)
        tok = None
        for c in range(math.ceil(n / C)):
            seg = tokens[c * C:(c + 1) * C]
            buf = np.zeros((1, C), np.int32)
            buf[0, :seg.size] = seg
            if self._paged:
                # no install operand: prefix hits arrive as shared page
                # ids already synced into the device block table
                tok, self.kv.pages = self._prefill(
                    self.params, self.kv.pages, self._d_bt,
                    np.int32(slot), buf, np.int32(idx0 + c * C),
                    np.int32(seg.size), np.int32(seed),
                    *self._lora_args())
                continue
            install = np.bool_(c == 0 and install_lane is not None)
            lane_arg = (install_lane if install
                        else self.kv.zeros_lane)
            tok, self.kv.cache = self._prefill(
                self.params, self.kv.cache, np.int32(slot), lane_arg,
                install, buf, np.int32(idx0 + c * C),
                np.int32(seg.size), np.int32(seed),
                *self._lora_args())
        return tok

    # ---- retirement -----------------------------------------------------

    def _materialize(self, st: _Slot, slot_idx: int) -> List[int]:
        """Collect a deferred-mode slot's tokens from the step log (the
        only point the engine blocks on decode outputs)."""
        out = [int(np.asarray(st.first_tok))]
        for s in range(st.start_step,
                       st.start_step + max(st.n_out - 1, 0)):
            buf = self._tok_log[s]
            if not isinstance(buf, np.ndarray):     # memoize the fetch
                buf = np.asarray(buf)
                self._tok_log[s] = buf
            out.append(int(buf[slot_idx]))
        return out

    def _prune_log(self):
        if not self._tok_log:
            return
        live = [s.start_step for s in self._slots if s is not None]
        floor = min(live) if live else self._step_no
        for s in [s for s in self._tok_log if s < floor]:
            del self._tok_log[s]

    def _retire(self, slot_idx: int, status: str, reason: str):
        slot = self._slots[slot_idx]
        self._slots[slot_idx] = None
        if self._defer:
            produced = self._materialize(slot, slot_idx)
            self._prune_log()
        else:
            produced = slot.produced
        if slot.in_batch:
            # boundary patch: drop the lane from the decode batch (the
            # freed lane keeps computing masked garbage — values only)
            self._d_active = self._d_active.at[slot_idx].set(False)
            self._n_active -= 1
        self.kv.free(slot_idx)
        if self._paged:
            # the freed row now names the trash page — REQUIRED, not
            # hygiene: the retired lane keeps scattering its masked
            # garbage every step, and its old pages may be reallocated
            # (or live on as shared prefix pages) immediately
            self._sync_bt(slot_idx)
        self._lora_release(slot_idx)
        spec = ({"n_drafted": slot.drafted, "n_accepted": slot.accepted}
                if self._spec else {})
        self._finish(slot.req.req_id, status, reason, produced, **spec)

    def _finish(self, req_id: int, status: str, reason: str,
                produced: List[int], **fields):
        if status == "evicted" and not reason.startswith("shed"):
            self.metrics.incr("evictions")  # sheds counted separately
        self.metrics.event(req_id, status, reason=reason,
                           n_generated=len(produced), **fields)
        self.results[req_id] = RequestResult(
            req_id=req_id, status=status,
            tokens=np.asarray(produced, np.int32), reason=reason)

    # ---- introspection --------------------------------------------------

    def pop_result(self, req_id: int) -> Optional[RequestResult]:
        """Remove and return a finished request's result — the
        long-running server's pressure valve (`results` is otherwise
        bounded only by the number of requests ever served; pair with
        `metrics.drain()`)."""
        return self.results.pop(req_id, None)

    @property
    def n_active(self) -> int:
        return self._n_active

    def slot_view(self) -> List[Optional[int]]:
        """req_id per slot (None = free) — the occupancy diagram."""
        return [None if s is None else s.req.req_id for s in self._slots]
