"""Admission queue for the serving engine: bounded backpressure, FIFO /
shortest-prompt-first policies, per-request deadlines, cancellation.

The scheduler is pure host-side bookkeeping — it decides WHICH request
enters a freed KV slot; the engine decides WHEN (whenever a slot is
free at a step boundary). Policies:

- ``fifo`` — arrival order. Predictable TTFT ordering; long prompts at
  the head delay everyone (head-of-line blocking).
- ``sjf`` — shortest prompt first. Minimizes mean TTFT under mixed
  lengths (a short prompt's prefill is cheap, so serving it first costs
  the long one little); starvation is bounded by the queue's deadline
  mechanism, not by the policy.

Backpressure is a bounded queue: `submit` on a full queue raises
`Backpressure` carrying a machine-readable reason — the caller (an RPC
frontend, `runtime.RequestFeeder`) turns that into a 429/retry. A
silent unbounded queue would instead convert overload into unbounded
TTFT, the failure mode continuous batching exists to avoid.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

POLICIES = ("fifo", "sjf")

_ids = itertools.count()


def new_request_id() -> int:
    """Reserve a request id up front — for callers that may SUBMIT the
    same logical request several times (`runtime.RequestFeeder`'s
    bounded backpressure retry): a stable id keeps metrics at one
    record per request instead of one per attempt."""
    return next(_ids)


class Backpressure(Exception):
    """Admission rejected; ``reason`` says why (machine-readable)."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


@dataclasses.dataclass
class Request:
    """One generation request.

    ``tokens``: prompt ids (1-D). ``prefix``: optional shared-prefix ids
    (e.g. a system prompt) — requests with an identical prefix tuple
    share its K/V through the pool's prefix pages. ``deadline``:
    absolute `time.monotonic()` instant; past it the request is evicted
    wherever it is (queued or mid-decode) and its slot freed.
    """

    tokens: np.ndarray
    max_new_tokens: int
    prefix: Optional[Tuple[int, ...]] = None
    deadline: Optional[float] = None
    req_id: Optional[int] = None
    submitted_at: float = 0.0

    def __post_init__(self):
        self.tokens = np.asarray(self.tokens, np.int32).reshape(-1)
        if self.tokens.size < 1:
            raise ValueError("empty prompt (after the shared prefix, a "
                             "request needs >= 1 token of its own)")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.prefix is not None:
            self.prefix = tuple(int(t) for t in self.prefix)
        if self.req_id is None:
            self.req_id = next(_ids)

    @property
    def total_len(self) -> int:
        """Cache positions the request needs: prefix + prompt +
        generated (the final sampled token is never written back)."""
        plen = len(self.prefix) if self.prefix else 0
        return plen + self.tokens.size + self.max_new_tokens - 1


class Scheduler:
    """Bounded admission queue with pluggable dequeue policy."""

    def __init__(self, max_queue: int = 64, policy: str = "fifo"):
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, "
                             f"got {policy!r}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = int(max_queue)
        self.policy = policy
        self._queue: List[Request] = []
        # submit may run on an ingest thread (`runtime.RequestFeeder`)
        # while the engine loop pops — one lock keeps the bound exact
        self._lock = threading.Lock()

    @property
    def depth(self) -> int:
        return len(self._queue)

    def submit(self, req: Request, now: Optional[float] = None) -> int:
        """Enqueue or raise `Backpressure`. Returns the request id."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if len(self._queue) >= self.max_queue:
                raise Backpressure(
                    f"queue full ({self.max_queue}); retry later")
            if req.deadline is not None and req.deadline <= now:
                raise Backpressure("deadline already passed at submit")
            req.submitted_at = now
            self._queue.append(req)
            return req.req_id

    def cancel(self, req_id: int) -> bool:
        """Remove a QUEUED request. Returns False if not queued (it may
        already be running — the engine owns cancellation there)."""
        with self._lock:
            for i, r in enumerate(self._queue):
                if r.req_id == req_id:
                    del self._queue[i]
                    return True
            return False

    def expire(self, now: Optional[float] = None) -> List[Request]:
        """Drop and return queued requests whose deadline has passed."""
        now = time.monotonic() if now is None else now
        with self._lock:
            dead = [r for r in self._queue
                    if r.deadline is not None and r.deadline <= now]
            if dead:
                gone = {r.req_id for r in dead}
                self._queue = [r for r in self._queue
                               if r.req_id not in gone]
            return dead

    def pop(self, n: int) -> List[Request]:
        """Up to ``n`` requests to admit, per policy. Deadline expiry is
        the ENGINE's job (call `expire` first) so evictions are observed
        in one place."""
        with self._lock:
            if n <= 0 or not self._queue:
                return []
            if self.policy == "sjf":
                order = sorted(
                    range(len(self._queue)),
                    key=lambda i: (self._queue[i].tokens.size, i))
                take = order[:n]
                out = [self._queue[i] for i in take]  # shortest first
                taken = set(take)
                self._queue = [r for i, r in enumerate(self._queue)
                               if i not in taken]
                return out
            out, self._queue = self._queue[:n], self._queue[n:]
            return out

    def snapshot(self) -> Sequence[int]:
        return [r.req_id for r in self._queue]
