"""Admission queue for the serving engine: bounded backpressure, FIFO /
shortest-prompt-first policies, per-tenant QoS classes, per-request
deadlines, cancellation.

The scheduler is pure host-side bookkeeping — it decides WHICH request
enters a freed KV slot; the engine decides WHEN (whenever a slot is
free at a step boundary). Policies:

- ``fifo`` — arrival order. Predictable TTFT ordering; long prompts at
  the head delay everyone (head-of-line blocking).
- ``sjf`` — shortest prompt first. Minimizes mean TTFT under mixed
  lengths (a short prompt's prefill is cheap, so serving it first costs
  the long one little); starvation is bounded by the queue's deadline
  mechanism, not by the policy.

QoS classes (`Request.qos`) generalize the deadline mechanism into a
tenant contract, ordered strongest to weakest:

- ``guaranteed`` — dequeued first; NEVER shed while weaker-class load
  is present (the overload drill's pinned property).
- ``best_effort`` — the default; dequeued after guaranteed, shed only
  once every sheddable request is gone.
- ``sheddable`` — batch/backfill traffic; first out the airlock under
  overload, both at the queue (`submit` sheds it to admit a stronger
  class) and at the frontend (degraded-mode load shedding).

Within a class the dequeue policy (fifo/sjf) applies unchanged, so the
class lattice never reorders same-class tenants — cross-class priority,
intra-class fairness.

Backpressure is a bounded queue: `submit` on a full queue first tries
to SHED a strictly-weaker queued request (weakest class first, youngest
first — it has waited least); only when no weaker victim exists does it
raise `Backpressure`, carrying structured fields — ``queue_depth`` and
``retry_after_s`` (the backoff floor `runtime.RequestFeeder` honors) —
so the caller's 429 tells the client WHEN to come back, not just no.
A silent unbounded queue would instead convert overload into unbounded
TTFT, the failure mode continuous batching exists to avoid.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

POLICIES = ("fifo", "sjf")

#: QoS classes, strongest first; index = priority rank (lower = first
#: dequeued, last shed)
QOS_CLASSES = ("guaranteed", "best_effort", "sheddable")

_ids = itertools.count()


def new_request_id() -> int:
    """Reserve a request id up front — for callers that may SUBMIT the
    same logical request several times (`runtime.RequestFeeder`'s
    bounded backpressure retry, `serving.replica`'s failover
    resubmission): a stable id keeps metrics at one record per request
    AND (via the engine's derived per-request sampling seed) makes the
    regenerated token stream bit-identical to the lost one."""
    return next(_ids)


def qos_rank(qos: str) -> int:
    """Priority rank of a QoS class (0 = strongest). Raises on unknown
    classes — a typo'd class silently becoming best-effort would void
    the tenant contract."""
    try:
        return QOS_CLASSES.index(qos)
    except ValueError:
        raise ValueError(f"qos must be one of {QOS_CLASSES}, got {qos!r}")


class Backpressure(Exception):
    """Admission rejected; ``reason`` says why (machine-readable).

    Structured fields (both optional — None when the rejecting layer
    can't estimate them):

    - ``queue_depth``: queued requests at rejection time.
    - ``retry_after_s``: the server's backoff hint — the FLOOR for any
      client retry delay (`runtime.RequestFeeder` clamps its
      exponential-backoff schedule up to it). 0.0 means "retrying is
      pointless" (e.g. the deadline already passed at submit).
    """

    def __init__(self, reason: str, *,
                 queue_depth: Optional[int] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(reason)
        self.reason = reason
        self.queue_depth = queue_depth
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class Request:
    """One generation request.

    ``tokens``: prompt ids (1-D). ``prefix``: optional shared-prefix ids
    (e.g. a system prompt) — requests with an identical prefix tuple
    share its K/V through the pool's prefix pages. ``deadline``:
    absolute `time.monotonic()` instant; past it the request is evicted
    wherever it is (queued or mid-decode) and its slot freed. ``qos``:
    tenant class (see `QOS_CLASSES`). ``seed``: per-request sampling
    seed — the engine derives one from the request id when None, so a
    resubmitted request (same id) regenerates the identical stream.
    """

    tokens: np.ndarray
    max_new_tokens: int
    prefix: Optional[Tuple[int, ...]] = None
    deadline: Optional[float] = None
    req_id: Optional[int] = None
    qos: str = "best_effort"
    tenant: Optional[str] = None
    seed: Optional[int] = None
    submitted_at: float = 0.0

    def __post_init__(self):
        self.tokens = np.asarray(self.tokens, np.int32).reshape(-1)
        if self.tokens.size < 1:
            raise ValueError("empty prompt (after the shared prefix, a "
                             "request needs >= 1 token of its own)")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.prefix is not None:
            self.prefix = tuple(int(t) for t in self.prefix)
        qos_rank(self.qos)                     # validate loudly
        if self.seed is not None:
            # the engine's counter keys take int32 seeds; an unmasked
            # 64-bit seed would pass admission and then crash the
            # engine step — under a supervisor that reads as a replica
            # crash loop. Fold deterministically instead.
            self.seed = int(self.seed) & 0x7FFFFFFF
        if self.req_id is None:
            self.req_id = next(_ids)

    @property
    def total_len(self) -> int:
        """Cache positions the request needs: prefix + prompt +
        generated (the final sampled token is never written back)."""
        plen = len(self.prefix) if self.prefix else 0
        return plen + self.tokens.size + self.max_new_tokens - 1

    @property
    def rank(self) -> int:
        return qos_rank(self.qos)


class Scheduler:
    """Bounded admission queue with pluggable dequeue policy and QoS
    class priority. Shed victims land in an internal list the OWNER
    (engine/frontend) drains via `drain_shed` and finishes as evicted —
    the scheduler never invents terminal results itself."""

    def __init__(self, max_queue: int = 64, policy: str = "fifo",
                 retry_after_s: float = 0.05):
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, "
                             f"got {policy!r}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = int(max_queue)
        self.policy = policy
        # the 429 hint under a full queue scales with how much of the
        # queue must drain before a retry can land — depth/max_queue
        # full queues hint one full unit, near-empty ones a fraction
        self.retry_after_base_s = float(retry_after_s)
        self._queue: List[Request] = []
        self._shed: List[Request] = []
        # submit may run on an ingest thread (`runtime.RequestFeeder`)
        # while the engine loop pops — one lock keeps the bound exact
        self._lock = threading.Lock()

    @property
    def depth(self) -> int:
        return len(self._queue)

    def _retry_after(self) -> float:
        return self.retry_after_base_s * max(
            1.0, len(self._queue) / self.max_queue)

    def submit(self, req: Request, now: Optional[float] = None) -> int:
        """Enqueue or raise `Backpressure`. On a full queue, first
        sheds a strictly-weaker-class queued request (weakest class
        first, youngest first — it has waited least and its tenant
        signed up for shedding); the victim lands in `drain_shed`.
        Returns the request id."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if req.deadline is not None and req.deadline <= now:
                raise Backpressure("deadline already passed at submit",
                                   queue_depth=len(self._queue),
                                   retry_after_s=0.0)
            if len(self._queue) >= self.max_queue:
                victim = self._pick_shed_victim_locked(req.rank)
                if victim is None:
                    raise Backpressure(
                        f"queue full ({self.max_queue}); retry later",
                        queue_depth=len(self._queue),
                        retry_after_s=self._retry_after())
                # identity removal: dataclass == would compare the
                # numpy token arrays elementwise
                self._queue = [r for r in self._queue
                               if r is not victim]
                self._shed.append(victim)
            req.submitted_at = now
            self._queue.append(req)
            return req.req_id

    def _pick_shed_victim_locked(self, incoming_rank: int
                                 ) -> Optional[Request]:
        """Weakest class strictly below ``incoming_rank``'s priority,
        youngest arrival within it. A guaranteed request therefore
        never sheds another guaranteed one, and nothing sheds an
        equal-or-stronger class."""
        victim = None
        for r in self._queue:
            if r.rank <= incoming_rank:
                continue
            if (victim is None or r.rank > victim.rank
                    or (r.rank == victim.rank
                        and r.submitted_at > victim.submitted_at)):
                victim = r
        return victim

    def drain_shed(self) -> List[Request]:
        """Remove and return requests shed by `submit` since the last
        drain — the owner finishes them (evicted, reason shed)."""
        with self._lock:
            out, self._shed = self._shed, []
            return out

    def cancel(self, req_id: int) -> bool:
        """Remove a QUEUED request. Returns False if not queued (it may
        already be running — the engine owns cancellation there)."""
        with self._lock:
            for i, r in enumerate(self._queue):
                if r.req_id == req_id:
                    del self._queue[i]
                    return True
            return False

    def expire(self, now: Optional[float] = None) -> List[Request]:
        """Drop and return queued requests whose deadline has passed,
        ordered class-strongest-first then earliest-deadline-first
        within a class (the eviction observation order — metrics read
        causality off it)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            dead = [r for r in self._queue
                    if r.deadline is not None and r.deadline <= now]
            if dead:
                gone = {r.req_id for r in dead}
                self._queue = [r for r in self._queue
                               if r.req_id not in gone]
                dead.sort(key=lambda r: (r.rank, r.deadline))
            return dead

    def pop(self, n: int, prefer=None) -> List[Request]:
        """Up to ``n`` requests to admit: strongest QoS class first,
        the fifo/sjf policy within a class. Deadline expiry is the
        ENGINE's job (call `expire` first) so evictions are observed
        in one place.

        ``prefer`` (optional ``Request -> bool``) is a WITHIN-CLASS
        tiebreak ranked between the class and the policy: preferred
        requests dequeue first inside their QoS class, and the class
        lattice is never crossed (a preferred sheddable request still
        waits behind every guaranteed one). The engine's prefix-aware
        admission passes its radix-hit probe here when the pool is
        near capacity — a hit turns a slot over sooner."""
        with self._lock:
            if n <= 0 or not self._queue:
                return []

            def boost(i):
                if prefer is None:
                    return 0
                return 0 if prefer(self._queue[i]) else 1

            if self.policy == "sjf":
                def key(i):
                    return (self._queue[i].rank, boost(i),
                            self._queue[i].tokens.size, i)
            else:
                def key(i):
                    return (self._queue[i].rank, boost(i), i)
            order = sorted(range(len(self._queue)), key=key)
            take = order[:n]
            out = [self._queue[i] for i in take]
            taken = set(take)
            self._queue = [r for i, r in enumerate(self._queue)
                           if i not in taken]
            return out

    def snapshot(self) -> Sequence[int]:
        return [r.req_id for r in self._queue]
