"""`apex1_tpu.serving` — continuous-batching inference engine.

The serving layer the ROADMAP's "heavy traffic" north star needs on
top of the `models.generate` decode spine: a request scheduler with
backpressure and deadlines (`scheduler`), a fixed-slot KV pool with
refcounted shared-prefix pages (`kv_pool`), the two-executable
continuous-batching loop itself (`engine`), and per-request lifecycle
metrics (`metrics`). See ``docs/serving.md`` § Engine.

Quick start::

    from apex1_tpu.models.generate import llama_decoder
    from apex1_tpu.serving import Engine, EngineConfig

    engine = Engine(*llama_decoder(model), params,
                    EngineConfig(max_slots=8, max_len=512, eos_id=2))
    rid = engine.submit(prompt_ids, max_new_tokens=64)
    engine.run()
    print(engine.results[rid].tokens)
"""

from apex1_tpu.serving.engine import (Engine, EngineConfig,  # noqa: F401
                                      RequestResult)
from apex1_tpu.serving.kv_pool import KVPool, PrefixPage  # noqa: F401
from apex1_tpu.serving.metrics import (RequestRecord,  # noqa: F401
                                       ServingMetrics)
from apex1_tpu.serving.scheduler import (Backpressure,  # noqa: F401
                                         Request, Scheduler)
