"""`apex1_tpu.serving` — continuous-batching inference engine behind a
fault-tolerant multi-replica front.

The serving layer the ROADMAP's "heavy traffic" north star needs on
top of the `models.generate` decode spine: a request scheduler with
backpressure, deadlines, and per-tenant QoS classes (`scheduler`), a
fixed-slot KV pool with refcounted shared-prefix pages (`kv_pool`),
the two-executable continuous-batching loop itself (`engine`),
per-request lifecycle metrics with failure-path counters (`metrics`),
supervised replicas with watchdog + idempotent resubmission
(`replica`), the load/SLO-routed multi-replica frontend with
hedging and degraded modes (`frontend`), and the disaggregated
prefill/decode two-pool frontend with manifest-verified KV handoff
(`disagg`). See ``docs/serving.md`` § Engine, § Failure model, and
§ Disaggregated serving.

Quick start (single engine)::

    from apex1_tpu.models.generate import llama_decoder
    from apex1_tpu.serving import Engine, EngineConfig

    engine = Engine(*llama_decoder(model), params,
                    EngineConfig(max_slots=8, max_len=512, eos_id=2))
    rid = engine.submit(prompt_ids, max_new_tokens=64)
    engine.run()
    print(engine.results[rid].tokens)

Multi-replica front::

    from apex1_tpu.serving import FrontendConfig, ServingFrontend

    front = ServingFrontend(lambda: make_my_engine(),
                            FrontendConfig(n_replicas=2)).start()
    rid = front.submit(prompt_ids, max_new_tokens=64, qos="guaranteed")
    front.run_until_drained()
    print(front.poll(rid).tokens)
"""

from apex1_tpu.serving.engine import (Engine, EngineConfig,  # noqa: F401
                                      RequestResult,
                                      derive_request_seed)
from apex1_tpu.serving.frontend import (DegradeProfile,  # noqa: F401
                                        FrontendConfig,
                                        ServingFrontend)
from apex1_tpu.serving.kv_pool import (KVPool, PagedKVPool,  # noqa: F401
                                       PagedPrefix, PrefixPage,
                                       RadixIndex)
from apex1_tpu.serving.metrics import (RequestRecord,  # noqa: F401
                                       ServingMetrics)
from apex1_tpu.serving.spec import ngram_propose  # noqa: F401
from apex1_tpu.serving.replica import (PoisonedRequest,  # noqa: F401
                                       ReplicaConfig, ReplicaKilled,
                                       ReplicaSupervisor, Submission)
from apex1_tpu.serving.scheduler import (Backpressure,  # noqa: F401
                                         QOS_CLASSES, Request,
                                         Scheduler, new_request_id)
from apex1_tpu.serving.disagg import (DisaggConfig,  # noqa: F401,E402
                                      DisaggFrontend, HandoffError,
                                      KVPage)
