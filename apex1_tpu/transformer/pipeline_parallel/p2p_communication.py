"""P2P boundary communication — reference
``apex/transformer/pipeline_parallel/p2p_communication.py :: send_forward,
recv_forward, send_backward, recv_backward, _communicate``.

The reference batches NCCL isend/irecv pairs between adjacent PP stages with
shape prenegotiation. On TPU the equivalent primitive is a ring
``collective_permute`` over the pp mesh axis — these helpers exist for
porting parity and for tests; the scan-based schedules call ppermute
directly. Shape negotiation (``tensor_shape`` args) is unnecessary: shapes
are static under XLA.

PAIRING CONTRACT (differs from NCCL two-sided semantics — review r5): the
ONE ring permute in ``send_forward`` both sends and delivers, so after
``y = send_forward(x)`` the RETURN VALUE ``y`` is the received activation —
``recv_forward`` is an IDENTITY shim. The only supported paired form is
therefore the CHAINED one::

    x = recv_forward(send_forward(out))   # == send_forward(out)

A reference-style statement pair ``send_forward(out); x = recv_forward(out)``
is a SILENT NO-OP: it binds ``x`` to the unshifted local ``out`` while
``send_forward``'s returned permute is discarded dead code (XLA DCE's it —
no communication happens at all). Port such call sites to the chained form,
or better, to the fused names, which make the actual dataflow explicit.

Ring wraparound: stage 0's "received" value after ``send_forward`` is stage
P-1's output (a ring has no edge). The reference's ``recv_forward`` returns
``None`` at the first stage instead; under SPMD every device computes, so
callers mask stage 0's input themselves (the schedules inject the fresh
microbatch there — see ``schedules.pipeline_apply``'s stage-0 select), and
symmetrically stage P-1's input under ``send_backward``.
"""

from __future__ import annotations

import jax

from apex1_tpu.core.mesh import AXIS_PP


def _ring_perm(P, reverse=False):
    if reverse:
        return [(i, (i - 1) % P) for i in range(P)]
    return [(i, (i + 1) % P) for i in range(P)]


def send_forward_recv_forward(x, *, axis_name: str = AXIS_PP):
    """Send activation to the next stage; receive from the previous
    (one fused ring step — ≙ fused ``send_forward`` + ``recv_forward``)."""
    P = jax.lax.axis_size(axis_name)
    return jax.lax.ppermute(x, axis_name, perm=_ring_perm(P))


def send_backward_recv_backward(g, *, axis_name: str = AXIS_PP):
    """Send gradient to the previous stage; receive from the next."""
    P = jax.lax.axis_size(axis_name)
    return jax.lax.ppermute(g, axis_name, perm=_ring_perm(P, reverse=True))


# the permute lives in send_*; recv_* are identity shims so the
# reference's paired send-then-recv call pattern performs exactly ONE
# ring shift (see PAIRING CONTRACT above)
send_forward = send_forward_recv_forward
send_backward = send_backward_recv_backward


def recv_forward(x, *, axis_name: str = AXIS_PP):
    """Identity shim: pass it ``send_forward``'s RETURN VALUE
    (``x = recv_forward(send_forward(out))``). Called standalone on a
    local value it is a no-op that silently drops the communication —
    see PAIRING CONTRACT in the module docstring."""
    del axis_name
    return x


def recv_backward(g, *, axis_name: str = AXIS_PP):
    """Identity shim: pass it ``send_backward``'s RETURN VALUE
    (``g = recv_backward(send_backward(out))``). Called standalone on a
    local value it is a no-op that silently drops the communication —
    see PAIRING CONTRACT in the module docstring."""
    del axis_name
    return g
