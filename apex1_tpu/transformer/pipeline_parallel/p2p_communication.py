"""P2P boundary communication — reference
``apex/transformer/pipeline_parallel/p2p_communication.py :: send_forward,
recv_forward, send_backward, recv_backward, _communicate``.

The reference batches NCCL isend/irecv pairs between adjacent PP stages with
shape prenegotiation. On TPU the equivalent primitive is a ring
``collective_permute`` over the pp mesh axis — these helpers exist for
porting parity and for tests; the scan-based schedules call ppermute
directly. Shape negotiation (``tensor_shape`` args) is unnecessary: shapes
are static under XLA.
"""

from __future__ import annotations

import jax

from apex1_tpu.core.mesh import AXIS_PP


def _ring_perm(P, reverse=False):
    if reverse:
        return [(i, (i - 1) % P) for i in range(P)]
    return [(i, (i + 1) % P) for i in range(P)]


def send_forward_recv_forward(x, *, axis_name: str = AXIS_PP):
    """Send activation to the next stage; receive from the previous
    (one fused ring step — ≙ fused ``send_forward`` + ``recv_forward``)."""
    P = jax.lax.axis_size(axis_name)
    return jax.lax.ppermute(x, axis_name, perm=_ring_perm(P))


def send_backward_recv_backward(g, *, axis_name: str = AXIS_PP):
    """Send gradient to the previous stage; receive from the next."""
    P = jax.lax.axis_size(axis_name)
    return jax.lax.ppermute(g, axis_name, perm=_ring_perm(P, reverse=True))


# single-direction names for API parity; on a ring each is the same permute
send_forward = send_forward_recv_forward
recv_forward = send_forward_recv_forward
send_backward = send_backward_recv_backward
recv_backward = send_backward_recv_backward
