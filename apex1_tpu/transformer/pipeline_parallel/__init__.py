"""Pipeline parallelism — reference ``apex/transformer/pipeline_parallel``."""

from apex1_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    allreduce_embedding_grads,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    one_f_one_b,
    pipeline_apply,
    pipeline_tied_apply,
    pipelined_loss_fn,
)
from apex1_tpu.transformer.pipeline_parallel import (  # noqa: F401
    p2p_communication)
