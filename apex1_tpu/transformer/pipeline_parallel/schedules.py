"""Pipeline-parallel schedules — reference
``apex/transformer/pipeline_parallel/schedules/*``:
``fwd_bwd_no_pipelining``, ``forward_backward_pipelining_without_interleaving``
(1F1B), ``fwd_bwd_pipelining_with_interleaving`` (virtual pipeline), selected
by ``get_forward_backward_func()``.

The reference schedules are host-side Python loops issuing NCCL p2p
send/recv per microbatch (§3.4 call stack: warmup `p - rank - 1` fwds,
steady 1F1B, cooldown). Under XLA the schedule must be a compiled program:
here the pipeline is ONE ``lax.scan`` over ticks inside ``shard_map`` over
the ``pp`` axis, with a ring ``ppermute`` moving boundary activations each
tick. ``jax.grad`` through the scan gives the backward pass — the transpose
of ``ppermute`` is the reverse-direction ``ppermute``, so the backward
program is the mirrored pipeline the reference hand-codes.

Schedule math:
- V = 1 (non-interleaved): microbatch m occupies stage s at tick t = m + s;
  total ticks M + P − 1 — the same fill/steady/drain structure as 1F1B
  (identical bubble: P−1; 1F1B vs GPipe differ only in *activation memory*,
  which `jax.checkpoint` on the stage function controls here).
- V > 1 (interleaved/circular ≙ virtual pipeline): each stage owns V model
  chunks (chunk c = v·P + s lives on stage s). Microbatch m enters chunk v
  at tick t = v·M + m + s; the ring permute routes stage P−1 → stage 0 for
  free (chunk boundary), with a stage-0 FIFO holding recirculated
  activations for M−P+1 ticks. Requires M ≥ P (the reference's interleaved
  schedule asserts microbatches % pp == 0 similarly). Total ticks
  V·M + P − 1 — bubble still P−1, matching interleaved 1F1B's bubble
  shrink vs running V·M microbatches through a V·P-deep pipe.

Bubble ticks (fraction (P−1)/(VM+P−1)) SKIP the stage compute via a
per-tick ``lax.cond`` — like 1F1B, the schedule does no redundant work;
bubble ranks idle through the tick and forward zeros to the ring permute
(quantified via XLA cost analysis: tools/pipeline_cost.py, docs/parallel.md
"Pipeline cost model" — whether lax.cond actually elides the branch on real
TPU hardware is still unmeasured; tools/cond_elision_probe.py is queued).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from apex1_tpu.core.mesh import AXIS_PP
from apex1_tpu.transformer import parallel_state


def _tree_select_chunk(stacked, v):
    """Select chunk v from leaves shaped (V, ...)."""
    return jax.tree_util.tree_map(
        lambda p: jax.lax.dynamic_index_in_dim(p, v, axis=0,
                                               keepdims=False), stacked)


def pipeline_apply(
    stage_fn: Callable,
    chunk_params,
    microbatches,
    *,
    num_chunks: int = 1,
    axis_name: str = AXIS_PP,
    broadcast_outputs: bool = True,
    remat_stage: bool = False,
    scan_unroll: int | bool = 1,
    skip_bubbles: bool = True,
    with_aux: bool = False,
    boundary_shape: tuple[int, ...] | None = None,
    boundary_dtype=None,
):
    """Run the pipelined forward. MUST be called inside ``shard_map`` over
    ``axis_name``.

    ``remat_stage=True`` wraps ``stage_fn`` in ``jax.checkpoint``: the
    backward scan then recomputes each tick's stage activations instead
    of storing them, bounding per-stage activation memory at O(1 tick) +
    boundary carries — the memory property the reference's 1F1B schedule
    achieves by interleaving backward steps (``deallocate_output_tensor``,
    warmup ``p − rank − 1``). Measured numbers: docs/parallel.md
    ("Pipeline cost model").

    - ``stage_fn(params_chunk, x) -> y``: one pipeline-chunk forward; input
      and output must have identical shape/dtype (boundary activation).
    - ``chunk_params``: pytree with leading axis V (chunks per stage) on
      every leaf — the local stage's chunk parameters. For V=1 pass leaves
      shaped (1, ...).
    - ``microbatches``: (M, ...) tensor of microbatch inputs, replicated
      across the pp axis (only stage 0 consumes; ≙ the reference reading
      the batch on the first stage).

    GRAD CONVENTIONS (pick by how you differentiate):

    - ``broadcast_outputs=True`` (default): returns (M, ...) outputs of the
      LAST chunk on every rank (masked psum broadcast). Correct when the
      loss is differentiated OUTSIDE the ``shard_map`` (``jax.grad`` of the
      shard_mapped callable) — shard_map's transpose accounts for the
      replication.
    - ``broadcast_outputs=False``: returns the PARTIAL outputs — real
      values on the last stage, zeros elsewhere; their sum over the pp
      axis is the broadcast value. REQUIRED when ``jax.grad`` runs INSIDE
      the shard_map (a whole train step in one shard_map): JAX transposes
      ``psum`` to ``psum``, and with every rank seeding the same replicated
      loss the broadcast form scales every gradient by P. Under the partial
      convention, compute per-rank partial losses (mask with the last-stage
      indicator), take grads, then ``psum`` the loss VALUE for logging;
      grads of pp-replicated leaves (tied embeddings, shared heads) combine
      with :func:`allreduce_embedding_grads`.

    ``skip_bubbles`` (default True) elides bubble-tick stage compute with
    a per-tick ``lax.cond``. CONTRACT: ``stage_fn`` must NOT contain
    ``lax.ppermute`` (ring attention, halo exchange). XLA lowers ppermute
    to ONE collective-permute whose rendezvous spans every device in the
    mesh, so ranks that skip a tick desynchronize the pairing across ticks
    and the data lands in the wrong tick (observed empirically; loss moves
    by ~2e-3 rel on a pp2×cp2 ring-attention step). Group-scoped
    collectives (``psum``/``all_gather``/``reduce_scatter``/
    ``all_to_all``) rendezvous per replica-group and are verified safe
    (mask-vs-skip exact match on a pp2×cp2 mesh for each class). Pass
    ``skip_bubbles=False`` for ppermute-bearing stages — bubble ticks then
    run ``stage_fn`` on zeros and mask the result (wall-time equivalent to
    the reference's idle bubble; the skip saves power/FLOPs, not
    critical-path latency).

    ``with_aux=True``: ``stage_fn`` returns ``(y, aux)`` with ``aux`` a
    scalar side loss (e.g. the MoE router's load-balance term). The
    pipeline sums aux over this rank's VALID ticks only and returns
    ``(outputs, aux_sum)`` — per-rank partials over the pp axis (each
    stage's layers contribute exactly once), so under the partial-loss
    convention adding ``aux_sum`` to the rank's partial loss and psumming
    over pp yields the whole model's aux term.

    VARIABLE BOUNDARY SHAPES (≙ the reference's ``decoder_seq_length`` /
    ``_communicate`` shape negotiation, SURVEY #56): the reference's
    host-driven p2p can send a different tensor shape between each stage
    pair; a compiled SPMD scan cannot — every tick's ppermute carries ONE
    static buffer. The mesh-native equivalent is PAD-TO-MAX: pass
    ``boundary_shape`` (>= the microbatch trailing shape, elementwise) and
    ``boundary_dtype``; stage-0 injections are zero-padded into that
    buffer, ``stage_fn`` maps boundary-shaped x to boundary-shaped y
    (masking per ``lax.axis_index`` where its real extent is narrower —
    e.g. a T5 decoder stage using only the first ``decoder_seq_length``
    rows), and outputs come back boundary-shaped for the caller to slice.
    Zero-region garbage is dead by construction: it receives zero
    cotangents (outputs sliced/masked) and bubble ticks never read it.
    Parity-tested in ``test_pipeline.py::TestVariableBoundary``.
    """
    if remat_stage:
        stage_fn = jax.checkpoint(stage_fn)
    P = jax.lax.axis_size(axis_name)
    s = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    V = num_chunks
    if V > 1 and M < P:
        raise ValueError(
            f"interleaved pipeline requires num_microbatches ({M}) >= "
            f"pipeline size ({P})")
    T = V * M + P - 1

    x_shape = boundary_shape or microbatches.shape[1:]
    dtype = boundary_dtype or microbatches.dtype
    if len(x_shape) != microbatches.ndim - 1 or any(
            b < m for b, m in zip(x_shape, microbatches.shape[1:])):
        raise ValueError(
            f"boundary_shape {x_shape} must have the microbatch rank and "
            f"cover the microbatch shape {microbatches.shape[1:]}")
    if tuple(x_shape) != microbatches.shape[1:]:
        # pad-to-max once up front (XLA fuses the pad; the scan then
        # carries the uniform boundary buffer)
        pads = [(0, 0)] + [(0, b - m) for b, m in
                           zip(x_shape, microbatches.shape[1:])]
        microbatches = jnp.pad(microbatches.astype(dtype), pads)
    else:
        microbatches = microbatches.astype(dtype)
    zeros_x = jnp.zeros(x_shape, dtype)

    if skip_bubbles:
        _check_skippable(
            stage_fn,
            (jax.tree_util.tree_map(lambda p: p[0], chunk_params), zeros_x),
            flag_name="skip_bubbles", caller="pipeline_apply")

    def tick(carry, t):
        x_recv, fifo, outs, aux_acc = carry
        # stage-0 FIFO: record the activation that arrived this tick
        # (sent by stage P-1 at tick t-1, i.e. chunk-output of slot t-P)
        m_arr = jnp.mod(t - P, M)
        arrival_ok = (s == 0) & (t >= P) & (V > 1)
        fifo = jnp.where(arrival_ok,
                         jax.lax.dynamic_update_index_in_dim(
                             fifo, x_recv, m_arr, axis=0),
                         fifo)

        u = t - s                       # local slot
        v = jnp.clip(u // M, 0, V - 1)  # chunk index
        m = jnp.mod(u, M)               # microbatch index
        valid = (u >= 0) & (u < V * M)

        # stage-0 input: fresh microbatch for chunk 0, recirculated otherwise
        fresh = jax.lax.dynamic_index_in_dim(microbatches, m, axis=0,
                                             keepdims=False)
        recirc = jax.lax.dynamic_index_in_dim(fifo, m, axis=0,
                                              keepdims=False)
        x0 = jnp.where(v == 0, fresh, recirc)
        x = jnp.where(s == 0, x0, x_recv)

        params_v = _tree_select_chunk(chunk_params, v)
        # Bubble ticks (fill/drain, fraction (P−1)/(VM+P−1)) carry no real
        # microbatch: skip the stage compute entirely with a per-tick
        # `lax.cond` (the `ring_attention` causal-skip pattern) instead of
        # running `stage_fn` on zeros and masking — 1F1B does no redundant
        # compute (SURVEY #55) and neither should the scan schedule. The
        # predicate is uniform within a pp rank (and across its tp/cp/ep
        # subgroups), so group-scoped collectives (psum / all_gather /
        # reduce_scatter / all_to_all) inside `stage_fn` are safe: peers
        # share (s, t), take the same branch, and each replica_group
        # rendezvouses independently (verified mask-vs-skip exact-match,
        # tools/pipeline_cost.py repro). ``ppermute`` is NOT safe — see
        # the ``skip_bubbles`` contract in the docstring.
        # (``skip_bubbles=False`` keeps the old mask-only path — the A/B
        # lever tools/pipeline_cost.py times, since static cost_analysis
        # prices a conditional's branches whether or not they execute.)
        zero_aux = jnp.zeros([], jnp.float32)

        def run(ops):
            out = stage_fn(*ops)
            return out if with_aux else (out, zero_aux)

        if skip_bubbles:
            y, aux = jax.lax.cond(valid, run,
                                  lambda ops: (zeros_x, zero_aux),
                                  (params_v, x))
        else:
            y, aux = run((params_v, x))
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)

        out_ok = valid & (s == P - 1) & (v == V - 1)
        outs = jnp.where(out_ok,
                         jax.lax.dynamic_update_index_in_dim(
                             outs, y, m, axis=0),
                         outs)

        y_send = jax.lax.ppermute(
            y, axis_name, perm=[(i, (i + 1) % P) for i in range(P)])
        return (y_send, fifo, outs, aux_acc), None

    init = (zeros_x,
            jnp.zeros((M,) + x_shape, dtype),
            jnp.zeros((M,) + x_shape, dtype),
            jnp.zeros([], jnp.float32))
    # scan_unroll > 1 lets XLA software-pipeline the tick loop (overlap a
    # tick's ppermute with the next tick's compute); True also makes every
    # tick visible to cost_analysis (tools/pipeline_cost.py)
    (x_recv, fifo, outs, aux_sum), _ = jax.lax.scan(
        tick, init, jnp.arange(T), unroll=scan_unroll)

    if not broadcast_outputs:
        # accumulated on the last stage only; zeros elsewhere
        return (outs, aux_sum) if with_aux else outs
    # replicate last-stage outputs (transpose: cotangent flows to stage P-1)
    is_last = (s == P - 1).astype(outs.dtype)
    bcast = jax.lax.psum(outs * is_last, axis_name)
    return (bcast, aux_sum) if with_aux else bcast


# ---------------------------------------------------------------------------
# tied-embedding pipeline (embedding group)
# ---------------------------------------------------------------------------

def pipeline_tied_apply(
    stage_fn: Callable,
    chunk_params,
    embed_fn: Callable,
    head_fn: Callable,
    tied_params,
    tokens_mb,
    *,
    num_chunks: int = 1,
    axis_name: str = AXIS_PP,
    broadcast_outputs: bool = True,
    **pipeline_kwargs,
):
    """Pipeline with a TIED input-embedding / LM-head weight — reference
    ``parallel_state.initialize_model_parallel``'s embedding group ({first,
    last} PP stages) plus the post-step embedding-grad all-reduce the
    schedules issue (§3.4 "embedding-grad all-reduce across embedding
    group").

    ``tied_params`` (the shared vocab-embedding tree) is REPLICATED across
    the pp axis — the mesh-native form of "a copy lives on the first and
    last stage". ``embed_fn(tied_params, tokens) -> (..., D)`` feeds the
    pipeline; its cotangent is masked to stage 0 by ``pipeline_apply``'s
    stage-0 input select, so only the first stage's copy accumulates the
    input-embedding grad. ``head_fn(tied_params, outs) -> z`` is applied to
    the last-chunk outputs, masked to the last stage, so its cotangent
    lands on stage P−1 only.

    Grad conventions (see :func:`pipeline_apply`):

    - ``broadcast_outputs=True``: ``z`` is psum-broadcast; differentiate
      OUTSIDE the shard_map — shard_map's replicated-input transpose then
      IS the embedding-group all-reduce (tied grads arrive combined).
    - ``broadcast_outputs=False``: ``z`` is the per-rank PARTIAL (zeros off
      the last stage; psum the value for logging). For ``jax.grad`` INSIDE
      the shard_map; combine the tied grads with
      :func:`allreduce_embedding_grads` — a psum over pp in which middle
      stages contribute zeros, exactly the reference's embedding-group
      all-reduce.
    """
    P = jax.lax.axis_size(axis_name)
    s = jax.lax.axis_index(axis_name)
    h_mb = jax.vmap(lambda t: embed_fn(tied_params, t))(tokens_mb)
    outs = pipeline_apply(stage_fn, chunk_params, h_mb,
                          num_chunks=num_chunks, axis_name=axis_name,
                          broadcast_outputs=False, **pipeline_kwargs)
    z = head_fn(tied_params, outs)
    last = s == P - 1
    z = jax.tree_util.tree_map(lambda a: a * last.astype(a.dtype), z)
    if not broadcast_outputs:
        return z
    return jax.tree_util.tree_map(
        lambda a: jax.lax.psum(a, axis_name), z)


def allreduce_embedding_grads(tied_grads, axis_name: str = AXIS_PP):
    """≙ the reference's embedding-grad all-reduce over the embedding group
    after the pipeline step: sums the first-stage (input embedding) and
    last-stage (LM head) contributions; middle stages contribute zeros."""
    return jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g, axis_name), tied_grads)


# ---------------------------------------------------------------------------
# true 1F1B: staggered forward/backward in ONE scan, VJP residual ring
# ---------------------------------------------------------------------------

def _x_dependent_mask(fn, *args, arg_index):
    """Trace-time reachability: which flat outputs of ``fn(*args)`` depend
    on ``args[arg_index]``? Conservative over sub-jaxprs (an equation with
    any tainted input taints every output). Used to split VJP residuals
    into activations (ring-buffered) vs parameter-only values (recomputed
    for free at the backward tick — computing them needs no x)."""
    from jax.extend.core import Literal

    closed = jax.make_jaxpr(fn)(*args)
    flat_per_arg = [len(jax.tree_util.tree_leaves(a)) for a in args]
    lo = sum(flat_per_arg[:arg_index])
    hi = lo + flat_per_arg[arg_index]
    tainted = set(closed.jaxpr.invars[lo:hi])
    for eqn in closed.jaxpr.eqns:
        if any(not isinstance(v, Literal) and v in tainted
               for v in eqn.invars):
            tainted.update(eqn.outvars)
    return [not isinstance(v, Literal) and v in tainted
            for v in closed.jaxpr.outvars]


def _jaxpr_has_ppermute(closed) -> bool:
    """Recursively scan a (Closed)Jaxpr — including sub-jaxprs carried in
    equation params (cond/scan/pjit/remat/custom_vjp…) — for a ppermute
    equation."""
    from jax.extend.core import ClosedJaxpr, Jaxpr

    def subs(val):
        if isinstance(val, ClosedJaxpr):
            yield val.jaxpr
        elif isinstance(val, Jaxpr):
            yield val
        elif isinstance(val, (list, tuple)):
            for v in val:
                yield from subs(v)
        elif isinstance(val, dict):
            for v in val.values():
                yield from subs(v)

    stack = [closed.jaxpr if hasattr(closed, "jaxpr") else closed]
    seen = set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            if eqn.primitive.name == "ppermute":
                return True
            for val in eqn.params.values():
                stack.extend(subs(val))
    return False


def _check_skippable(stage_fn, example_args, *, flag_name, caller):
    """Enforce the bubble-skip collective contract AT TRACE TIME
    (VERDICT r3 Weak #3): a ``lax.ppermute`` inside ``stage_fn`` under
    the skip path desynchronizes the mesh-wide rendezvous pairing across
    ticks and SILENTLY corrupts the result (~2e-3 rel loss shift observed
    on a pp2×cp2 ring-attention step) — group-scoped collectives
    (psum/all_gather/reduce_scatter/all_to_all) rendezvous per
    replica-group and are safe. The contract used to live only in the
    docstring; scanning the stage jaxpr makes the landmine impossible to
    step on. Raises ValueError on detection.

    The scan is best-effort: if the extra abstract trace of ``stage_fn``
    itself fails (it runs outside the cond/scan machinery, so exotic
    stage functions could trace differently), the contract check is
    skipped rather than rejecting a program that would have compiled."""
    try:
        closed = jax.make_jaxpr(stage_fn)(*example_args)
    except Exception:
        return
    if _jaxpr_has_ppermute(closed):
        raise ValueError(
            f"{caller}: stage_fn contains lax.ppermute (ring attention / "
            f"halo exchange), which is NOT safe under {flag_name}=True — "
            f"skipped ticks desynchronize ppermute's mesh-wide rendezvous "
            f"pairing and corrupt results silently. Pass {flag_name}="
            f"False for ppermute-bearing stages (bubble ticks then run on "
            f"zeros and mask — wall-time equivalent, the skip only saves "
            f"FLOPs/power).")


def one_f_one_b(
    stage_fn: Callable,
    stage_params,
    microbatches,
    loss_mb: Callable,
    *,
    axis_name: str = AXIS_PP,
    num_chunks: int = 1,
    skip_idle: bool = True,
    scan_unroll: int | bool = 1,
    loss_params=None,
    with_aux: bool = False,
    aux_cotangent=None,
):
    """TRUE 1F1B (reference
    ``forward_backward_pipelining_without_interleaving`` and, with
    ``num_chunks`` V>1, ``..._with_interleaving``): each stage
    interleaves one microbatch's backward between forwards, so the live
    activation count is bounded by the schedule (O(P) for V=1, O(V·P)
    interleaved) independent of M — the schedule's defining memory
    property — WITHOUT the recompute that
    ``pipeline_apply(remat_stage=True)`` + ``jax.grad`` pays.

    Clocking, V=1 (tick ``t`` of ``T = 2(M+P−1)``): stage ``s`` runs fwd
    of microbatch ``m`` at ``t = 2m + s`` and bwd of ``m`` at
    ``t = 2m + 2P−1−s``. Fwd and bwd ticks of one stage have opposite
    parity (never collide); boundary activations ride a forward ring
    ppermute one tick after production, cotangents a reverse ring one
    tick after consumption — the compiled-SPMD form of the reference's
    warmup/steady-1F1B/cooldown send-recv loop. Residual lifetime is
    ``2P−1−2s`` ticks, so a depth-``P`` ring (slot ``m mod P``) suffices.

    Clocking, V>1 (Megatron's interleaved order: groups of P
    microbatches cycle through all V chunks before the next group —
    requires ``M % P == 0``, the reference's ``microbatches % pp == 0``
    assertion, and P ≥ 2): with ``m = g·P + r``, stage ``s`` runs fwd of
    (g, v, r) at ``t = 2(g·V·P + v·P + r) + s`` and bwd at
    ``t = D + 2(g·V·P + (V−1−v)·P + r) + (2P−1−s)`` with fill delay
    ``D = (V−1)·2P`` (even → the fwd/bwd parity split is preserved; at
    V=1 every formula reduces to the non-interleaved clocking). Chunk
    hand-off recirculates through depth-P FIFOs on both rings: stage
    P−1's chunk-v output arrives at stage 0 P ticks before chunk v+1
    consumes it, and stage 0's chunk-(v+1) cotangent arrives at stage
    P−1 P ticks before chunk v's backward seeds from it. In steady
    state every stage does useful work every tick (all even slots fwd,
    all odd slots bwd — zero idle), total ticks
    ``T = D + 2·V·M + 2P − 2``.

    The ring stores ONLY the x-dependent VJP residual leaves (the
    per-layer activations Megatron keeps between fwd and bwd);
    parameter-only residuals (weights, their casts) are recomputed at
    the bwd tick from a zeros-input VJP trace whose x-dependent half is
    dead code. Ring capacity is sized from the worst-case residual
    lifetime — ``G_live`` groups of V·P slots where ``G_live =
    lifetime_max // (2·V·P) + 1`` (1 group at V=1 → the P-slot ring
    above; 2 at V≥2) — so ring memory is O(V·P) activations, never
    O(V·M). Executed stage work with ``skip_idle``: exactly ``2·V·M``
    per stage vs ``3·V·M`` for the remat path. The ``skip_bubbles``
    collective contract (ppermute-free stages) applies to ``skip_idle``
    — for the stage AND its transpose (psum/all_gather/reduce_scatter/
    all_to_all transpose within the class; ppermute does not).

    MUST be called inside ``shard_map`` over ``axis_name``.

    - ``stage_fn(stage_params, x) -> y`` — ONE chunk's forward; boundary
      in = boundary out (shape/dtype), as in :func:`pipeline_apply`.
      With ``num_chunks`` V>1, ``stage_params`` leaves carry a leading
      (V, ...) chunk axis (chunk c = v·P + s lives on stage s, as in
      :func:`pipeline_apply`) and the returned ``grads`` keep it.
    - ``loss_mb(y, m) -> scalar`` — microbatch ``m``'s loss, evaluated
      on the LAST stage right after its LAST-chunk forward; its grad
      seeds that microbatch's backward (≙ the reference's ``loss_func``
      + ``backward_step`` seed). The objective is the SUM over
      microbatches — fold any 1/M inside ``loss_mb``.

    ``loss_params`` (optional): a pytree of parameters the loss itself
    uses (an LM head, a final norm — what the reference runs as the
    last stage's ``post_process``). The signature becomes
    ``loss_mb(loss_params, y, m)`` and the return gains
    ``dloss_params`` — fp32 grads accumulated over the last stage's
    forward ticks (zeros on other ranks; psum over pp combines, exactly
    the embedding-group convention).

    ``with_aux=True``: ``stage_fn`` returns ``(y, aux)`` with ``aux`` a
    scalar side objective (MoE router balance). Each backward tick
    seeds the stage VJP with cotangent ``(dy, aux_cotangent)`` — pass
    the constant (traced scalars fine: fold the loss scale and any
    replication correction in; see the llama_3d seed-multiplicity note)
    — and the return gains ``aux_sum``: this rank's sum of aux VALUES
    over its valid forward ticks (per-rank partial over pp, unscaled by
    ``aux_cotangent``; weight it into the logged loss yourself).

    Returns ``(loss_sum, grads, dmicrobatches[, dloss_params]
    [, aux_sum])``, per-rank PARTIALS: ``loss_sum`` is real on the last
    stage (zeros elsewhere — psum over pp for the value), ``grads``
    (fp32, ``stage_params``-shaped) is this stage's accumulated
    parameter gradient, and ``dmicrobatches`` (M, ...) is the
    per-microbatch input cotangent, real on stage 0 — feed it to the
    embedding's VJP to finish the model backward.
    """
    P = jax.lax.axis_size(axis_name)
    s = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    V = num_chunks
    if V > 1:
        if M % P:
            raise ValueError(
                f"interleaved 1F1B requires num_microbatches ({M}) % "
                f"pipeline size ({P}) == 0 (the reference's "
                f"microbatches %% pp assertion)")
        if P < 2:
            raise ValueError("interleaved 1F1B needs pipeline size >= 2")
        chunk_params = stage_params
    else:
        # lift to one chunk so V=1 and V>1 share the machinery
        chunk_params = jax.tree_util.tree_map(lambda p: p[None],
                                              stage_params)
    D_ = (V - 1) * 2 * P
    VP = V * P
    T = D_ + 2 * V * M + 2 * P - 2
    # residual-ring capacity: worst-case lifetime (v=0 residual at s=0)
    # over the slot-reuse interval 2·V·P (same (v, r), next group)
    lifetime_max = D_ + (V - 1) * 2 * P + 2 * P - 1
    G_live = lifetime_max // (2 * VP) + 1
    R = G_live * VP
    x_shape = microbatches.shape[1:]
    dtype = microbatches.dtype
    zeros_x = jnp.zeros(x_shape, dtype)
    is_last = s == P - 1
    zero_aux = jnp.zeros([], jnp.float32)
    if with_aux and aux_cotangent is None:
        raise ValueError(
            "with_aux=True requires aux_cotangent — a zero default would "
            "silently drop the aux objective from every gradient")
    daux = (jnp.asarray(aux_cotangent, jnp.float32) if with_aux
            else zero_aux)

    def stage_pair(p, x):
        # uniform (y, aux) shape so the VJP/residual machinery below is
        # one code path; the dummy aux of a plain stage is a constant
        # whose cotangent (daux = 0) contributes nothing
        out = stage_fn(p, x)
        y, aux = out if with_aux else (out, zero_aux)
        return y, aux.astype(jnp.float32)

    def _loss(lp, yy, m):
        lm = (loss_mb(yy, m) if loss_params is None
              else loss_mb(lp, yy, m))
        return lm.astype(jnp.float32)

    zeros_lp = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), loss_params)

    def _vjp_leaves(p, x):
        return jax.tree_util.tree_leaves(jax.vjp(stage_pair, p, x)[1])

    # trace-time constants: residual treedef, leaf shapes, x-dependence
    # (chunk-independent — every chunk shares stage_fn and shapes)
    params0 = jax.tree_util.tree_map(lambda p: p[0], chunk_params)
    if skip_idle:
        # fwd/bwd ticks run under per-tick lax.cond: the ppermute-free
        # contract covers the stage AND the cond-gated loss head
        _check_skippable(stage_pair, (params0, zeros_x),
                         flag_name="skip_idle", caller="one_f_one_b")
        _check_skippable(
            _loss, (loss_params, zeros_x, jnp.zeros([], jnp.int32)),
            flag_name="skip_idle", caller="one_f_one_b (loss_mb)")
    _, _vjp0 = jax.vjp(stage_pair, params0, zeros_x)  # arrays DCE'd
    res_treedef = jax.tree_util.tree_structure(_vjp0)
    res_sds = jax.eval_shape(_vjp_leaves, params0, zeros_x)
    xdep = _x_dependent_mask(_vjp_leaves, params0, zeros_x,
                             arg_index=1)
    ring0 = [jnp.zeros((R,) + sd.shape, sd.dtype)
             for sd, d in zip(res_sds, xdep) if d]

    fwd_perm = [(i, (i + 1) % P) for i in range(P)]
    bwd_perm = [(i, (i - 1) % P) for i in range(P)]

    def _decomp(uu):
        """uu = g·V·P + v·P + r -> (g, v, r, m)."""
        g = uu // VP
        rem = jnp.mod(uu, VP)
        v = rem // P
        r = jnp.mod(rem, P)
        return g, v, r, g * P + r

    def tick(carry, t):
        (x_recv, dy_recv, ring, dy_ring, fwd_fifo, dy_fifo, gacc, lacc,
         dmb, lpacc, aux_acc) = carry

        # ---- chunk-recirculation FIFO writes (statically elided at
        # V=1, where the FIFO carries are empty tuples) ----
        if V > 1:
            # fwd arrival at stage 0: chunk-v output of (g, v, r) sent
            # by stage P-1 at t-1 -> (t - P)/2 = g·VP + v·P + r
            w1 = t - P
            g1, v1, r1, _ = _decomp(w1 // 2)
            arr1 = ((w1 >= 0) & (w1 % 2 == 0) & (w1 // 2 < V * M)
                    & (v1 <= V - 2) & (s == 0))
            fwd_fifo = jnp.where(
                arr1,
                jax.lax.dynamic_update_index_in_dim(fwd_fifo, x_recv,
                                                    r1, axis=0),
                fwd_fifo)
            # bwd arrival at stage P-1: chunk-(v+1) input-cotangent of
            # (g, r) sent by stage 0 at t-1 -> (t - D - 2P)/2 decomposes
            # with vv = V-1-v_producer
            w2 = t - D_ - 2 * P
            g2, vv2, r2, _ = _decomp(w2 // 2)
            arr2 = ((w2 >= 0) & (w2 % 2 == 0) & (w2 // 2 < V * M)
                    & (vv2 <= V - 2) & is_last)
            dy_fifo = jnp.where(
                arr2,
                jax.lax.dynamic_update_index_in_dim(dy_fifo, dy_recv,
                                                    r2, axis=0),
                dy_fifo)

        # ---- forward subtick: fwd(g, v, r) at t = 2(g·VP+v·P+r)+s ----
        u = t - s
        uu = jnp.clip(u // 2, 0, V * M - 1)
        g_f, v_f, r_f, m_f = _decomp(uu)
        valid_f = (u >= 0) & (u % 2 == 0) & (u // 2 < V * M)
        fresh = jax.lax.dynamic_index_in_dim(microbatches, m_f, axis=0,
                                             keepdims=False)
        if V > 1:
            recirc = jax.lax.dynamic_index_in_dim(fwd_fifo, r_f, axis=0,
                                                  keepdims=False)
            x0 = jnp.where(v_f == 0, fresh, recirc)
        else:
            x0 = fresh
        x_in = jnp.where(s == 0, x0, x_recv)
        params_f = _tree_select_chunk(chunk_params, v_f)
        # the loss attaches only to the LAST chunk's output on the last
        # stage — gate its (head-projection-sized) value_and_grad under
        # a cond instead of computing-and-masking it on every rank and
        # chunk (predicate uniform across each pp rank's tp/dp/ep/cp
        # peers, so loss_mb's group-scoped collectives stay safe — the
        # skip_bubbles contract)
        pred_loss = is_last & (v_f == V - 1)

        def run_fwd(ops):
            p_f, x_in = ops
            (y, aux), vjp_fn = jax.vjp(stage_pair, p_f, x_in)
            leaves = jax.tree_util.tree_leaves(vjp_fn)
            dep = [lf for lf, d in zip(leaves, xdep) if d]

            def with_loss(y):
                lm, (dlp, dy_self) = jax.value_and_grad(
                    _loss, argnums=(0, 1))(loss_params, y, m_f)
                return (lm,
                        jax.tree_util.tree_map(
                            lambda g: g.astype(jnp.float32), dlp),
                        dy_self.astype(dtype))

            def no_loss(y):
                return jnp.zeros([], jnp.float32), zeros_lp, zeros_x

            lm, dlp, dy_self = jax.lax.cond(pred_loss, with_loss,
                                            no_loss, y)
            return y, aux, dep, lm, dy_self, dlp

        def zero_fwd(ops):
            return (zeros_x, zero_aux,
                    [jnp.zeros(sd.shape, sd.dtype)
                     for sd, d in zip(res_sds, xdep) if d],
                    jnp.zeros([], jnp.float32), zeros_x, zeros_lp)

        if skip_idle:
            y, aux, dep, lm, dy_self, dlp = jax.lax.cond(
                valid_f, run_fwd, zero_fwd, (params_f, x_in))
        else:
            y, aux, dep, lm, dy_self, dlp = run_fwd((params_f, x_in))
            y = jnp.where(valid_f, y, zeros_x)
        aux_acc = aux_acc + jnp.where(valid_f, aux, 0.0)
        # the loss attaches to the LAST chunk's output on the last stage
        out_f = valid_f & is_last & (v_f == V - 1)
        lpacc = jax.tree_util.tree_map(
            lambda a, g: a + jnp.where(out_f, g, 0.0), lpacc, dlp)

        slot_f = (jnp.mod(g_f, G_live) * VP + v_f * P + r_f)
        ring = [jnp.where(valid_f,
                          jax.lax.dynamic_update_index_in_dim(
                              buf, lf, slot_f, axis=0),
                          buf)
                for buf, lf in zip(ring, dep)]
        dy_ring = jnp.where(
            out_f,
            jax.lax.dynamic_update_index_in_dim(dy_ring, dy_self, r_f,
                                                axis=0),
            dy_ring)
        lacc = lacc + jnp.where(out_f, lm, 0.0)

        # ---- backward subtick: bwd(g, v, r) at
        #      t = D + 2(g·VP + (V−1−v)·P + r) + 2P−1−s ----
        w = t - D_ - (2 * P - 1 - s)
        ww = jnp.clip(w // 2, 0, V * M - 1)
        g_b, vv_b, r_b, m_b = _decomp(ww)
        v_b = V - 1 - vv_b
        valid_b = (w >= 0) & (w % 2 == 0) & (w // 2 < V * M)
        # last stage seeds chunk V-1 from the loss grad, lower chunks
        # from the recirculated cotangent FIFO
        seed = jax.lax.dynamic_index_in_dim(dy_ring, r_b, axis=0,
                                            keepdims=False)
        if V > 1:
            seed = jnp.where(
                v_b == V - 1, seed,
                jax.lax.dynamic_index_in_dim(dy_fifo, r_b, axis=0,
                                             keepdims=False))
        dy = jnp.where(is_last, seed, dy_recv)
        slot_b = (jnp.mod(g_b, G_live) * VP + v_b * P + r_b)
        stored = [jax.lax.dynamic_index_in_dim(buf, slot_b, axis=0,
                                               keepdims=False)
                  for buf in ring]
        params_b = _tree_select_chunk(chunk_params, v_b)

        def run_bwd(ops):
            dy_in, stored, p_b = ops
            # parameter-only residuals are x-independent: recompute them
            # from a zeros-x VJP (its x-dependent half is dead code),
            # splice in the ring's activation leaves, rebuild the VJP
            fresh_leaves = _vjp_leaves(p_b, zeros_x)
            it = iter(stored)
            leaves = [next(it) if d else fl
                      for fl, d in zip(fresh_leaves, xdep)]
            vjp_fn = jax.tree_util.tree_unflatten(res_treedef, leaves)
            dp, dx = vjp_fn((dy_in, daux))
            return (jax.tree_util.tree_map(
                        lambda g: g.astype(jnp.float32), dp),
                    dx.astype(dtype))

        def zero_bwd(ops):
            return (jax.tree_util.tree_map(
                        lambda p: jnp.zeros(jnp.shape(p), jnp.float32),
                        params0),
                    zeros_x)

        if skip_idle:
            dp, dx = jax.lax.cond(valid_b, run_bwd, zero_bwd,
                                  (dy, stored, params_b))
        else:
            dp, dx = run_bwd((dy, stored, params_b))
            dx = jnp.where(valid_b, dx, zeros_x)
        gacc = jax.tree_util.tree_map(
            lambda a, g: a.at[v_b].add(jnp.where(valid_b, g, 0.0)),
            gacc, dp)
        dmb = jnp.where(valid_b & (s == 0) & (v_b == 0),
                        jax.lax.dynamic_update_index_in_dim(
                            dmb, dx.astype(jnp.float32), m_b, axis=0),
                        dmb)

        y_send = jax.lax.ppermute(y, axis_name, fwd_perm)
        dx_send = jax.lax.ppermute(dx, axis_name, bwd_perm)
        return (y_send, dx_send, ring, dy_ring, fwd_fifo, dy_fifo, gacc,
                lacc, dmb, lpacc, aux_acc), None

    fifo0 = (jnp.zeros((P,) + x_shape, dtype) if V > 1 else ())
    init = (zeros_x, zeros_x, ring0,
            jnp.zeros((P,) + x_shape, dtype),      # dy_ring (loss seeds)
            fifo0,                                 # fwd recirc FIFO
            fifo0,                                 # dy recirc FIFO
            jax.tree_util.tree_map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32),
                chunk_params),
            jnp.zeros([], jnp.float32),
            jnp.zeros((M,) + x_shape, jnp.float32),
            zeros_lp, zero_aux)
    (_, _, _, _, _, _, grads, loss_sum, dmb, dloss_params, aux_sum), _ = \
        jax.lax.scan(tick, init, jnp.arange(T), unroll=scan_unroll)
    if V == 1:
        grads = jax.tree_util.tree_map(lambda g: g[0], grads)
    out = (loss_sum, grads, dmb)
    if loss_params is not None:
        out = out + (dloss_params,)
    if with_aux:
        out = out + (aux_sum,)
    return out

def forward_backward_no_pipelining(loss_fn, params, microbatches):
    """≙ ``fwd_bwd_no_pipelining``: sequential microbatches, one grad
    accumulation (grad sync happens once, outside — exactly the reference's
    "grad-sync only on the last microbatch" semantics under jit).

    ``loss_fn(params, microbatch) -> scalar``. Returns (mean_loss, grads).
    """
    grad_fn = jax.value_and_grad(loss_fn)

    def body(carry, mb):
        loss_acc, grad_acc = carry
        loss, grads = grad_fn(params, mb)
        return (loss_acc + loss,
                jax.tree_util.tree_map(jnp.add, grad_acc, grads)), None

    M = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    init = (jnp.zeros([], jnp.float32),
            jax.tree_util.tree_map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params))
    (loss_sum, grad_sum), _ = jax.lax.scan(body, init, microbatches)
    scale = 1.0 / M
    return loss_sum * scale, jax.tree_util.tree_map(
        lambda g: g * scale, grad_sum)


# ---------------------------------------------------------------------------
# mesh-level wrapper: full train-style fwd+bwd through the pipeline
# ---------------------------------------------------------------------------

def pipelined_loss_fn(
    stage_fn: Callable,
    loss_fn: Callable,
    mesh,
    *,
    num_chunks: int = 1,
    axis_name: str = AXIS_PP,
    params_spec=None,
    check_vma: bool = False,
    **pipeline_kwargs,
):
    """Build ``f(chunk_params_stacked, microbatches, targets) -> loss`` that
    runs the pipeline under ``shard_map`` over ``mesh``; differentiate with
    ``jax.grad`` for the full 1F1B-equivalent fwd+bwd.

    ``chunk_params_stacked`` leaves are (V, P, ...) — chunk-major, stage
    second — sharded on axis 1 over pp. ``loss_fn(outputs, targets) ->
    scalar`` runs replicated (outputs are broadcast from the last stage).
    Extra keyword arguments (``skip_bubbles`` — REQUIRED False for
    ppermute-bearing stages, ``remat_stage``, ``scan_unroll``,
    ``boundary_shape``, ...) pass through to :func:`pipeline_apply`.
    """
    from jax.sharding import PartitionSpec as Ps

    if params_spec is None:
        params_spec = Ps(None, axis_name)

    def inner(chunk_params, microbatches, targets):
        # drop the stage axis (size 1 locally)
        local = jax.tree_util.tree_map(lambda p: p[:, 0], chunk_params)
        outs = pipeline_apply(stage_fn, local, microbatches,
                              num_chunks=num_chunks, axis_name=axis_name,
                              **pipeline_kwargs)
        return loss_fn(outs, targets)

    smapped = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(params_spec, Ps(), Ps()),
        out_specs=Ps(),
        check_vma=check_vma)

    def f(chunk_params, microbatches, targets):
        # loss is replicated; take it as-is
        return smapped(chunk_params, microbatches, targets)

    return f


# ---------------------------------------------------------------------------
# Megatron-parity surface
# ---------------------------------------------------------------------------

def forward_backward_pipelining_without_interleaving(
        stage_fn, loss_fn, mesh, chunk_params, microbatches, targets,
        **kw):
    """1F1B-equivalent schedule (V=1). Returns (loss, grads)."""
    f = pipelined_loss_fn(stage_fn, loss_fn, mesh, num_chunks=1, **kw)
    return jax.value_and_grad(f)(chunk_params, microbatches, targets)


def forward_backward_pipelining_with_interleaving(
        stage_fn, loss_fn, mesh, chunk_params, microbatches, targets,
        num_chunks: int = 2, **kw):
    """Interleaved/virtual-pipeline schedule (V=num_chunks)."""
    f = pipelined_loss_fn(stage_fn, loss_fn, mesh, num_chunks=num_chunks,
                          **kw)
    return jax.value_and_grad(f)(chunk_params, microbatches, targets)


def get_forward_backward_func():
    """≙ ``schedules/__init__.py :: get_forward_backward_func`` — selects by
    the installed parallel state."""
    if (parallel_state.model_parallel_is_initialized()
            and parallel_state.get_pipeline_model_parallel_world_size() > 1):
        if parallel_state.get_virtual_pipeline_model_parallel_world_size():
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
