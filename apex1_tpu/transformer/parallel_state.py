"""Model-parallel topology state — reference
``apex/transformer/parallel_state.py :: initialize_model_parallel``.

The reference carves ``world_size`` NCCL ranks into TP groups (contiguous
ranks), then DP, then PP (strided outermost), plus embedding groups
({first, last} PP stage) and virtual-pipeline bookkeeping, all stored in
module-level globals that every transformer module queries.

TPU-native: ONE ``jax.sharding.Mesh`` is the topology — a mesh axis IS a
process group. This module keeps the reference's *API shape* (initialize /
getters / destroy, module-level state) so Megatron-style code ports
mechanically, while the returned objects are mesh axes and sizes. "Rank"
getters are meaningful only inside ``shard_map``-ped code, where they return
traced ``jax.lax.axis_index`` values.

Mesh layout matches the reference's rank order: TP innermost (contiguous
devices ⇒ fastest ICI), then CP, then PP, then DP/FSDP outermost (DCN on
multi-slice).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

from apex1_tpu.core import mesh as mesh_lib
from apex1_tpu.core.mesh import (AXIS_CP, AXIS_DP, AXIS_FSDP, AXIS_PP,
                                 AXIS_TP, MeshConfig)

_MESH: Optional[Mesh] = None
_VIRTUAL_PP_SIZE: Optional[int] = None
_VIRTUAL_PP_RANK: Optional[int] = None


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: int | None = None,
    context_parallel_size: int = 1,
    fsdp_size: int = 1,
    *,
    devices=None,
) -> Mesh:
    """Build and install the global mesh (≙ creating the NCCL groups).

    Data-parallel size is inferred as world // (tp·pp·cp·fsdp), exactly as
    the reference infers DP from world_size.
    """
    global _MESH, _VIRTUAL_PP_SIZE, _VIRTUAL_PP_RANK
    if _MESH is not None:
        raise RuntimeError(
            "model parallel already initialized; call destroy_model_parallel"
            " first")
    cfg = MeshConfig(dp=-1, fsdp=fsdp_size,
                     pp=pipeline_model_parallel_size,
                     cp=context_parallel_size,
                     tp=tensor_model_parallel_size)
    _MESH = mesh_lib.make_mesh(cfg, devices=devices)
    if virtual_pipeline_model_parallel_size is not None:
        if pipeline_model_parallel_size <= 1:
            raise ValueError("virtual pipeline requires pp > 1")
    _VIRTUAL_PP_SIZE = virtual_pipeline_model_parallel_size
    _VIRTUAL_PP_RANK = 0 if virtual_pipeline_model_parallel_size else None
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def destroy_model_parallel() -> None:
    global _MESH, _VIRTUAL_PP_SIZE, _VIRTUAL_PP_RANK
    _MESH = None
    _VIRTUAL_PP_SIZE = None
    _VIRTUAL_PP_RANK = None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError("call initialize_model_parallel() first")
    return _MESH


def set_mesh(mesh: Mesh) -> None:
    """Install an externally built mesh (pjit-style workflows)."""
    global _MESH
    _MESH = mesh


# -- group getters: the mesh axis IS the group ------------------------------

def get_tensor_model_parallel_group() -> str:
    return AXIS_TP


def get_pipeline_model_parallel_group() -> str:
    return AXIS_PP


def get_data_parallel_group() -> tuple[str, str]:
    """dp + fsdp jointly replicate gradients (fsdp shards them)."""
    return (AXIS_DP, AXIS_FSDP)


def get_context_parallel_group() -> str:
    return AXIS_CP


def get_embedding_group() -> str:
    """≙ the reference's embedding group ({first, last} PP stage ranks,
    built by ``initialize_model_parallel`` for tied input-embedding/LM-head
    grad sync). Mesh-native: the group IS the pp axis — the embedding-grad
    all-reduce is a psum over pp in which middle stages contribute zeros
    (see ``pipeline_parallel.schedules.allreduce_embedding_grads``), which
    is numerically identical to the reference's two-rank all-reduce."""
    return AXIS_PP


def is_rank_in_embedding_group():
    """Traced predicate: does this pp rank hold a tied-embedding copy that
    receives a nonzero grad contribution (first or last stage)? Valid
    inside any ``shard_map`` over a mesh with a pp axis (reads the
    enclosing mesh, not the module-level global)."""
    s = jax.lax.axis_index(AXIS_PP)
    return (s == 0) | (s == jax.lax.axis_size(AXIS_PP) - 1)


# -- size getters -----------------------------------------------------------

def get_tensor_model_parallel_world_size() -> int:
    return get_mesh().shape[AXIS_TP]


def get_pipeline_model_parallel_world_size() -> int:
    return get_mesh().shape[AXIS_PP]


def get_data_parallel_world_size() -> int:
    return get_mesh().shape[AXIS_DP] * get_mesh().shape[AXIS_FSDP]


def get_context_parallel_world_size() -> int:
    return get_mesh().shape[AXIS_CP]


def get_world_size() -> int:
    return get_mesh().size


# -- rank getters (traced; valid under shard_map over the mesh) -------------

def get_tensor_model_parallel_rank():
    return jax.lax.axis_index(AXIS_TP)


def get_pipeline_model_parallel_rank():
    return jax.lax.axis_index(AXIS_PP)


def get_data_parallel_rank():
    return jax.lax.axis_index((AXIS_DP, AXIS_FSDP))


def get_context_parallel_rank():
    return jax.lax.axis_index(AXIS_CP)


def is_pipeline_first_stage(ignore_virtual: bool = False):
    if not ignore_virtual and _VIRTUAL_PP_SIZE is not None:
        if _VIRTUAL_PP_RANK != 0:
            return False
    return get_pipeline_model_parallel_rank() == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if not ignore_virtual and _VIRTUAL_PP_SIZE is not None:
        if _VIRTUAL_PP_RANK != _VIRTUAL_PP_SIZE - 1:
            return False
    return (get_pipeline_model_parallel_rank()
            == get_pipeline_model_parallel_world_size() - 1)


# -- virtual pipeline (interleaved schedule bookkeeping) --------------------

def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PP_SIZE


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _VIRTUAL_PP_RANK


def set_virtual_pipeline_model_parallel_rank(rank: int) -> None:
    global _VIRTUAL_PP_RANK
    _VIRTUAL_PP_RANK = rank
