"""Transformer enums — reference ``apex/transformer/enums.py ::
ModelType, AttnType, AttnMaskType`` (consumed across the reference's
tensor/pipeline layers and fused-softmax adapter)."""

from __future__ import annotations

import enum


class ModelType(enum.Enum):
    encoder_or_decoder = 1
    encoder_and_decoder = 2


class AttnType(enum.Enum):
    self_attn = 1
    cross_attn = 2


class AttnMaskType(enum.Enum):
    padding = 1
    causal = 2
