"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

**Beyond-reference capability** (SURVEY.md §2.6 marks EP *[absent]* in
apex): provided because expert parallelism is a first-class distributed
strategy on TPU pods. The design is the canonical TPU MoE dataflow
(Mesh-TensorFlow / Switch-Transformer lineage, via PAPERS.md patterns):

- **Router**: dense gate → softmax → top-k (k ∈ {1, 2}); combine weights
  renormalized over the selected experts; Switch-style load-balance aux
  loss ``E · Σ_e f_e · p̄_e`` (fraction routed × mean prob).
- **Capacity-based dispatch**: each expert processes at most
  ``capacity = ceil(k · T / E · capacity_factor)`` tokens; overflow
  tokens are DROPPED from that expert (identity residual still carries
  them — Switch semantics). Dispatch/combine are one-hot einsum tensors,
  so the whole layer is static-shaped and MXU-friendly — no sorting, no
  dynamic shapes under jit.
- **Expert parallelism**: two forms, same math:
  1. **GSPMD**: stacked expert weights (E, ...) sharded over ``ep`` via
     `param_specs`; XLA inserts the all-to-alls.
  2. **Explicit shard_map** (`moe_shard_map_apply`): tokens sharded over
     ``ep``; ``jax.lax.all_to_all`` routes (expert, capacity) slots to
     the expert's device and back — the NCCL-alltoall dataflow the
     reference never had, on ICI.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.mesh import AXIS_EP


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2                 # 1 = Switch, 2 = GShard-style
    capacity_factor: float = 1.25
    hidden_size: int = 64
    ffn_size: int = 256
    aux_loss_weight: float = 1e-2


def _capacity(cfg: MoEConfig, n_tokens: int) -> int:
    # ceil, per the docstring: capacity_factor=1.0 must not drop tokens
    # under perfectly balanced routing
    cap = math.ceil(cfg.capacity_factor * cfg.top_k * n_tokens
                    / cfg.num_experts)
    return max(1, cap)


def router(x2, wg, cfg: MoEConfig, token_mask=None, *, stats_axes=None):
    """Top-k routing for flat tokens ``x2`` (T, H) with gate ``wg`` (H, E).

    Returns ``(dispatch (T, E, C) bool-as-float, combine (T, E, C) float,
    aux_loss scalar)``. Everything static-shaped: position-in-expert is a
    masked cumsum, tokens beyond capacity get zero dispatch/combine.
    ``token_mask`` (T,) bool: False tokens (padding in packed batches)
    claim no capacity and are excluded from the load-balance statistics.

    ``stats_axes``: mesh axis name(s) that shard ONE logical batch's
    tokens across callers (tp sequence shards, ep/dp token subsets, cp
    sequence shards). The Switch aux statistics (assignment fraction f,
    mean router prob p) are then ``psum``-combined over those axes before
    forming ``Σ f·p``, so every rank returns the aux loss of the GLOBAL
    token set — matching the unpartitioned model exactly (Σ f·p is
    nonlinear in the per-shard means, so summing per-shard aux would
    not). Dispatch/combine stay local; capacity is per-shard.
    """
    T = x2.shape[0]
    E, k = cfg.num_experts, cfg.top_k
    C = _capacity(cfg, T)
    logits = (x2.astype(jnp.float32) @ wg.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)            # (T, E)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)      # (T, k)
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)
    mask = (jnp.ones((T,), jnp.float32) if token_mask is None
            else token_mask.astype(jnp.float32))

    # Switch aux loss over the TOP-1 assignment fraction (valid tokens)
    top1_hot = jax.nn.one_hot(gate_idx[:, 0], E) * mask[:, None]
    n_sum = jnp.sum(mask)
    f_sum = jnp.sum(top1_hot, axis=0)                  # count per expert
    p_sum = jnp.sum(probs * mask[:, None], axis=0)     # prob mass
    if stats_axes is not None:
        n_sum = jax.lax.psum(n_sum, stats_axes)
        f_sum = jax.lax.psum(f_sum, stats_axes)
        p_sum = jax.lax.psum(p_sum, stats_axes)
    n_valid = jnp.maximum(n_sum, 1.0)
    f = f_sum / n_valid                                # fraction per expert
    p = p_sum / n_valid                                # mean prob
    aux = cfg.aux_loss_weight * E * jnp.sum(f * p)

    dispatch = jnp.zeros((T, E, C), jnp.float32)
    combine = jnp.zeros((T, E, C), jnp.float32)
    # priority: k-th choices claim capacity after all (k-1)-th choices —
    # GShard ordering; positions via exclusive cumsum per expert
    used = jnp.zeros((E,), jnp.float32)
    for j in range(k):
        hot = jax.nn.one_hot(gate_idx[:, j], E) * mask[:, None]  # (T, E)
        pos = (jnp.cumsum(hot, axis=0) - hot) + used[None, :]  # (T, E)
        within = (pos < C) & (hot > 0)
        pos_c = jax.nn.one_hot(pos.astype(jnp.int32), C) * within[..., None]
        dispatch = dispatch + hot[..., None] * pos_c
        combine = combine + (gate_vals[:, j, None, None]
                             * hot[..., None] * pos_c)
        used = used + jnp.sum(hot * within, axis=0)
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Dense-dispatch MoE FFN (GSPMD form): stacked expert weights
    (E, H, F)/(E, F, H); shard dim 0 over ``ep`` via `param_specs` and
    pjit does the rest. Returns ``(y, aux_loss)``."""

    cfg: MoEConfig
    dtype: jnp.dtype = jnp.float32
    act: Callable = jax.nn.gelu

    @nn.compact
    def __call__(self, x, token_mask=None):
        cfg = self.cfg
        lead = x.shape[:-1]
        H = x.shape[-1]
        x2 = x.reshape(-1, H)
        if token_mask is not None:
            token_mask = token_mask.reshape(-1)
        init = nn.initializers.normal(0.02)
        wg = self.param("router", init, (H, cfg.num_experts), jnp.float32)
        w1 = self.param("w1", init, (cfg.num_experts, H, cfg.ffn_size),
                        jnp.float32)
        w2 = self.param("w2", init, (cfg.num_experts, cfg.ffn_size, H),
                        jnp.float32)
        dispatch, combine, aux = router(x2, wg, cfg, token_mask)
        xe = jnp.einsum("tec,th->ech", dispatch.astype(self.dtype),
                        x2.astype(self.dtype))          # (E, C, H)
        h = self.act(jnp.einsum("ech,ehf->ecf", xe,
                                w1.astype(self.dtype)))
        ye = jnp.einsum("ecf,efh->ech", h, w2.astype(self.dtype))
        y = jnp.einsum("tec,ech->th", combine.astype(self.dtype), ye)
        return y.reshape(*lead, H).astype(x.dtype), aux


def param_specs(params, *, axis=AXIS_EP):
    """PartitionSpecs for a `MoEMLP` param tree: expert-stacked weights
    shard dim 0 over ``ep``; the router stays replicated."""
    from apex1_tpu.parallel.specs import specs_from_rules
    return specs_from_rules(
        params, ((r"w[12]$", P(axis, None, None)),), default=P())


def moe_shard_map_apply(x_local, wg, w1_local, w2_local, cfg: MoEConfig,
                        *, axis_name=AXIS_EP, act=jax.nn.gelu,
                        token_mask=None, stats_axes=None):
    """Explicit expert-parallel dataflow — call inside ``shard_map`` with
    tokens sharded over ``axis_name`` (x_local: (T_local, H)) and expert
    weights sharded over dim 0 (w1_local: (E_local, H, F)).

    Per device: route the LOCAL tokens against all E experts, build the
    local dispatch (T_l, E, C_l from the local token count), then
    ``all_to_all`` the (E, C_l, H) expert inputs so each device holds its
    own experts' slots from EVERY device — (E_l, ep·C_l, H) — runs its
    expert FFNs, and all_to_alls back. Two all-to-alls per layer over
    ICI, ≙ the NCCL alltoall in GPU MoE stacks.
    """
    ep = jax.lax.axis_size(axis_name)
    E = cfg.num_experts
    if E % ep:
        raise ValueError(f"num_experts {E} must divide by ep={ep}")
    dispatch, combine, aux = router(x_local, wg, cfg, token_mask,
                                    stats_axes=stats_axes)  # (T_l, E, C_l)
    dtype = x_local.dtype
    xe = jnp.einsum("tec,th->ech", dispatch.astype(dtype), x_local)
    # (E, C_l, H) -> split expert axis across devices, gather capacity:
    # each device ends with (E_l, ep*C_l, H)
    xe = jax.lax.all_to_all(xe, axis_name, split_axis=0, concat_axis=1,
                            tiled=True)
    h = act(jnp.einsum("ech,ehf->ecf", xe, w1_local.astype(dtype)))
    ye = jnp.einsum("ecf,efh->ech", h, w2_local.astype(dtype))
    # route results back: split capacity, gather experts
    ye = jax.lax.all_to_all(ye, axis_name, split_axis=1, concat_axis=0,
                            tiled=True)
    y = jnp.einsum("tec,ech->th", combine.astype(dtype), ye)
    # aux is a per-shard mean over local tokens; callers pmean it
    return y, aux
