"""Model-parallel framework — reference ``apex/transformer`` (vendored
Megatron core): parallel topology state, tensor parallelism, pipeline
schedules, microbatch calculators."""

from apex1_tpu.transformer import enums  # noqa: F401
from apex1_tpu.transformer import log_util  # noqa: F401
from apex1_tpu.transformer import moe  # noqa: F401
from apex1_tpu.transformer import parallel_state  # noqa: F401
from apex1_tpu.transformer import tensor_parallel  # noqa: F401
from apex1_tpu.transformer import pipeline_parallel  # noqa: F401
from apex1_tpu.transformer.enums import (  # noqa: F401
    AttnMaskType, AttnType, ModelType)
from apex1_tpu.transformer.log_util import set_logging_level  # noqa: F401
from apex1_tpu.transformer.microbatches import (  # noqa: F401
    build_num_microbatches_calculator)
