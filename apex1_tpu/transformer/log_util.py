"""Logging knob — reference ``apex/transformer/log_util.py ::
set_logging_level, get_transformer_logger``."""

from __future__ import annotations

import logging

_LOGGER_NAME = "apex1_tpu.transformer"


def get_transformer_logger(name: str | None = None) -> logging.Logger:
    return logging.getLogger(
        f"{_LOGGER_NAME}.{name}" if name else _LOGGER_NAME)


def set_logging_level(verbosity) -> None:
    """Set the transformer subsystem's log level (int or name)."""
    if isinstance(verbosity, str):
        verbosity = getattr(logging, verbosity.upper())
    get_transformer_logger().setLevel(verbosity)
