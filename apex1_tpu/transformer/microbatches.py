"""Microbatch calculators — reference ``apex/transformer/microbatches.py ::
build_num_microbatches_calculator, ConstantNumMicroBatches,
RampupBatchsizeNumMicroBatches``.

Pure host-side arithmetic mapping global batch size → (micro_batch_size,
num_micro_batches), including the linear batch-size ramp-up used by
Megatron-style trainers. Unchanged semantics; shapes must stay static per
compiled program, so a ramp-up implies recompilation per batch-size plateau
(the reference re-buckets identically).
"""

from __future__ import annotations


class ConstantNumMicroBatchesCalculator:
    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        micro_times_dp = micro_batch_size * data_parallel_size
        if global_batch_size % micro_times_dp:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"micro_batch*dp {micro_times_dp}")
        self.micro_batch_size = micro_batch_size
        self.num_micro_batches = global_batch_size // micro_times_dp
        self.current_global_batch_size = global_batch_size

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples: int, consistency_check: bool = True):
        pass


class RampupBatchsizeNumMicroBatchesCalculator:
    """Linear ramp from ``start_batch_size`` to ``global_batch_size`` by
    ``batch_size_increment`` every ``ramup_samples / steps`` samples."""

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        diff = global_batch_size - start_batch_size
        if diff % batch_size_increment:
            raise ValueError("ramp range not divisible by increment")
        num_increments = diff // batch_size_increment
        self.rampup_samples_per_increment = (
            ramup_samples / num_increments if num_increments else 0)
        self.update(0)

    def update(self, consumed_samples: int,
               consistency_check: bool = True) -> None:
        if (self.rampup_samples_per_increment == 0
                or consumed_samples > self.ramup_samples):
            current = self.global_batch_size
        else:
            steps = int(consumed_samples
                        // self.rampup_samples_per_increment)
            current = min(self.start_batch_size
                          + steps * self.batch_size_increment,
                          self.global_batch_size)
        micro_times_dp = self.micro_batch_size * self.data_parallel_size
        if consistency_check and current % micro_times_dp:
            raise ValueError(
                f"ramped batch {current} not divisible by micro*dp "
                f"{micro_times_dp}")
        self.current_global_batch_size = current
        self.num_micro_batches = current // micro_times_dp

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size


def build_num_microbatches_calculator(
        rampup_batch_size, global_batch_size: int, micro_batch_size: int,
        data_parallel_size: int):
    """``rampup_batch_size``: None or (start, increment, samples) — the
    reference's 3-element CLI arg."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatchesCalculator(
            global_batch_size, micro_batch_size, data_parallel_size)
    start, increment, samples = (int(x) for x in rampup_batch_size)
    return RampupBatchsizeNumMicroBatchesCalculator(
        start, increment, samples, global_batch_size, micro_batch_size,
        data_parallel_size)
