"""TP autograd collectives — reference
``apex/transformer/tensor_parallel/mappings.py``.

Each reference function is an ``autograd.Function`` pairing a forward
collective with its dual in backward:

    copy_to_tensor_model_parallel_region      fwd identity        bwd psum
    reduce_from_tensor_model_parallel_region  fwd psum            bwd identity
    scatter_to_tensor_model_parallel_region   fwd split(last)     bwd all-gather
    gather_from_tensor_model_parallel_region  fwd all-gather      bwd split
    scatter_to_sequence_parallel_region       fwd split(seq)      bwd all-gather
    gather_from_sequence_parallel_region      fwd all-gather(seq) bwd reduce-scatter
    reduce_scatter_to_sequence_parallel_region fwd reduce-scatter bwd all-gather

Implemented as ``jax.custom_vjp`` over XLA collectives, usable inside
``shard_map`` over the tp axis (axis_name parameter; default the canonical
"tp"). Under pure pjit/GSPMD these functions are unnecessary — sharding
annotations make XLA insert the same collectives — but the explicit forms
are required for schedule-controlled blocks and for parity tests
(≙ ``tests/L0/run_transformer/test_mapping.py``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex1_tpu.core.mesh import AXIS_TP


def _axis_size(axis_name):
    return jax.lax.axis_size(axis_name)


def _axis_index(axis_name):
    return jax.lax.axis_index(axis_name)


def _split_dim(x, axis_name, dim):
    """Local chunk of ``x`` along ``dim`` for this rank."""
    n = _axis_size(axis_name)
    if x.shape[dim] % n:
        raise ValueError(f"dim {dim} size {x.shape[dim]} not divisible by "
                         f"tp size {n}")
    chunk = x.shape[dim] // n
    idx = _axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=dim)


def _all_gather_dim(x, axis_name, dim):
    return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)


def _reduce_scatter_dim(x, axis_name, dim):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=dim,
                                tiled=True)


# -- tensor-parallel region --------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis_name=AXIS_TP):
    """``_CopyToModelParallelRegion``: identity fwd, all-reduce bwd."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis_name=AXIS_TP):
    """``_ReduceFromModelParallelRegion``: all-reduce fwd, identity bwd."""
    return jax.lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis_name=AXIS_TP):
    """``_ScatterToModelParallelRegion``: split last dim fwd, gather bwd."""
    return _split_dim(x, axis_name, -1)


def _scatter_fwd(x, axis_name):
    return _split_dim(x, axis_name, -1), None


def _scatter_bwd(axis_name, _, g):
    return (_all_gather_dim(g, axis_name, -1),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis_name=AXIS_TP):
    """``_GatherFromModelParallelRegion``: gather last dim fwd, split bwd."""
    return _all_gather_dim(x, axis_name, -1)


def _gather_fwd(x, axis_name):
    return _all_gather_dim(x, axis_name, -1), None


def _gather_bwd(axis_name, _, g):
    return (_split_dim(g, axis_name, -1),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# -- sequence-parallel region (Megatron SP; seq = leading dim) ---------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def scatter_to_sequence_parallel_region(x, axis_name=AXIS_TP, seq_dim=0):
    """``_ScatterToSequenceParallelRegion``: split seq fwd, gather bwd."""
    return _split_dim(x, axis_name, seq_dim)


def _sp_scatter_fwd(x, axis_name, seq_dim):
    return _split_dim(x, axis_name, seq_dim), None


def _sp_scatter_bwd(axis_name, seq_dim, _, g):
    return (_all_gather_dim(g, axis_name, seq_dim),)


scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def gather_from_sequence_parallel_region(x, axis_name=AXIS_TP, seq_dim=0,
                                         tensor_parallel_output_grad=True):
    """``_GatherFromSequenceParallelRegion``: all-gather seq fwd; bwd is
    reduce-scatter when the consumer is a TP op (each rank contributes a
    full-size grad), else a plain split."""
    return _all_gather_dim(x, axis_name, seq_dim)


def _sp_gather_fwd(x, axis_name, seq_dim, tensor_parallel_output_grad):
    return _all_gather_dim(x, axis_name, seq_dim), None


def _sp_gather_bwd(axis_name, seq_dim, tensor_parallel_output_grad, _, g):
    if tensor_parallel_output_grad:
        return (_reduce_scatter_dim(g, axis_name, seq_dim),)
    return (_split_dim(g, axis_name, seq_dim),)


gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def reduce_scatter_to_sequence_parallel_region(x, axis_name=AXIS_TP,
                                               seq_dim=0):
    """``_ReduceScatterToSequenceParallelRegion``: reduce-scatter fwd,
    all-gather bwd."""
    return _reduce_scatter_dim(x, axis_name, seq_dim)


def _sp_rs_fwd(x, axis_name, seq_dim):
    return _reduce_scatter_dim(x, axis_name, seq_dim), None


def _sp_rs_bwd(axis_name, seq_dim, _, g):
    return (_all_gather_dim(g, axis_name, seq_dim),)


reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)
