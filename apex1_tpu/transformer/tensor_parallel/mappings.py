"""TP autograd collectives — reference
``apex/transformer/tensor_parallel/mappings.py``.

Each reference function is an ``autograd.Function`` pairing a forward
collective with its dual in backward:

    copy_to_tensor_model_parallel_region      fwd identity        bwd psum
    reduce_from_tensor_model_parallel_region  fwd psum            bwd identity
    scatter_to_tensor_model_parallel_region   fwd split(last)     bwd all-gather
    gather_from_tensor_model_parallel_region  fwd all-gather      bwd split
    scatter_to_sequence_parallel_region       fwd split(seq)      bwd all-gather
    gather_from_sequence_parallel_region      fwd all-gather(seq) bwd reduce-scatter
    reduce_scatter_to_sequence_parallel_region fwd reduce-scatter bwd all-gather

Implemented as ``jax.custom_vjp`` over XLA collectives, usable inside
``shard_map`` over the tp axis (axis_name parameter; default the canonical
"tp"). Under pure pjit/GSPMD these functions are unnecessary — sharding
annotations make XLA insert the same collectives — but the explicit forms
are required for schedule-controlled blocks and for parity tests
(≙ ``tests/L0/run_transformer/test_mapping.py``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex1_tpu.core.mesh import AXIS_TP
from apex1_tpu.ops._common import vary as _vary  # ring-carry vma typing


def _axis_size(axis_name):
    return jax.lax.axis_size(axis_name)


def _axis_index(axis_name):
    return jax.lax.axis_index(axis_name)


def _split_dim(x, axis_name, dim):
    """Local chunk of ``x`` along ``dim`` for this rank."""
    n = _axis_size(axis_name)
    if x.shape[dim] % n:
        raise ValueError(f"dim {dim} size {x.shape[dim]} not divisible by "
                         f"tp size {n}")
    chunk = x.shape[dim] // n
    idx = _axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=dim)


def _all_gather_dim(x, axis_name, dim):
    return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)


def _reduce_scatter_dim(x, axis_name, dim):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=dim,
                                tiled=True)


# -- tensor-parallel region --------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis_name=AXIS_TP):
    """``_CopyToModelParallelRegion``: identity fwd, all-reduce bwd."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis_name=AXIS_TP):
    """``_ReduceFromModelParallelRegion``: all-reduce fwd, identity bwd."""
    return jax.lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis_name=AXIS_TP):
    """``_ScatterToModelParallelRegion``: split last dim fwd, gather bwd."""
    return _split_dim(x, axis_name, -1)


def _scatter_fwd(x, axis_name):
    return _split_dim(x, axis_name, -1), None


def _scatter_bwd(axis_name, _, g):
    return (_all_gather_dim(g, axis_name, -1),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis_name=AXIS_TP):
    """``_GatherFromModelParallelRegion``: gather last dim fwd, split bwd."""
    return _all_gather_dim(x, axis_name, -1)


def _gather_fwd(x, axis_name):
    return _all_gather_dim(x, axis_name, -1), None


def _gather_bwd(axis_name, _, g):
    return (_split_dim(g, axis_name, -1),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# -- sequence-parallel region (Megatron SP; seq = leading dim) ---------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def scatter_to_sequence_parallel_region(x, axis_name=AXIS_TP, seq_dim=0):
    """``_ScatterToSequenceParallelRegion``: split seq fwd, gather bwd."""
    return _split_dim(x, axis_name, seq_dim)


def _sp_scatter_fwd(x, axis_name, seq_dim):
    return _split_dim(x, axis_name, seq_dim), None


def _sp_scatter_bwd(axis_name, seq_dim, _, g):
    return (_all_gather_dim(g, axis_name, seq_dim),)


scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def gather_from_sequence_parallel_region(x, axis_name=AXIS_TP, seq_dim=0,
                                         tensor_parallel_output_grad=True):
    """``_GatherFromSequenceParallelRegion``: all-gather seq fwd; bwd is
    reduce-scatter when the consumer is a TP op (each rank contributes a
    full-size grad), else a plain split."""
    return _all_gather_dim(x, axis_name, seq_dim)


def _sp_gather_fwd(x, axis_name, seq_dim, tensor_parallel_output_grad):
    return _all_gather_dim(x, axis_name, seq_dim), None


def _sp_gather_bwd(axis_name, seq_dim, tensor_parallel_output_grad, _, g):
    if tensor_parallel_output_grad:
        return (_reduce_scatter_dim(g, axis_name, seq_dim),)
    return (_split_dim(g, axis_name, seq_dim),)


gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def reduce_scatter_to_sequence_parallel_region(x, axis_name=AXIS_TP,
                                               seq_dim=0):
    """``_ReduceScatterToSequenceParallelRegion``: reduce-scatter fwd,
    all-gather bwd."""
    return _reduce_scatter_dim(x, axis_name, seq_dim)


def _sp_rs_fwd(x, axis_name, seq_dim):
    return _reduce_scatter_dim(x, axis_name, seq_dim), None


def _sp_rs_bwd(axis_name, seq_dim, _, g):
    return (_all_gather_dim(g, axis_name, seq_dim),)


reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)


# -- decomposed collective matmuls (chunk-pipelined, transfers overlapped) ----
#
# The monolithic SP collectives above expose the whole transfer before
# (all-gather) or after (reduce-scatter) the matmul. These variants
# decompose the collective into n per-shard chunks ppermuted around the
# tp ring, one chunk per step, with each transfer issued so the step's
# partial dot has NO data dependence on it — XLA's async
# collective-permute then hides the ICI time behind the MXU work (the
# technique of arxiv 2305.06942's fused computation-collective ops and
# the reference's DDP bucketed overlap, applied to Megatron-SP's
# boundary collectives). `testing.hlo_probe` pins the overlap shape on
# optimized HLO. Opt-in via ``overlap=`` on the layer entry points in
# `tensor_parallel.layers`; the monolithic forms above stay the default.


def _chunk(x, seq_dim, start, size):
    return jax.lax.dynamic_slice_in_dim(x, start, size, axis=seq_dim)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def all_gather_matmul(x, w, axis_name=AXIS_TP, seq_dim=0):
    """``all_gather(x, seq_dim) @ w`` with the gather decomposed into a
    ppermute ring: each of the n steps multiplies the currently-held
    chunk while the NEXT chunk is already in flight (prologue + n−2
    in-loop transfers = n−1 permutes, all overlapped).

    ``x``: the local sequence chunk (S/n, …, in); ``w``: (in, out_shard).
    Returns the full-sequence product (S, …, out_shard) in fp32 (the
    chunk dots accumulate with ``preferred_element_type=float32``; cast
    at the call site like the monolithic path does).
    """
    return _agm_loop(x, w, axis_name, seq_dim)


def _agm_loop(x, w, axis_name, seq_dim):
    n = _axis_size(axis_name)
    chunk = x.shape[seq_dim]

    def dot(c):
        return jnp.dot(c, w, preferred_element_type=jnp.float32)

    if n == 1:
        return dot(x)
    idx = _axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    out_shape = list(x.shape)
    out_shape[seq_dim] = chunk * n
    out_shape[-1] = w.shape[-1]
    y = _vary(jnp.zeros(tuple(out_shape), jnp.float32), axis_name)

    def place(y, part, src):
        return jax.lax.dynamic_update_slice_in_dim(
            y, part, src * chunk, axis=seq_dim)

    # prologue: issue the transfer for step 1, then dot the local chunk
    # — the dot has no dependence on the in-flight chunk
    cur = jax.lax.ppermute(x, axis_name, perm)
    y = place(y, dot(x), idx)

    def step(carry, t):
        cur, y = carry
        nxt = jax.lax.ppermute(cur, axis_name, perm)   # chunk t+1
        y = place(y, dot(cur), (idx - t) % n)          # chunk t
        return (nxt, y), None

    if n > 2:
        (cur, y), _ = jax.lax.scan(step, (cur, y), jnp.arange(1, n - 1))
    # epilogue: last chunk — nothing left to transfer
    return place(y, dot(cur), (idx - (n - 1)) % n)


def _agm_fwd(x, w, axis_name, seq_dim):
    return _agm_loop(x, w, axis_name, seq_dim), (x, w)


def _agm_bwd(axis_name, seq_dim, res, g):
    x, w = res
    # dx: reduce-scatter of g @ wᵀ — itself the decomposed overlapped
    # form; dw: re-gather x (Megatron re-all-gathers in backward rather
    # than saving the gathered activation) and contract the sequence
    dx = matmul_reduce_scatter(g, jnp.swapaxes(w, 0, 1), axis_name,
                               seq_dim)
    gx = _all_gather_dim(x, axis_name, seq_dim)
    dw = jnp.matmul(gx.reshape(-1, gx.shape[-1]).T,
                    g.reshape(-1, g.shape[-1]),
                    preferred_element_type=jnp.float32)
    return dx.astype(x.dtype), dw.astype(w.dtype)


all_gather_matmul.defvjp(_agm_fwd, _agm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def matmul_reduce_scatter(x, w, axis_name=AXIS_TP, seq_dim=0):
    """``psum_scatter(x @ w, seq_dim)`` with the reduce-scatter
    decomposed into a ppermute ring: a travelling per-chunk accumulator
    hops toward its owner while each step's partial dot — independent
    of the in-flight transfer (each hop ships ``acc + pend``, both scan
    carries; the dot's result enters the carry as next step's ``pend``)
    — overlaps it. n accumulator hops total (one zero-valued seed hop —
    see the in-loop comment on why add-then-hop loses the overlap);
    each rank's own partial is computed at the last step and folded in
    after the loop, so per chunk the summation order matches a
    monolithic ring reduce-scatter.

    ``x``: full-sequence local operand (S, …, in_shard); ``w``:
    (in_shard, out). Returns this rank's sequence chunk (S/n, …, out)
    of the summed product, in fp32.
    """
    return _mrs_loop(x, w, axis_name, seq_dim)


def _mrs_loop(x, w, axis_name, seq_dim):
    n = _axis_size(axis_name)
    S = x.shape[seq_dim]
    if S % n:
        raise ValueError(f"seq dim {seq_dim} size {S} not divisible by "
                         f"tp size {n}")
    chunk = S // n

    def part(c):
        rows = _chunk(x, seq_dim, c * chunk, chunk)
        return jnp.dot(rows, w, preferred_element_type=jnp.float32)

    if n == 1:
        return part(0)
    idx = _axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # travelling accumulator + one-step-delayed "pending" partial: the
    # hop ships acc+pend — BOTH carry values — and this step's dot
    # lands in the carry untouched. An add-then-hop accumulator reads
    # nicer but XLA fuses the add INTO the dot (convolution_add
    # fusion), making the fused compute consume the permute-done and
    # serializing the transfer against the MXU work — observed on the
    # v5e AOT probe; the hlo_probe gate in tools/aot_check.py keeps it
    # from regressing. Cost: one zero-valued seed hop (n hops instead
    # of n−1), fully overlapped.
    shape = list(x.shape)
    shape[seq_dim] = chunk
    shape[-1] = w.shape[-1]
    acc = _vary(jnp.zeros(tuple(shape), jnp.float32), axis_name)
    pend = _vary(jnp.zeros(tuple(shape), jnp.float32), axis_name)

    def step(carry, t):
        acc, pend = carry
        acc = jax.lax.ppermute(acc + pend, axis_name, perm)
        # chunk order per chunk c: devices c+1, c+2, …, c−1, then the
        # owner folds its own partial in after the loop — the same
        # summation order as a monolithic psum_scatter ring
        pend = part((idx - 1 - t) % n)
        return (acc, pend), None

    (acc, pend), _ = jax.lax.scan(step, (acc, pend), jnp.arange(0, n))
    return acc + pend


def _mrs_fwd(x, w, axis_name, seq_dim):
    return _mrs_loop(x, w, axis_name, seq_dim), (x, w)


def _mrs_bwd(axis_name, seq_dim, res, g):
    x, w = res
    # dx: all-gather(g) @ wᵀ — the decomposed overlapped form again;
    # dw: xᵀ contracted with the re-gathered cotangent
    dx = all_gather_matmul(g, jnp.swapaxes(w, 0, 1), axis_name, seq_dim)
    gg = _all_gather_dim(g, axis_name, seq_dim)
    dw = jnp.matmul(x.reshape(-1, x.shape[-1]).T,
                    gg.reshape(-1, gg.shape[-1]),
                    preferred_element_type=jnp.float32)
    return dx.astype(x.dtype), dw.astype(w.dtype)


matmul_reduce_scatter.defvjp(_mrs_fwd, _mrs_bwd)
