"""Shape utilities — reference ``apex/transformer/utils.py :: divide,
split_tensor_along_last_dim`` and ``tensor_parallel/utils.py ::
VocabUtility``."""

from __future__ import annotations

import jax.numpy as jnp


def ensure_divisibility(numerator: int, denominator: int) -> None:
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(x, num_partitions: int):
    """Static split into equal chunks (reference returns contiguous views)."""
    ensure_divisibility(x.shape[-1], num_partitions)
    return jnp.split(x, num_partitions, axis=-1)


class VocabUtility:
    """Vocab-range arithmetic for vocab-sharded tables."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(per_partition_size, rank,
                                                  world_size=None):
        del world_size
        start = rank * per_partition_size
        return start, start + per_partition_size

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size, rank,
                                           world_size):
        per = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per, rank)
