"""MP RNG + activation checkpointing — reference
``apex/transformer/tensor_parallel/random.py :: CudaRNGStatesTracker,
model_parallel_cuda_manual_seed, checkpoint``.

JAX's counter-based threefry removes the stateful machinery (SURVEY §5.4):
- per-TP-rank dropout divergence = ``fold_in`` of the axis index;
- checkpoint recompute replays keys exactly (no state snapshot needed);
- ``--distribute-saved-activations`` ≙ remat + sharding constraints.

The tracker API shape is preserved so ported code reads the same.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax

from apex1_tpu.core.mesh import AXIS_TP
from apex1_tpu.core.random import domain_key

_MODEL_PARALLEL_RNG = "model-parallel-rng"


class RNGStatesTracker:
    """≙ ``CudaRNGStatesTracker``: named RNG domains. ``add(name, seed)``
    registers a domain; ``fork(name)`` yields the domain key (per-TP-rank
    when used inside shard_map)."""

    def __init__(self):
        self._seeds: dict[str, jax.Array] = {}

    def reset(self):
        self._seeds.clear()

    def get_states(self):
        return dict(self._seeds)

    def set_states(self, states):
        self._seeds = dict(states)

    def add(self, name: str, seed: int):
        if name in self._seeds:
            raise RuntimeError(f"rng domain {name} already present")
        self._seeds[name] = jax.random.PRNGKey(seed)

    def fork(self, name: str = _MODEL_PARALLEL_RNG, *,
             tp_axis: str | None = AXIS_TP) -> jax.Array:
        key = self._seeds[name]
        if tp_axis is not None:
            try:
                key = jax.random.fold_in(key, jax.lax.axis_index(tp_axis))
            except NameError:
                pass  # not inside shard_map; single-rank semantics
        return key


_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    """≙ ``get_cuda_rng_tracker``."""
    return _TRACKER


def model_parallel_seed(seed: int) -> None:
    """≙ ``model_parallel_cuda_manual_seed(seed)``: default stream seeded
    ``seed`` (same across TP), model-parallel domain ``seed + 2718`` with
    the per-rank fold applied at ``fork`` time."""
    _TRACKER.reset()
    _TRACKER.add("default", seed)
    _TRACKER.add(_MODEL_PARALLEL_RNG, seed + 2718)


# checkpoint: the reference's ``checkpoint(fn, *args)`` recomputes fn in
# backward with exact RNG replay. jax.checkpoint IS that; policies expose
# the reference's distribute/checkpoint knobs.
checkpoint = jax.checkpoint


def checkpoint_policy(name: str = "nothing_saveable"):
    """Remat policies: "nothing_saveable" (recompute all, the reference's
    full activation checkpointing), "dots_saveable" (keep matmul outputs),
    "dots_with_no_batch_dims_saveable" (keep weight-stationary dots —
    Megatron's selective ``--recompute-activations``). Unknown names
    raise immediately (config validation calls this too, so a typo'd
    ``remat_policy`` fails at construction, not deep inside tracing)."""
    pol = getattr(jax.checkpoint_policies, name, None)
    if pol is None:
        valid = [n for n in dir(jax.checkpoint_policies)
                 if not n.startswith("_")]
        raise ValueError(f"unknown checkpoint policy {name!r}; valid "
                         f"names: {valid}")
    return pol


def checkpoint_with_policy(fn: Callable, policy_name: str):
    return jax.checkpoint(fn, policy=checkpoint_policy(policy_name))
