"""Tensor parallelism — reference ``apex/transformer/tensor_parallel``."""

from apex1_tpu.transformer.tensor_parallel.mappings import (  # noqa: F401
    copy_to_tensor_model_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    scatter_to_tensor_model_parallel_region,
    scatter_to_sequence_parallel_region,
    gather_from_sequence_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    all_gather_matmul,
    matmul_reduce_scatter,
)
from apex1_tpu.transformer.tensor_parallel.layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)
from apex1_tpu.transformer.tensor_parallel.cross_entropy import (  # noqa: F401
    vocab_parallel_cross_entropy, vocab_parallel_linear_cross_entropy,
)
from apex1_tpu.transformer.tensor_parallel.random import (  # noqa: F401
    RNGStatesTracker, checkpoint, get_rng_tracker, model_parallel_seed)
from apex1_tpu.transformer.tensor_parallel.utils import (  # noqa: F401
    VocabUtility, divide, split_tensor_along_last_dim)
from apex1_tpu.transformer.tensor_parallel.data import broadcast_data  # noqa: F401
