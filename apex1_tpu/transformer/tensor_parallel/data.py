"""Input-data broadcast within the TP group — reference
``apex/transformer/tensor_parallel/data.py :: broadcast_data``.

The reference broadcasts the host batch from TP-rank-0 over NCCL so every
TP rank traces identical data. Under a JAX single-controller mesh, inputs
placed with a replicated sharding across the tp axis ARE that broadcast —
this helper exists for porting parity and for the shard_map path, where it
re-synchronizes by taking rank-0's copy (an exactness guard against
divergent per-rank host data, ≙ the reference's keys/dtype checks).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex1_tpu.core.mesh import AXIS_TP


def broadcast_data(keys, data: dict, datatype=None, *, axis_name=AXIS_TP):
    """Inside shard_map: make ``data[k]`` identical across the tp axis by
    selecting rank-0's values (psum of the masked copy)."""
    out = {}
    for k in keys:
        x = data[k]
        if datatype is not None:
            x = x.astype(datatype)
        is0 = (jax.lax.axis_index(axis_name) == 0)
        cast = jnp.asarray(x)
        # float path sums zeros elsewhere; works for ints too
        out[k] = jax.lax.psum(jnp.where(is0, cast, jnp.zeros_like(cast)),
                              axis_name)
    return out
