"""Vocab-parallel cross-entropy — reference
``apex/transformer/tensor_parallel/cross_entropy.py ::
vocab_parallel_cross_entropy``.

Reference algorithm over vocab-sharded logits, reproduced step for step:
  1. local max → all-reduce MAX          (numerical stability)
  2. local Σ exp(x−max) → all-reduce SUM (denominator)
  3. target logit gathered via the local-range mask trick → all-reduce SUM
  4. loss = log(Σexp) − (target − max)
Backward is local: softmax_shard − onehot_shard (custom_vjp, no collective —
the reference's backward is likewise local).

Runs inside ``shard_map`` over the tp axis. Label smoothing follows the
newer reference signature (``label_smoothing`` arg).

``vocab_parallel_linear_cross_entropy`` below goes a step further than the
reference: the LM-head matmul is fused INTO the vocab-parallel CE
(``ops/linear_xent.py`` kernels per shard + pmax/psum stat merge), so not
even the local logits slice materializes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu.core.mesh import AXIS_TP
from apex1_tpu.ops._common import NEG_INF, use_pallas


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def vocab_parallel_cross_entropy(logits_shard, targets, label_smoothing=0.0,
                                 axis_name=AXIS_TP):
    """``logits_shard``: (..., V/tp) this rank's vocab slice; ``targets``:
    (...) global vocab ids (replicated). Returns per-token loss
    (replicated)."""
    loss, _ = _fwd(logits_shard, targets, label_smoothing, axis_name)
    return loss


def _stats(logits_shard, targets, axis_name):
    x = logits_shard.astype(jnp.float32)
    per = x.shape[-1]
    start = jax.lax.axis_index(axis_name) * per
    local_max = jnp.max(x, axis=-1)
    gmax = jax.lax.pmax(local_max, axis_name)
    e = jnp.exp(x - gmax[..., None])
    gsum = jax.lax.psum(jnp.sum(e, axis=-1), axis_name)
    # target-logit mask trick
    local_t = targets - start
    in_shard = (local_t >= 0) & (local_t < per)
    local_t = jnp.clip(local_t, 0, per - 1)
    tgt = jnp.take_along_axis(x, local_t[..., None], axis=-1)[..., 0]
    tgt = jax.lax.psum(jnp.where(in_shard, tgt, 0.0), axis_name)
    return x, gmax, gsum, tgt, in_shard, local_t, start, per


def _fwd(logits_shard, targets, label_smoothing, axis_name):
    x, gmax, gsum, tgt, in_shard, local_t, start, per = _stats(
        logits_shard, targets, axis_name)
    lse = gmax + jnp.log(gsum)
    loss = lse - tgt
    if label_smoothing:
        vocab = per * jax.lax.axis_size(axis_name)
        mean_x = jax.lax.psum(jnp.sum(x, axis=-1), axis_name) / vocab
        loss = ((1.0 - label_smoothing) * loss
                + label_smoothing * (lse - mean_x))
    return loss, (logits_shard, targets, gmax, gsum)


def _bwd(label_smoothing, axis_name, res, dloss):
    logits_shard, targets, gmax, gsum = res
    x = logits_shard.astype(jnp.float32)
    per = x.shape[-1]
    start = jax.lax.axis_index(axis_name) * per
    p = jnp.exp(x - gmax[..., None]) / gsum[..., None]
    local_t = targets - start
    in_shard = (local_t >= 0) & (local_t < per)
    onehot = ((jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
               == jnp.clip(local_t, 0, per - 1)[..., None])
              & in_shard[..., None])
    grad = p - (1.0 - label_smoothing) * onehot
    if label_smoothing:
        vocab = per * jax.lax.axis_size(axis_name)
        grad = grad - label_smoothing / vocab
    grad = grad * dloss[..., None]
    return grad.astype(logits_shard.dtype), None


vocab_parallel_cross_entropy.defvjp(
    lambda lg, t, ls, ax: _fwd(lg, t, ls, ax),
    _bwd)


# ---------------------------------------------------------------------------
# Fused LM-head + vocab-parallel CE: the `ops.linear_xent` kernels composed
# over the tp axis — each rank's W shard (V/tp, H) produces partial
# online-softmax stats (never materializing even the LOCAL logits slice),
# merged with pmax/psum. A capability the reference does NOT have (its
# vocab-parallel CE takes materialized sharded logits). Both the Pallas
# and the XLA-composite implementations share ONE hand-written custom_vjp
# (collectives live inside fwd/bwd), so correctness never depends on
# shard_map's transpose conventions for replicated operands.
# ---------------------------------------------------------------------------

def _xla_shard_stats(x2, w_shard, t2, off, k):
    """jnp twin of ``ops.linear_xent.shard_stats`` (materializes the local
    logits slice — the gold / CPU path)."""
    logits = jnp.einsum("th,vh->tv", x2.astype(jnp.float32),
                        w_shard.astype(jnp.float32))
    gcol = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + off
    valid = gcol < k
    xm = jnp.where(valid, logits, NEG_INF)
    m = jnp.max(xm, axis=-1)
    l = jnp.sum(jnp.where(valid, jnp.exp(xm - m[:, None]), 0.0), axis=-1)
    tgt = jnp.sum(jnp.where(gcol == t2, logits, 0.0), axis=-1)
    sumx = jnp.sum(jnp.where(valid, logits, 0.0), axis=-1)
    return m, l, tgt, sumx


def _xla_shard_grads(x2, w_shard, t2, lse, dloss, off, smoothing,
                     padding_idx, k):
    """jnp twin of ``ops.linear_xent.shard_grads``."""
    logits = jnp.einsum("th,vh->tv", x2.astype(jnp.float32),
                        w_shard.astype(jnp.float32))
    gcol = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + off
    valid = gcol < k
    p = jnp.where(valid, jnp.exp(logits - lse[:, None]), 0.0)
    g = p - (1.0 - smoothing) * (gcol == t2) - smoothing / k
    g = jnp.where(valid, g, 0.0)
    dl = dloss.astype(jnp.float32)
    if padding_idx is not None:
        dl = jnp.where(t2[:, 0] == padding_idx, 0.0, dl)
    g = g * dl[:, None]
    dx = (g @ w_shard.astype(jnp.float32)).astype(x2.dtype)
    dw = (g.T @ x2.astype(jnp.float32)).astype(w_shard.dtype)
    return dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _vp_fused(x2, w_shard, t2, axis_name, smoothing, padding_idx,
              num_classes, fused_merge=False):
    return _vp_fused_fwd(x2, w_shard, t2, axis_name, smoothing,
                         padding_idx, num_classes, fused_merge)[0]


def _vp_merge(m, l, tgt, sumx, axis_name):
    gmax = jax.lax.pmax(m, axis_name)
    gsum = jax.lax.psum(l * jnp.exp(m - gmax), axis_name)
    return (gmax + jnp.log(gsum), jax.lax.psum(tgt, axis_name),
            jax.lax.psum(sumx, axis_name))


def _vp_k(w_shard, axis_name, num_classes):
    vocab = w_shard.shape[0] * jax.lax.axis_size(axis_name)
    return num_classes if num_classes is not None else vocab


def _vp_fused_fwd(x2, w_shard, t2, axis_name, smoothing, padding_idx,
                  num_classes, fused_merge=False):
    k = _vp_k(w_shard, axis_name, num_classes)
    off = jax.lax.axis_index(axis_name) * w_shard.shape[0]
    if fused_merge:
        # fused comm-kernel form (ops.fused_collective): the kernel's
        # final vocab tile packs [m, l, tgt, sumx] into ONE stat stream
        # and the cross-shard ladder collapses to pmax + one packed
        # psum (2 collectives instead of 4) — bitwise the decomposed
        # path's numbers (packed psum reduces lanes independently)
        from apex1_tpu.ops.fused_collective import (
            fused_vocab_parallel_merge)
        if use_pallas():
            from apex1_tpu.ops.linear_xent import shard_stats_packed
            stats = shard_stats_packed(x2, w_shard, t2, col_offset=off,
                                       num_classes=k)
        else:
            m, l, tgt, sumx = _xla_shard_stats(x2, w_shard, t2, off, k)
            stats = jnp.stack([m, l, tgt, sumx], axis=-1)
        lse, tgt, sumx = fused_vocab_parallel_merge(stats, axis_name)
    else:
        if use_pallas():
            from apex1_tpu.ops.linear_xent import shard_stats
            m, l, tgt, sumx = shard_stats(x2, w_shard, t2, col_offset=off,
                                          num_classes=k)
        else:
            m, l, tgt, sumx = _xla_shard_stats(x2, w_shard, t2, off, k)
        lse, tgt, sumx = _vp_merge(m, l, tgt, sumx, axis_name)
    loss = ((1.0 - smoothing) * (lse - tgt)
            + smoothing * (lse - sumx / k))
    if padding_idx is not None:
        loss = jnp.where(t2[:, 0] == padding_idx, 0.0, loss)
    return loss, (x2, w_shard, t2, lse)


def _vp_fused_bwd(axis_name, smoothing, padding_idx, num_classes,
                  fused_merge, res, dloss):
    x2, w_shard, t2, lse = res
    k = _vp_k(w_shard, axis_name, num_classes)
    off = jax.lax.axis_index(axis_name) * w_shard.shape[0]
    if use_pallas():
        from apex1_tpu.ops.linear_xent import shard_grads
        dx_part, dw = shard_grads(x2, w_shard, t2, lse, dloss,
                                  col_offset=off, smoothing=smoothing,
                                  padding_idx=padding_idx, num_classes=k)
    else:
        dx_part, dw = _xla_shard_grads(x2, w_shard, t2, lse, dloss, off,
                                       smoothing, padding_idx, k)
    # dx is SHARD-PARTIAL (this rank saw only its vocab columns): the
    # cross-shard sum belongs to the ONE input collective the wrapper
    # applied (copy-region bwd psum, or all_gather bwd reduce-scatter) —
    # summing here as well would double-count (Megatron's CE backward is
    # likewise local)
    return dx_part, dw, np.zeros(t2.shape, dtype=jax.dtypes.float0)


_vp_fused.defvjp(_vp_fused_fwd, _vp_fused_bwd)


def vocab_parallel_linear_cross_entropy(x, w_shard, labels, *,
                                        axis_name=AXIS_TP,
                                        label_smoothing: float = 0.0,
                                        padding_idx: int | None = None,
                                        num_classes: int | None = None,
                                        sequence_parallel_input=False,
                                        fused: bool = False):
    """CE of ``softmax(x @ global_Wᵀ)`` with W vocab-sharded over
    ``axis_name`` — on TPU, logits (even the local slice) never
    materialize. Runs inside ``shard_map``; shards must be equal-sized
    (Megatron ``VocabUtility`` equal-split convention).

    ``w_shard`` (V/tp, H) is this rank's rows; ``labels`` are GLOBAL
    vocab ids over the GLOBAL token set. Like the reference's
    ``ColumnParallelLinear``, the op applies exactly ONE input collective
    so activation gradients come out right (the kernel's dx cotangent is
    shard-partial):

    - ``sequence_parallel_input=False`` (default): ``x`` (..., H) is
      replicated across tp → copy-to-region (identity fwd, psum bwd).
    - ``True``: ``x`` (..., H) is this rank's SEQUENCE shard (leading
      token axis sharded over tp; ≙ Megatron SP's gather before the
      head) → internal tiled all_gather (bwd reduce-scatter). The
      returned loss covers the GLOBAL token set, replicated.

    Returns per-token fp32 loss, identical on every rank.
    ``num_classes`` masks global lane-pad columns.

    ``fused=True`` (opt-in, default off = the untouched legacy path):
    the fused comm-kernel merge — per-shard stats packed into one
    kernel output by the final vocab tile
    (`ops.linear_xent.shard_stats_packed`) and the pmax/psum ladder
    collapsed to TWO collectives
    (`ops.fused_collective.fused_vocab_parallel_merge`). Bitwise the
    same loss as ``fused=False`` (pinned by test_fused_collective;
    structural 2-vs-4 collective count pinned via
    `testing.hlo_probe.count_collectives`).
    """
    from apex1_tpu.transformer.tensor_parallel.mappings import (
        copy_to_tensor_model_parallel_region)
    if x.shape[-1] != w_shard.shape[-1]:
        raise ValueError(f"hidden mismatch: x {x.shape} vs w_shard "
                         f"{w_shard.shape}")
    x2 = x.reshape(-1, x.shape[-1])
    if sequence_parallel_input:
        x2 = jax.lax.all_gather(x2, axis_name, axis=0, tiled=True)
    else:
        x2 = copy_to_tensor_model_parallel_region(x2, axis_name)
    t2 = labels.reshape(-1, 1).astype(jnp.int32)
    if t2.shape[0] != x2.shape[0]:
        raise ValueError(
            f"labels cover {t2.shape[0]} tokens but x has {x2.shape[0]} "
            "(labels must span the GLOBAL token set)")
    vocab = w_shard.shape[0] * jax.lax.axis_size(axis_name)
    if num_classes is not None and not (0 < num_classes <= vocab):
        raise ValueError(f"num_classes {num_classes} must be in "
                         f"(0, {vocab}]")
    loss = _vp_fused(x2, w_shard, t2, axis_name, float(label_smoothing),
                     padding_idx, num_classes, bool(fused))
    lead = labels.shape
    return loss.reshape(lead)
