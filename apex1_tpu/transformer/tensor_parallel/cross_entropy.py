"""Vocab-parallel cross-entropy — reference
``apex/transformer/tensor_parallel/cross_entropy.py ::
vocab_parallel_cross_entropy``.

Reference algorithm over vocab-sharded logits, reproduced step for step:
  1. local max → all-reduce MAX          (numerical stability)
  2. local Σ exp(x−max) → all-reduce SUM (denominator)
  3. target logit gathered via the local-range mask trick → all-reduce SUM
  4. loss = log(Σexp) − (target − max)
Backward is local: softmax_shard − onehot_shard (custom_vjp, no collective —
the reference's backward is likewise local).

Runs inside ``shard_map`` over the tp axis. Label smoothing follows the
newer reference signature (``label_smoothing`` arg).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex1_tpu.core.mesh import AXIS_TP


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def vocab_parallel_cross_entropy(logits_shard, targets, label_smoothing=0.0,
                                 axis_name=AXIS_TP):
    """``logits_shard``: (..., V/tp) this rank's vocab slice; ``targets``:
    (...) global vocab ids (replicated). Returns per-token loss
    (replicated)."""
    loss, _ = _fwd(logits_shard, targets, label_smoothing, axis_name)
    return loss


def _stats(logits_shard, targets, axis_name):
    x = logits_shard.astype(jnp.float32)
    per = x.shape[-1]
    start = jax.lax.axis_index(axis_name) * per
    local_max = jnp.max(x, axis=-1)
    gmax = jax.lax.pmax(local_max, axis_name)
    e = jnp.exp(x - gmax[..., None])
    gsum = jax.lax.psum(jnp.sum(e, axis=-1), axis_name)
    # target-logit mask trick
    local_t = targets - start
    in_shard = (local_t >= 0) & (local_t < per)
    local_t = jnp.clip(local_t, 0, per - 1)
    tgt = jnp.take_along_axis(x, local_t[..., None], axis=-1)[..., 0]
    tgt = jax.lax.psum(jnp.where(in_shard, tgt, 0.0), axis_name)
    return x, gmax, gsum, tgt, in_shard, local_t, start, per


def _fwd(logits_shard, targets, label_smoothing, axis_name):
    x, gmax, gsum, tgt, in_shard, local_t, start, per = _stats(
        logits_shard, targets, axis_name)
    lse = gmax + jnp.log(gsum)
    loss = lse - tgt
    if label_smoothing:
        vocab = per * jax.lax.axis_size(axis_name)
        mean_x = jax.lax.psum(jnp.sum(x, axis=-1), axis_name) / vocab
        loss = ((1.0 - label_smoothing) * loss
                + label_smoothing * (lse - mean_x))
    return loss, (logits_shard, targets, gmax, gsum)


def _bwd(label_smoothing, axis_name, res, dloss):
    logits_shard, targets, gmax, gsum = res
    x = logits_shard.astype(jnp.float32)
    per = x.shape[-1]
    start = jax.lax.axis_index(axis_name) * per
    p = jnp.exp(x - gmax[..., None]) / gsum[..., None]
    local_t = targets - start
    in_shard = (local_t >= 0) & (local_t < per)
    onehot = ((jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
               == jnp.clip(local_t, 0, per - 1)[..., None])
              & in_shard[..., None])
    grad = p - (1.0 - label_smoothing) * onehot
    if label_smoothing:
        vocab = per * jax.lax.axis_size(axis_name)
        grad = grad - label_smoothing / vocab
    grad = grad * dloss[..., None]
    return grad.astype(logits_shard.dtype), None


vocab_parallel_cross_entropy.defvjp(
    lambda lg, t, ls, ax: _fwd(lg, t, ls, ax),
    _bwd)
