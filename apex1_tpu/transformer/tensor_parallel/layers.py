"""Tensor-parallel layers — reference
``apex/transformer/tensor_parallel/layers.py :: ColumnParallelLinear,
RowParallelLinear, VocabParallelEmbedding``.

Two usage modes, matching SURVEY §7's design stance:

1. **GSPMD (default, TPU-idiomatic)** — flax modules create FULL-size params
   carrying ``nn.with_partitioning`` metadata (column weight sharded on the
   tp axis along out-features, row weight along in-features, embedding along
   vocab). Under ``pjit`` over a mesh, XLA inserts exactly the collectives
   the reference codes by hand (identity/all-reduce duals). Sequence
   parallelism = activation sharding constraints along the seq dim
   (``sequence_parallel_enabled``), reproducing the all-gather /
   reduce-scatter placement of Megatron SP.

2. **Explicit shard_map** — the functional forms (`column_parallel_linear`,
   `row_parallel_linear`, `vocab_parallel_embedding`) take LOCAL shards and
   use the `mappings` collectives, for schedule-controlled blocks and for
   the parity tests (≙ ``test_layers.py``).

``gradient_accumulation_fusion`` (reference ☢#27 ``wgrad_gemm_accum_fp32``)
needs no code: XLA accumulates wgrads in fp32 when params are fp32 masters
(the matmul's preferred_element_type) and fuses the accumulation — decision
documented here per the component inventory.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex1_tpu.core.mesh import AXIS_TP
from apex1_tpu.transformer.tensor_parallel import mappings as mp


def _maybe_constrain(x, spec):
    """with_sharding_constraint if a mesh context is active."""
    try:
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.PartitionSpec(*spec))
    except (ValueError, RuntimeError):
        return x  # no mesh context (single-device tests)


# ---------------------------------------------------------------------------
# GSPMD flax modules
# ---------------------------------------------------------------------------

class ColumnParallelLinear(nn.Module):
    """Y = XW + b with W column-sharded: (in, out/tp) per rank.

    ``gather_output=True`` replicates Y (reference default True; Megatron
    uses False to feed RowParallelLinear directly).
    """

    features: int
    use_bias: bool = True
    gather_output: bool = False
    sequence_parallel_enabled: bool = False
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    tp_axis: str = AXIS_TP

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (None, self.tp_axis)),
            (in_features, self.features), self.param_dtype)
        if self.sequence_parallel_enabled:
            # activations arrive seq-sharded; all-gather happens via the
            # sharding constraint change (XLA inserts it)
            x = _maybe_constrain(x, (None,) * (x.ndim - 1) + (None,))
        y = jnp.dot(x, kernel.astype(self.dtype),
                    preferred_element_type=jnp.float32).astype(self.dtype)
        if self.use_bias:
            bias = self.param(
                "bias", nn.with_partitioning(nn.initializers.zeros,
                                             (self.tp_axis,)),
                (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        if self.gather_output:
            y = _maybe_constrain(y, (None,) * y.ndim)
        else:
            y = _maybe_constrain(y, (None,) * (y.ndim - 1) + (self.tp_axis,))
        return y


class RowParallelLinear(nn.Module):
    """Y = XW + b with W row-sharded: (in/tp, out) per rank; the partial
    products all-reduce (or reduce-scatter along seq under SP). Bias is
    added once, after the reduction (reference semantics)."""

    features: int
    use_bias: bool = True
    input_is_parallel: bool = True
    sequence_parallel_enabled: bool = False
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    tp_axis: str = AXIS_TP

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (self.tp_axis, None)),
            (in_features, self.features), self.param_dtype)
        y = jnp.dot(x, kernel.astype(self.dtype),
                    preferred_element_type=jnp.float32).astype(self.dtype)
        if self.sequence_parallel_enabled:
            # output sharded along seq: XLA lowers to reduce-scatter
            y = _maybe_constrain(
                y, (None,) * (y.ndim - 2) + (self.tp_axis, None))
        else:
            y = _maybe_constrain(y, (None,) * y.ndim)
        if self.use_bias:
            bias = self.param("bias",
                              nn.with_partitioning(nn.initializers.zeros,
                                                   (None,)),
                              (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        return y


class VocabParallelEmbedding(nn.Module):
    """Embedding table sharded along vocab; lookup of out-of-shard tokens
    contributes zero and the partial results all-reduce (GSPMD: gather on a
    vocab-sharded table lowers to the same masked-lookup + psum)."""

    num_embeddings: int
    features: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    embedding_init: Callable = nn.initializers.normal(0.02)
    tp_axis: str = AXIS_TP

    @nn.compact
    def __call__(self, tokens):
        table = self.param(
            "embedding",
            nn.with_partitioning(self.embedding_init, (self.tp_axis, None)),
            (self.num_embeddings, self.features), self.param_dtype)
        y = jnp.take(table, tokens, axis=0).astype(self.dtype)
        return _maybe_constrain(y, (None,) * (tokens.ndim + 1))


# ---------------------------------------------------------------------------
# explicit shard_map functional forms
# ---------------------------------------------------------------------------

def column_parallel_linear(x, kernel_shard, bias_shard=None, *,
                           gather_output=False,
                           sequence_parallel_enabled=False,
                           axis_name=AXIS_TP, overlap=False,
                           fused=False):
    """x: replicated (or seq-sharded under SP); kernel_shard: (in, out/tp).

    Reference fwd: ``copy_to_tensor_model_parallel_region`` (identity fwd /
    psum bwd) then local matmul; under SP, all-gather along seq instead.

    ``overlap`` (opt-in, sequence-parallel path only): decompose the
    seq all-gather into the chunk-pipelined
    `mappings.all_gather_matmul` ring so each ICI transfer hides behind
    a partial dot (fwd and bwd). Off by default — the legacy monolithic
    collective path is bit-for-bit untouched when ``overlap=False``.

    ``fused`` (opt-in, SP path only, exclusive with ``overlap``): the
    fused comm-kernel form — the same chunk-pipelined ring with each
    per-chunk dot running in the `ops.fused_collective._chunk_matmul`
    Pallas kernel (bitwise the ``overlap=True`` numbers on the CPU
    mesh; see docs/parallel.md "Fused comm-kernels").
    """
    if overlap and fused:
        raise ValueError("overlap= and fused= are exclusive: fused IS "
                         "the overlapped ring with the dot in a Pallas "
                         "kernel — pick one")
    if sequence_parallel_enabled and fused:
        from apex1_tpu.ops.fused_collective import fused_all_gather_matmul
        y = fused_all_gather_matmul(x, kernel_shard, axis_name, 0)
        y = y.astype(x.dtype)
    elif sequence_parallel_enabled and overlap:
        y = mp.all_gather_matmul(x, kernel_shard, axis_name, 0)
        y = y.astype(x.dtype)
    else:
        if sequence_parallel_enabled:
            x = mp.gather_from_sequence_parallel_region(
                x, axis_name, 0, True)
        else:
            x = mp.copy_to_tensor_model_parallel_region(x, axis_name)
        y = jnp.dot(x, kernel_shard, preferred_element_type=jnp.float32)
        y = y.astype(x.dtype)
    if bias_shard is not None:
        y = y + bias_shard
    if gather_output:
        y = mp.gather_from_tensor_model_parallel_region(y, axis_name)
    return y


def row_parallel_linear(x_parallel, kernel_shard, bias=None, *,
                        input_is_parallel=True,
                        sequence_parallel_enabled=False,
                        axis_name=AXIS_TP, overlap=False, fused=False):
    """x_parallel: (..., in/tp); kernel_shard: (in/tp, out).

    ``overlap`` (opt-in, sequence-parallel path only): decompose the
    seq reduce-scatter into the chunk-pipelined
    `mappings.matmul_reduce_scatter` ring (transfers hidden behind the
    per-chunk partial dots, fwd and bwd). Off by default — legacy path
    bit-for-bit untouched when ``overlap=False``.

    ``fused`` (opt-in, SP path only, exclusive with ``overlap``): the
    fused comm-kernel reduce-scatter
    (`ops.fused_collective.fused_matmul_reduce_scatter`) — the PR 4
    travelling-accumulator ring with the per-chunk dot in a Pallas
    kernel; bitwise the ``overlap=True`` numbers on the CPU mesh.
    """
    if overlap and fused:
        raise ValueError("overlap= and fused= are exclusive: fused IS "
                         "the overlapped ring with the dot in a Pallas "
                         "kernel — pick one")
    if not input_is_parallel:
        x_parallel = mp.scatter_to_tensor_model_parallel_region(
            x_parallel, axis_name)
    if sequence_parallel_enabled and fused:
        from apex1_tpu.ops.fused_collective import (
            fused_matmul_reduce_scatter)
        y = fused_matmul_reduce_scatter(x_parallel, kernel_shard,
                                        axis_name, 0)
        y = y.astype(x_parallel.dtype)
    elif sequence_parallel_enabled and overlap:
        y = mp.matmul_reduce_scatter(x_parallel, kernel_shard,
                                     axis_name, 0)
        y = y.astype(x_parallel.dtype)
    else:
        y = jnp.dot(x_parallel, kernel_shard,
                    preferred_element_type=jnp.float32)
        y = y.astype(x_parallel.dtype)
        if sequence_parallel_enabled:
            y = mp.reduce_scatter_to_sequence_parallel_region(y, axis_name,
                                                              0)
        else:
            y = mp.reduce_from_tensor_model_parallel_region(y, axis_name)
    if bias is not None:
        y = y + bias
    return y


def vocab_parallel_embedding(tokens, table_shard, *, axis_name=AXIS_TP):
    """table_shard: (vocab/tp, features) holding rows
    [rank·V/tp, (rank+1)·V/tp). Out-of-shard tokens are masked to row 0 and
    zeroed, partials psum — the reference's masked-lookup trick."""
    per = table_shard.shape[0]
    start = jax.lax.axis_index(axis_name) * per
    local = tokens - start
    in_shard = (local >= 0) & (local < per)
    local = jnp.clip(local, 0, per - 1)
    y = jnp.take(table_shard, local, axis=0)
    y = jnp.where(in_shard[..., None], y, 0.0)
    # custom-VJP reduce (all-reduce fwd, identity bwd), NOT raw psum: raw
    # psum transposes to psum, which under grad-inside-shard_map would
    # scale the table cotangent by tp (each rank seeds the replicated
    # output); identity-bwd routes each row's cotangent to the one rank
    # whose mask kept it — exact under both grad conventions
    return mp.reduce_from_tensor_model_parallel_region(y, axis_name)


def set_tensor_model_parallel_attributes(spec_tree):
    """Reference tags params with ``tensor_model_parallel`` attributes; the
    JAX equivalent information is the PartitionSpec pytree — returned
    untouched (exists for porting-surface parity)."""
    return spec_tree
