"""Plan emission — the winner as an EXECUTABLE spec.

A plan is a plain JSON document (schema ``apex1-plan-v1``) carrying
everything a consumer needs to run the chosen layout without asking
the planner anything else:

- ``mesh``: the five axis degrees for `core.mesh.make_mesh`;
- ``partition_rules``: regex -> PartitionSpec rules over flattened
  param paths (the SNIPPETS.md [2] ``match_partition_rules`` pattern),
  consumed through `parallel.specs.specs_from_rules` — pinned by test
  to reproduce `models.llama_3d.chunk_param_specs` /
  ``shared_param_specs`` leaf-for-leaf on the CPU mesh;
- ``schedule``: microbatch count/size, chunks, scan-vs-1f1b;
- ``kernel_flags``: the SP-boundary schedule (``overlap=`` vs
  ``fused=`` — PR 9's knobs) each consumer should flip;
- ``zero``: whether (and over which axis) the optimizer state shards,
  via `parallel.distributed_optimizer.shard_opt_state_specs`;
- ``predicted`` / ``memory`` / ``search``: the pricing evidence, so a
  plan is auditable after the fact.

DETERMINISM CONTRACT: `plan_json` is byte-identical for identical
inputs — sorted keys, no timestamps, no environment probes. The only
external input is the banked ``calibration.json``, whose identity
rides in ``provenance`` (pinned by tests/test_planner.py).

Serialization of a PartitionSpec entry: ``None`` -> null, an axis
name -> string, a multi-axis dim -> list of strings.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from apex1_tpu.planner.layouts import Layout, ModelShape

PLAN_SCHEMA = "apex1-plan-v1"


# -- partition rules -------------------------------------------------------

def spec_to_json(entries):
    return [list(e) if isinstance(e, (tuple, list)) else e
            for e in entries]


def spec_from_json(entries):
    from jax.sharding import PartitionSpec as P

    return P(*[tuple(e) if isinstance(e, list) else e
               for e in entries])


def partition_rules(moe: bool) -> list:
    """Regex -> spec-json rules for the llama_3d stacked param tree
    (paths ``chunk/<leaf>`` / ``shared/<leaf>``), first match wins:
    col-parallel stacks shard their last dim over tp,
    row-parallel their second-to-last, expert stacks over ep, norms
    and router replicated beyond the pp stage axis, embedding/head
    rows over tp. The stacked chunk leaves carry the
    (chunk, pp, layer) prefix — hence the leading (None, pp, None)."""
    rules = [
        [r"chunk/(attn_norm|mlp_norm)$", [None, "pp", None, None]],
    ]
    if moe:
        rules += [
            [r"chunk/wg$", [None, "pp", None, None, None]],
            [r"chunk/(w_moe1|w_moe2)$",
             [None, "pp", None, "ep", None, None]],
        ]
    rules += [
        [r"chunk/(wq|wk|wv|w_gate|w_up)$",
         [None, "pp", None, None, "tp"]],
        [r"chunk/(wo|w_down)$", [None, "pp", None, "tp", None]],
        [r"shared/(emb|head)$", ["tp", None]],
        [r"shared/final_norm$", []],
    ]
    return rules


def rules_to_specs(rules):
    """((regex, PartitionSpec), ...) ready for
    `parallel.specs.specs_from_rules` (lazy jax import — the plan
    itself never needs jax)."""
    return tuple((pat, spec_from_json(spec)) for pat, spec in rules)


def plan_param_specs(plan: dict, params):
    """PartitionSpec tree for a param tree, from the PLAN's rules —
    the consumer-side path (llama_3d --plan auto verifies this tree
    against the model's own hand-written specs before training)."""
    from jax.sharding import PartitionSpec as P

    from apex1_tpu.parallel.specs import specs_from_rules

    return specs_from_rules(
        params, rules_to_specs(plan["partition_rules"]["rules"]),
        default=spec_from_json(plan["partition_rules"]["default"]))


# -- plan document ---------------------------------------------------------

def build_plan(shape: ModelShape, layout: Layout, price: dict,
               mem: dict, *, generation: str, search: dict,
               provenance: Optional[dict] = None) -> dict:
    gib = 2.0 ** 30
    return {
        "schema": PLAN_SCHEMA,
        "generation": generation,
        "n_devices": layout.n_devices,
        "model": dataclasses.asdict(shape),
        "mesh": {"dp": layout.dp, "pp": layout.pp, "cp": layout.cp,
                 "ep": layout.ep, "tp": layout.tp},
        "schedule": {"kind": layout.schedule,
                     "num_microbatches": layout.num_microbatches,
                     "microbatch_size": layout.microbatch_size,
                     "num_chunks": layout.num_chunks},
        "kernel_flags": {"sp_boundary": layout.sp_mode},
        "zero": {"enabled": layout.zero, "axis": "dp",
                 "consumer": "parallel.distributed_optimizer."
                             "shard_opt_state_specs"},
        "partition_rules": {"rules": partition_rules(shape.moe),
                            "default": []},
        "predicted": price,
        "memory": {k: round(v / gib, 4) if k != "fits" else v
                   for k, v in mem.items()},
        "search": search,
        "provenance": provenance or {},
    }


def plan_json(plan: dict) -> str:
    """THE serialization — sorted keys, fixed indent, trailing
    newline. Byte-identical for identical plans (the determinism
    pin)."""
    return json.dumps(plan, indent=1, sort_keys=True) + "\n"


def save_plan(plan: dict, path: str) -> str:
    from apex1_tpu.resilience.manifest import atomic_write_text

    atomic_write_text(path, plan_json(plan))
    return path


def load_plan(path: str) -> dict:
    """Parse + schema-check a banked plan. Raises ValueError (never a
    raw traceback from a foreign file) on anything but a v1 plan."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise ValueError(f"plan file unreadable: {path}: {e}") from e
    except json.JSONDecodeError as e:
        raise ValueError(f"plan file is not JSON: {path}: {e}") from e
    if not isinstance(doc, dict) or doc.get("schema") != PLAN_SCHEMA:
        raise ValueError(
            f"not an {PLAN_SCHEMA} plan: {path} "
            f"(schema={doc.get('schema') if isinstance(doc, dict) else None!r})")
    return doc


#: the ModelShape dims a replayed plan must agree on before its
#: schedule/rules may drive a model (global_batch deliberately
#: excluded: the plan's schedule IS the batch authority on replay)
PLAN_MODEL_KEYS = ("num_layers", "hidden_size", "ffn_size", "seq_len",
                   "vocab_size", "num_heads", "num_kv_heads",
                   "num_experts", "moe_top_k")


def check_plan_model(plan: dict, shape: ModelShape) -> list:
    """Mismatches between a plan's banked model dims and the model a
    consumer is about to drive with it — the ONE validation both
    ``examples/llama_3d.py --plan`` and ``bench.py --config llama_3d
    --plan`` apply (empty list = safe to consume)."""
    pm = plan.get("model", {})
    return [f"{k}: plan={pm.get(k)} model={getattr(shape, k)}"
            for k in PLAN_MODEL_KEYS
            if pm.get(k) != getattr(shape, k)]


#: the plan fields that define LAYOUT IDENTITY — two checkpoints are
#: layout-compatible (restorable into each other's state without a
#: reshard) iff their plan_spec dicts are equal. Pricing/provenance
#: fields are deliberately excluded: a re-search against a newer
#: calibration table that lands on the same layout is the SAME spec.
PLAN_SPEC_KEYS = ("schema", "n_devices", "mesh", "schedule", "zero",
                  "model")


def plan_spec(plan: dict) -> dict:
    """The layout-identity subset of a plan document (see
    `PLAN_SPEC_KEYS`) — what `resilience.ResilientCheckpointer` banks
    compares, and what `resilience.elastic_resume` checks to decide
    "same layout, plain resume" vs "re-plan + reshard"."""
    out = {}
    for k in PLAN_SPEC_KEYS:
        v = plan.get(k)
        out[k] = dict(v) if isinstance(v, dict) else v
    z = out.get("zero")
    if isinstance(z, dict):
        # the consumer pointer is documentation, not identity
        out["zero"] = {"enabled": bool(z.get("enabled")),
                       "axis": z.get("axis")}
    return out


def model_shape_from_plan(plan: dict) -> ModelShape:
    """Round-trip the banked model dims back into a `ModelShape` — the
    input `search.make_plan` needs to re-plan the SAME model for a
    different chip count (elastic resume reads the checkpoint's plan
    meta, never the command line, for the model)."""
    pm = dict(plan["model"])
    fields = {f.name for f in dataclasses.fields(ModelShape)}
    unknown = set(pm) - fields
    if unknown or not set(pm) >= {"name", "num_layers"}:
        raise ValueError(
            f"plan model dims do not round-trip into ModelShape "
            f"(unknown keys {sorted(unknown)})")
    return ModelShape(**pm)


def layout_from_plan(plan: dict) -> Layout:
    m, s = plan["mesh"], plan["schedule"]
    return Layout(dp=m["dp"], pp=m["pp"], cp=m["cp"], ep=m["ep"],
                  tp=m["tp"],
                  num_microbatches=s["num_microbatches"],
                  microbatch_size=s["microbatch_size"],
                  num_chunks=s["num_chunks"], schedule=s["kind"],
                  zero=plan["zero"]["enabled"],
                  sp_mode=plan["kernel_flags"]["sp_boundary"])


def llama3d_config_from_plan(plan: dict, model_cfg,
                             learning_rate: float = 1e-4,
                             ignore_zero: bool = False):
    """The plan as a runnable `models.llama_3d.Llama3DConfig` — the
    bridge `examples/llama_3d.py --plan` and `bench.py --config
    llama_3d` drive end-to-end. ``model_cfg`` is the LlamaConfig the
    plan's ModelShape was derived from (the plan carries dims, not
    weights-level config like the precision policy).

    A ``zero``-enabled plan is REFUSED by default: its HBM fit
    verdict divided the optimizer state by dp, and Llama3DConfig has
    no ZeRO wiring — executing it unsharded can OOM where the plan
    said "fits". Pass ``ignore_zero=True`` only when the consumer has
    stated it runs the unsharded optimizer anyway (and has the
    memory). The ``kernel_flags.sp_boundary`` knob is advisory here
    too: llama_3d's stage runs the default mappings; the flag exists
    for consumers that flip ``overlap=``/``fused=``."""
    from apex1_tpu.models.llama_3d import Llama3DConfig

    if plan.get("zero", {}).get("enabled") and not ignore_zero:
        raise ValueError(
            "plan has zero (ZeRO-1 optimizer sharding) enabled — its "
            "HBM fit assumed opt-state/dp, which Llama3DConfig does "
            "not implement; re-plan with allow_zero=False, or pass "
            "ignore_zero=True if the unsharded optimizer provably "
            "fits (consumer: parallel.distributed_optimizer)")
    lay = layout_from_plan(plan)
    moe = bool(plan["model"].get("num_experts", 0))
    return Llama3DConfig(
        model=model_cfg, dp=lay.dp, pp=lay.pp, tp=lay.tp, cp=lay.cp,
        ep=lay.ep, moe=moe, num_chunks=lay.num_chunks,
        num_microbatches=lay.num_microbatches,
        microbatch_size=lay.microbatch_size,
        learning_rate=learning_rate, schedule=lay.schedule)
