"""apex1_tpu.planner — the calibration-driven auto-parallel planner.

ROADMAP item 1 (AMP, arXiv 2210.07297; ZeRO axis from arXiv
2004.13336): instead of hand-picking dp x pp x cp x ep x tp, SEARCH
it — enumerate the legal layouts for a model on a chip topology
(`layouts`), prune by the analytic per-chip HBM model (`memory`),
price each survivor with the repo's own roofline + comms models
corrected by the banked silicon calibration (`cost` over
`apex1_tpu.perf_model` + `obs.calibrate`), and emit the winner as an
executable plan document (`emit`): mesh axes, regex partition rules
feeding `parallel.specs.specs_from_rules`, microbatch schedule, and
the SP-boundary kernel flags.

The repo's first subsystem that CHOOSES configurations instead of
measuring ones a human chose. Consumers: ``examples/llama_3d.py
--plan auto``, ``bench.py --config llama_3d``,
``tools/bench_planner_ab.py`` (the hardware A/B), and
``tools/aot_check.py``'s planner gate (AOT HBM truth for the pick).

No module under this package imports jax at module level — the whole
legality / memory / pricing path runs under a ``tools/lint.py``-style
stub parent with no jax installed at all; only plan CONSUMPTION
(`emit.plan_param_specs`, `emit.llama3d_config_from_plan`,
`memory.aot_memory_analysis`) reaches jax, lazily. CLI: ``python -m
apex1_tpu.planner`` (--smoke is the check_all gate). Contracts and
caveats: docs/planner.md.
"""

from apex1_tpu.planner.cost import (calibration_factor, price_layout,
                                    step_flops)
from apex1_tpu.planner.emit import (PLAN_SCHEMA, PLAN_SPEC_KEYS,
                                    build_plan, check_plan_model,
                                    layout_from_plan,
                                    llama3d_config_from_plan, load_plan,
                                    model_shape_from_plan,
                                    partition_rules, plan_json,
                                    plan_param_specs, plan_spec,
                                    rules_to_specs, save_plan)
from apex1_tpu.planner.layouts import (BANKED_SHAPES, SP_MODES, Layout,
                                       ModelShape, Violation,
                                       check_layout, enumerate_layouts)
from apex1_tpu.planner.memory import (fit_check, hbm_breakdown,
                                      params_per_device)
from apex1_tpu.planner.search import (PlanError, make_plan,
                                      plan_for_layout, search_layouts)

__all__ = [
    "BANKED_SHAPES", "Layout", "ModelShape", "PLAN_SCHEMA",
    "PLAN_SPEC_KEYS",
    "PlanError", "SP_MODES", "Violation", "build_plan",
    "calibration_factor", "check_layout", "check_plan_model",
    "enumerate_layouts",
    "fit_check", "hbm_breakdown", "layout_from_plan",
    "llama3d_config_from_plan", "load_plan", "make_plan",
    "model_shape_from_plan",
    "params_per_device", "partition_rules", "plan_for_layout",
    "plan_json",
    "plan_param_specs", "plan_spec", "price_layout", "rules_to_specs",
    "save_plan",
    "search_layouts", "step_flops",
]
