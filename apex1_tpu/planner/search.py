"""The search loop: enumerate -> pre-filter -> price -> pick -> emit.

`make_plan` is the planner's one front door (everything else is its
machinery): given a ModelShape and a chip count it returns the plan
document for the cheapest CALIBRATED layout, deterministically — the
enumeration order is fixed (`layouts.enumerate_layouts`), prices are
pure functions of (inputs, banked calibration.json), and ties break on
`Layout.sort_key`. Same inputs, byte-identical `emit.plan_json`.

Failure is loud and sized: no legal layout raises :class:`PlanError`
naming the violated rules of the nearest miss; every-layout-over-HBM
raises with the SMALLEST over-budget sizing message (so the error
tells you how far from fitting the model is, not just "no").
"""

from __future__ import annotations

from typing import Optional

from apex1_tpu.planner import cost, emit, memory
from apex1_tpu.planner.layouts import (Layout, ModelShape, check_layout,
                                       enumerate_layouts)


class PlanError(RuntimeError):
    """No plan exists for the request — message carries the why."""


def search_layouts(shape: ModelShape, n_devices: int, *,
                   generation: Optional[str] = None,
                   results_dir: Optional[str] = None,
                   use_calibration: bool = True,
                   **enum_kw) -> dict:
    """Full ranked search. Returns ``{"ranked": [(price, layout)...],
    "n_enumerated": int, "hbm_rejected": [msg...]}`` with ``ranked``
    sorted cheapest-calibrated-first."""
    gen = generation or "v5e"
    legal = list(enumerate_layouts(shape, n_devices, **enum_kw))
    if not legal:
        # name WHY: re-check the all-ones layout (and the requested
        # product) so the error carries rules, not a shrug
        probe = Layout(dp=n_devices,
                       num_microbatches=max(1, shape.global_batch
                                            // max(1, n_devices)))
        why = "; ".join(str(v) for v in
                        check_layout(shape, probe, n_devices)) \
            or "no axis factorization satisfies the legality rules"
        raise PlanError(
            f"no legal (dp,pp,cp,ep,tp) layout for "
            f"{shape.name} on {n_devices} device(s): {why}")
    fitting, rejected = [], []
    for lay in legal:
        msg = memory.fit_check(shape, lay, gen)
        if msg is None:
            fitting.append(lay)
        else:
            rejected.append(msg)
    if not fitting:
        # the closest miss (smallest total) is the actionable sizing
        closest = min(
            legal, key=lambda l: memory.hbm_breakdown(shape, l,
                                                      gen)["total"])
        raise PlanError(
            f"every legal layout for {shape.name} on {n_devices} "
            f"device(s) is over the HBM budget; closest: "
            f"{memory.fit_check(shape, closest, gen)}")
    # load the banked calibration ONCE per search: the step factor is
    # a property of the shape, the fused-kernel factor of
    # (tp>1, fused) — both constant across candidates; re-reading
    # calibration.json per layout would be 2N file parses for nothing
    cal = (cost.calibration_factor(shape, results_dir)
           if use_calibration else None)
    kf_fused = cost._sp_kernel_factor(
        Layout(tp=2, sp_mode="fused", num_microbatches=1),
        results_dir)
    # the non-fused fallback comes from the SAME function (tp=1 takes
    # the analytic branch) so both pricing paths report identical
    # provenance for the identical situation
    kf_none = cost._sp_kernel_factor(Layout(num_microbatches=1),
                                     results_dir)
    priced = [(cost.price_layout(
        shape, lay, generation=gen, results_dir=results_dir,
        use_calibration=use_calibration, calibration=cal,
        sp_kernel=(kf_fused if (lay.tp > 1 and lay.sp_mode == "fused")
                   else kf_none)), lay)
              for lay in fitting]
    priced.sort(key=lambda pl: (pl[0]["calibrated_step_ms"],
                                pl[1].sort_key()))
    return {"ranked": priced, "n_enumerated": len(legal),
            "hbm_rejected": rejected}


def make_plan(shape: ModelShape, n_devices: int, *,
              generation: Optional[str] = None,
              results_dir: Optional[str] = None,
              use_calibration: bool = True,
              top_k: int = 5, **enum_kw) -> dict:
    """Search and emit the winning plan document (`emit.build_plan`).
    ``enum_kw`` forwards to `layouts.enumerate_layouts` (allow_cp /
    allow_ep / allow_zero / sp_modes / microbatch_size)."""
    gen = generation or "v5e"
    res = search_layouts(shape, n_devices, generation=gen,
                         results_dir=results_dir,
                         use_calibration=use_calibration, **enum_kw)
    price, lay = res["ranked"][0]
    mem = memory.hbm_breakdown(shape, lay, gen)
    ranked_top = [
        {"mesh": l.mesh_str(),
         "calibrated_step_ms": round(p["calibrated_step_ms"], 4),
         "step_ms": round(p["step_ms"], 4)}
        for p, l in res["ranked"][:top_k]]
    provenance = _calibration_provenance(results_dir)
    return emit.build_plan(
        shape, lay, price, mem, generation=gen,
        search={"n_enumerated": res["n_enumerated"],
                "n_hbm_rejected": len(res["hbm_rejected"]),
                "ranked_top": ranked_top},
        provenance=provenance)


def plan_for_layout(shape: ModelShape, layout: Layout, *,
                    generation: Optional[str] = None,
                    results_dir: Optional[str] = None,
                    use_calibration: bool = True) -> dict:
    """A full plan document for a STATED layout (no search): legality-
    checked, priced, and emitted exactly like a searched plan, with
    ``search.stated = True`` marking that nothing was enumerated.

    This is what makes hand-picked runs self-describing: a training
    loop driven by ``--dp 2 --pp 2 --tp 2`` can bank the same
    ``apex1-plan-v1`` spec in its checkpoints that ``--plan auto``
    would, so elastic resume (`resilience.elastic`) works from either.
    An illegal layout raises :class:`PlanError` naming the rules; the
    HBM verdict is recorded in ``memory`` but deliberately not
    enforced — a stated layout is the operator's claim, and the AOT
    gate stays the real guard."""
    gen = generation or "v5e"
    violations = check_layout(shape, layout)
    if violations:
        raise PlanError(
            f"stated layout {layout.mesh_str()} is illegal for "
            f"{shape.name}: "
            + "; ".join(str(v) for v in violations))
    price = cost.price_layout(shape, layout, generation=gen,
                              results_dir=results_dir,
                              use_calibration=use_calibration)
    mem = memory.hbm_breakdown(shape, layout, gen)
    return emit.build_plan(
        shape, layout, price, mem, generation=gen,
        search={"n_enumerated": 0, "n_hbm_rejected": 0,
                "ranked_top": [], "stated": True},
        provenance=_calibration_provenance(results_dir))


def _calibration_provenance(results_dir: Optional[str] = None) -> dict:
    """Identity of the calibration table the prices rode on — banked
    fields only (deterministic for a given file; no clock reads)."""
    from apex1_tpu.obs.calibrate import CAL_NAME, load_calibration

    doc = load_calibration(results_dir)
    if doc is None:
        return {"calibration_table": None}
    return {"calibration_table": CAL_NAME,
            "calibration_generated_unix": doc.get("generated_unix"),
            "calibration_n_pairs": doc.get("n_pairs"),
            "calibration_prediction_table": doc.get("prediction_table")}
