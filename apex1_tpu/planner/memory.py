"""Analytic per-device HBM model — the planner's jax-free pre-filter.

Two memory checks gate a candidate layout, at very different prices:

1. THIS module: a closed-form byte count over the layout's shards —
   microseconds per candidate, runs with no jax, prunes the search
   space before anything compiles. Like ``vmem_model`` it is a GATING
   model: coarse, monotone in the degrees, calibrated against the AOT
   history the repo has banked (the llama_longctx sizing episode:
   aot_check measured the 22-layer variant at 18.7 GiB on a 15.75 GiB
   v5e and the shipped 16-layer at ~14.4 GiB — this model prices them
   at ~18.3 and ~14.1, same verdicts; pinned in tests/test_planner.py).
2. :func:`aot_memory_analysis`: XLA's real AOT memory analysis of the
   lowered ``models.llama_3d.build_step`` executable through the
   compile-only topology client — the on-device truth, minutes per
   config, run for the WINNER only (`tools/aot_check.py`'s planner
   gate), never inside the search loop.

Accounting (fp32-master training, the repo's O2 recipe — fused Adam on
fp32 masters, bf16 compute):

- weights: 4 B/param on the device's shard (layer dense matmuls /tp,
  experts /ep, stack /pp; norms+router replicated over tp; emb/head
  /tp, pp-replicated on the embedding group);
- grads: 4 B/param, same shards;
- optimizer: 8 B/param (two Adam moments) — divided by dp when the
  layout's ``zero`` flag shards the update
  (`parallel.distributed_optimizer.shard_opt_state_specs`);
- activations: the remat/scan pipeline keeps (a) the microbatch
  boundary stack — M x (S/(cp*tp)) x mb x E, held in fp32 through the
  backward — and (b) one layer's recompute working set at the GATHERED
  sequence width (S/cp), bf16;
- data: the (M, S/cp, mb) int32 token + label shards.

A 256 MiB system reserve is subtracted from the capability row's
``hbm_bytes`` (16 GiB v5e advertises ~15.75 usable — the figure the
banked aot logs report).
"""

from __future__ import annotations

from typing import Optional

from apex1_tpu.planner.layouts import Layout, ModelShape

#: bytes held back from the spec-sheet HBM figure (runtime + framework
#: reserve — v5e's 16 GiB advertises ~15.75 usable in the AOT logs).
#: This is the ONLY margin the pre-filter applies: the analytic count
#: is compared straight against the usable budget, and the AOT gate
#: (aot_memory_analysis via tools/aot_check.py) is what protects the
#: winner from the model's coarseness — not a fudge factor here.
HBM_RESERVE_BYTES = 256 * 2**20


def budget_bytes(generation: Optional[str] = None) -> int:
    """Usable per-chip HBM for planning at a capability row."""
    from apex1_tpu.core.capability import get_capability

    cap = get_capability(generation or "v5e")
    return cap.hbm_bytes - HBM_RESERVE_BYTES


def param_counts(shape: ModelShape) -> dict:
    """Global parameter counts by sharding class."""
    E, F = shape.hidden_size, shape.ffn_size
    HD = shape.num_heads * shape.head_dim
    KD = shape.num_kv_heads * shape.head_dim
    attn = E * HD * 2 + E * KD * 2          # wq + wo, wk + wv
    if shape.moe:
        dense_mlp = 0
        router = E * shape.num_experts
        experts = shape.num_experts * 2 * E * F   # w_moe1 + w_moe2
    else:
        dense_mlp = 3 * E * F               # gate, up, down
        router = 0
        experts = 0
    norms = 2 * E
    shared = 2 * shape.vocab_size * E + E   # emb, head, final_norm
    return dict(
        layer_tp_sharded=attn + dense_mlp,  # col/row shards over tp
        layer_replicated=norms + router,    # tp-replicated
        layer_ep_sharded=experts,           # expert stacks over ep
        shared_tp_sharded=2 * shape.vocab_size * E,
        shared_replicated=E,
        total=(shape.num_layers
               * (attn + dense_mlp + norms + router + experts)
               + shared))


def params_per_device(shape: ModelShape, layout: Layout) -> float:
    c = param_counts(shape)
    per_layer = (c["layer_tp_sharded"] / layout.tp
                 + c["layer_replicated"]
                 + c["layer_ep_sharded"] / layout.ep)
    return (shape.num_layers / layout.pp * per_layer
            + c["shared_tp_sharded"] / layout.tp
            + c["shared_replicated"])


def hbm_breakdown(shape: ModelShape, layout: Layout,
                  generation: Optional[str] = None) -> dict:
    """Per-device HBM bytes by component, plus the budget verdict."""
    p_dev = params_per_device(shape, layout)
    weights = 4.0 * p_dev
    grads = 4.0 * p_dev
    opt = 8.0 * p_dev / (layout.dp if layout.zero else 1)

    S_sp = shape.seq_len // (layout.cp * layout.tp)   # SP-region rows
    S_cp = shape.seq_len // layout.cp                 # gathered rows
    mb = layout.microbatch_size
    M = layout.num_microbatches
    E, F = shape.hidden_size, shape.ffn_size
    F_eff = F * (shape.moe_top_k if shape.moe else 1)
    Hl = max(1, shape.num_heads // layout.tp)
    # boundary stack (fp32 through the backward) + one layer's
    # recompute working set at the gathered width: residual in/out +
    # qkv/attn io + mlp hidden
    acts = (M * S_sp * mb * E * 4.0
            + S_cp * mb * (4 * E + 2 * F_eff
                           + 4 * Hl * shape.head_dim) * 2.0)
    data = 2.0 * M * S_cp * mb * 4.0                  # tokens + labels
    total = weights + grads + opt + acts + data
    budget = budget_bytes(generation)
    return dict(weights=weights, grads=grads, opt=opt, acts=acts,
                data=data, total=total, budget=float(budget),
                fits=total <= budget)


def fit_check(shape: ModelShape, layout: Layout,
              generation: Optional[str] = None) -> Optional[str]:
    """None when the layout fits the per-chip budget; otherwise the
    rejection message WITH the sizing stated (the contract the tests
    pin — an over-budget config must say by how much and why)."""
    b = hbm_breakdown(shape, layout, generation)
    if b["fits"]:
        return None
    gib = 2.0 ** 30
    return (f"hbm-fit: needs {b['total'] / gib:.2f} GiB/chip > "
            f"{b['budget'] / gib:.2f} GiB usable "
            f"({generation or 'v5e'}) — weights "
            f"{b['weights'] / gib:.2f} + grads {b['grads'] / gib:.2f} "
            f"+ opt {b['opt'] / gib:.2f} + acts {b['acts'] / gib:.2f} "
            f"+ data {b['data'] / gib:.2f} GiB at layout "
            f"{layout.mesh_str()}")


def aot_memory_analysis(cfg, mesh):
    """The on-device truth this module approximates: lower the full 3D
    train step (``models.llama_3d.build_step`` + ``abstract_state``)
    for an AOT topology mesh and return XLA's memory analysis
    (``temp_size_in_bytes`` / ``argument_size_in_bytes``). Requires
    jax + the compile-only topology client — `tools/aot_check.py`'s
    planner gate is the caller; the search loop never is."""
    from apex1_tpu.models.llama_3d import abstract_state, build_step

    step, _, _, _ = build_step(cfg, mesh)
    state, data = abstract_state(cfg, mesh)
    return step.lower(state, data, data).compile().memory_analysis()
