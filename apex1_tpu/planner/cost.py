"""The layout cost engine — analytic step pricing, silicon-corrected.

Prices one (ModelShape, Layout) pair in milliseconds per optimizer
step, through exactly the machinery the repo already trusts:

- compute + HBM terms ride `apex1_tpu.perf_model.roofline` (the SAME
  function `tools/predict_perf.py` tables — the AMP-style planner of
  arXiv 2210.07297 is only as good as its cost model, and this repo's
  cost model is the one its bench history has already scored);
- attention flops come from `perf_model.flash_flops_bytes` with the
  shipped two-pass-backward factor, the LM-head CE from
  `perf_model.linear_xent_flops`;
- ICI terms come from `perf_model.sp_boundary_comms` (the Megatron-SP
  boundary at the layout's OWN shard shape, exposed per the layout's
  ``sp_mode`` — serial / overlap / fused, PR 9's kernel-selection
  dimension) and `perf_model.ring_attention_comms` (cp ring), plus
  ring all-reduce gradient sync over the data replicas
  (`perf_model.allreduce_bytes` — the same bytes whether plain dp or
  the ZeRO reduce-scatter/all-gather split);
- the pipeline bubble multiplies the whole step by (M + pp - 1) / M;
- CALIBRATION: the analytic time is multiplied by the banked
  TPU-fitted slowdown (`obs.calibrate.step_slowdown` for the shape's
  own bench config; else the geometric mean of every banked tpu step
  factor, labelled ``fleet-geomean``; else 1.0 labelled
  ``uncalibrated``). cpu-proxy factors are NEVER applied — the
  calibrate module's own contract. `kernel_slowdown` is consulted for
  the SP-boundary kernels (tpu-backed entries only, i.e. PR 9's A/B
  once a window banks it); today's cpu-swept tables return None and
  the term stays analytic.

What a calibrated price licenses (docs/planner.md spells this out):
RANKING layouts against each other and against the banked history —
not predicting wall-clock on unmeasured silicon to better than the
fitted residual spread (x1.35 on the banked corpus).
"""

from __future__ import annotations

import math
from typing import Optional

from apex1_tpu.perf_model import (allreduce_bytes, flash_flops_bytes,
                                  linear_xent_flops,
                                  ring_attention_comms, roofline,
                                  sp_boundary_comms)
from apex1_tpu.planner import memory
from apex1_tpu.planner.layouts import Layout, ModelShape

DTYPE_BYTES = 2   # bf16 compute


def step_flops(shape: ModelShape) -> dict:
    """Global fwd+bwd flops per optimizer step, by component.

    Dense matmuls count 2*M*N*K fwd and x3 for fwd+bwd (dX + dW);
    flash attention carries its own x4.5 two-pass-backward factor
    (`perf_model.flash_flops_bytes` docstring); the fused LM-head CE
    is the 6*T*E*V fwd+bwd total (`perf_model.linear_xent_flops`)."""
    E, F, V = shape.hidden_size, shape.ffn_size, shape.vocab_size
    HD = shape.num_heads * shape.head_dim
    KD = shape.num_kv_heads * shape.head_dim
    T = shape.tokens_per_step
    qkvo = 2.0 * T * (E * HD + 2 * E * KD + HD * E)
    if shape.moe:
        mlp = (2.0 * T * E * shape.num_experts          # router
               + shape.moe_top_k * 4.0 * T * E * F)     # w1 + w2
    else:
        mlp = 6.0 * T * E * F                           # gate, up, down
    linear = shape.num_layers * (qkvo + mlp) * 3.0      # fwd+bwd
    attn_f, _ = flash_flops_bytes(shape.global_batch, shape.num_heads,
                                  shape.num_kv_heads, shape.seq_len,
                                  shape.head_dim, causal=True,
                                  grad=True)
    attn = shape.num_layers * attn_f
    ce = float(linear_xent_flops(T, E, V))
    return dict(linear=linear, attn=attn, ce=ce,
                total=linear + attn + ce)


def _sp_exposed_bytes(shape: ModelShape, layout: Layout,
                      generation: str) -> float:
    """Per-device exposed ICI bytes from the Megatron-SP boundaries of
    ONE step: per layer 2 all-gathers + 2 reduce-scatters forward, the
    mirrored duals backward — each priced at the layout's shard shape
    and exposed per its sp_mode."""
    if layout.tp < 2:
        return 0.0
    rows = (shape.seq_len // layout.cp) * layout.microbatch_size
    E, F = shape.hidden_size, shape.ffn_size
    HD = shape.num_heads * shape.head_dim
    KD = shape.num_kv_heads * shape.head_dim
    key = f"exposed_{layout.sp_mode}"
    boundaries = (
        # (local K of the overlapped chunk dot, out width, acc bytes,
        #  hop width). AG boundaries hop the bf16 INPUT activation
        # (width E — constant in tp, the dot's output shard is not
        # what travels); RS boundaries hop the fp32 partial-result
        # accumulator (width = the output, hop_width None).
        # attn AG -> qkv col-parallel dot
        (E, (HD + 2 * KD) // layout.tp, DTYPE_BYTES, E),
        # attn RS after wo row-parallel dot
        (HD // layout.tp, E, 4, None),
        # mlp AG -> gate+up col-parallel dot
        (E, 2 * F // layout.tp, DTYPE_BYTES, E),
        # mlp RS after down row-parallel dot
        (F // layout.tp, E, 4, None),
    )
    per_layer = 0.0
    for local_k, out_w, acc, hop_w in boundaries:
        m = sp_boundary_comms(generation, layout.tp, rows=rows,
                              local_k=max(1, local_k),
                              out_width=max(1, out_w), acc_bytes=acc,
                              hop_width=hop_w)
        if m is None:
            return 0.0
        per_layer += m[key]
    layers_dev = shape.num_layers / layout.pp
    # backward mirrors every boundary through the dual collective
    return per_layer * 2.0 * layers_dev * layout.num_microbatches


def _cp_exposed_bytes(shape: ModelShape, layout: Layout,
                      generation: str) -> float:
    """Per-device exposed ICI bytes from the ring-attention cp axis
    (double-buffered schedule — the shipped default; only the per-hop
    residual the attend cannot cover is exposed)."""
    if layout.cp < 2:
        return 0.0
    m = ring_attention_comms(
        generation, layout.cp, B=layout.microbatch_size,
        Hq=max(1, shape.num_heads // layout.tp),
        Hkv=max(1, shape.num_kv_heads // layout.tp),
        S=shape.seq_len, D=shape.head_dim)
    if m is None:
        return 0.0
    per_layer = m["exp_f_overlap"] + m["exp_b_overlap"]
    return (per_layer * (shape.num_layers / layout.pp)
            * layout.num_microbatches)


def _dp_exposed_bytes(shape: ModelShape, layout: Layout) -> float:
    """Gradient-sync bytes per device: fp32 grads ring-all-reduced over
    the data replicas (dp x ep x cp). The ZeRO layout moves the same
    total as its reduce-scatter + updated-param all-gather
    (`perf_model.allreduce_bytes`)."""
    replicas = layout.dp * layout.ep * layout.cp
    grad_bytes = 4.0 * memory.params_per_device(shape, layout)
    return allreduce_bytes(grad_bytes, replicas)


def _pp_exposed_bytes(shape: ModelShape, layout: Layout) -> float:
    """Pipeline boundary p2p: one SP-sharded boundary activation per
    microbatch per stage boundary, forward + backward."""
    if layout.pp < 2:
        return 0.0
    act = (shape.seq_len // (layout.cp * layout.tp)
           * layout.microbatch_size * shape.hidden_size * DTYPE_BYTES)
    return (2.0 * layout.num_microbatches * act
            * (layout.pp - 1) / layout.pp)


def _hbm_bytes_per_device(shape: ModelShape, layout: Layout) -> float:
    """First-order HBM traffic per device per step: stage weights
    re-streamed per microbatch (fwd + 2x bwd), the optimizer's fp32
    read-modify-write, and the residual-stream activation traffic."""
    p_dev = memory.params_per_device(shape, layout)
    weight_stream = (p_dev * DTYPE_BYTES * 3.0
                     * layout.num_microbatches)
    opt_rw = 28.0 * p_dev   # m/v/master read+write + grad read
    tok_dev = (shape.tokens_per_step
               / (layout.dp * layout.ep * layout.cp))
    act_stream = (tok_dev * shape.hidden_size * DTYPE_BYTES
                  * (shape.num_layers / layout.pp) * 12.0 / layout.tp)
    return weight_stream + opt_rw + act_stream


def calibration_factor(shape: ModelShape,
                       results_dir: Optional[str] = None) -> dict:
    """The banked slowdown to apply to this shape's analytic price.

    Preference order: the shape's OWN tpu step factor
    (``step:<shape.name>``), else the fleet geometric mean of every
    banked tpu step factor (an unmeasured config inherits the fleet's
    typical roofline shortfall rather than raw optimism), else 1.0.
    The provenance string rides into the plan so a consumer can see
    WHICH correction priced it."""
    from apex1_tpu.obs.calibrate import load_calibration

    doc = load_calibration(results_dir)
    if doc is None:
        return dict(slowdown=1.0, source="uncalibrated "
                    "(no banked calibration.json)")
    f = doc.get("factors", {}).get(f"step:{shape.name}")
    if isinstance(f, dict) and isinstance(f.get("slowdown"),
                                          (int, float)) \
            and f["slowdown"] > 0:
        return dict(slowdown=float(f["slowdown"]),
                    source=f"step:{shape.name} (n={f.get('n')}, "
                           f"banked calibration.json)")
    steps = [v["slowdown"] for k, v in
             sorted(doc.get("factors", {}).items())
             if k.startswith("step:") and isinstance(v, dict)
             and isinstance(v.get("slowdown"), (int, float))
             and v["slowdown"] > 0]
    if steps:
        geo = math.exp(sum(math.log(s) for s in steps) / len(steps))
        return dict(slowdown=geo,
                    source=f"fleet-geomean over {len(steps)} banked "
                           f"tpu step factors")
    return dict(slowdown=1.0,
                source="uncalibrated (no tpu step factors banked)")


def _sp_kernel_factor(layout: Layout,
                      results_dir: Optional[str] = None) -> dict:
    """TPU-backed kernel slowdown for the SP-boundary schedule the
    layout selected — PR 9's A/B data once a hardware window banks it
    (`fused_comm_ab` in the tpu_watch queue feeds the tuning tables
    and calibration fit). Today's tables are cpu-swept, so
    `kernel_slowdown` (tpu-only by contract) returns None and the
    boundary term stays analytic — labelled as such."""
    from apex1_tpu.obs.calibrate import kernel_slowdown

    # only the fused schedule runs a Pallas kernel with its own banked
    # factor; the overlap/serial schedules are XLA ppermute + dots,
    # already covered by the step-level calibration
    f = (kernel_slowdown("fused_collective_matmul", results_dir)
         if (layout.tp > 1 and layout.sp_mode == "fused") else None)
    if isinstance(f, dict) and isinstance(f.get("slowdown"),
                                          (int, float)):
        return dict(slowdown=float(f["slowdown"]),
                    source="kernel:fused_collective_matmul (banked "
                           "tpu A/B)")
    return dict(slowdown=1.0, source="analytic (no tpu kernel factor "
                "banked for the SP boundary)")


def price_layout(shape: ModelShape, layout: Layout, *,
                 generation: Optional[str] = None,
                 results_dir: Optional[str] = None,
                 use_calibration: bool = True,
                 calibration: Optional[dict] = None,
                 sp_kernel: Optional[dict] = None) -> dict:
    """Milliseconds per optimizer step for one layout, with the full
    breakdown and calibration provenance. Deterministic: same inputs
    (and same banked calibration.json) -> identical floats.

    ``calibration`` / ``sp_kernel``: precomputed factor docs
    (`calibration_factor` / `_sp_kernel_factor` output). The step
    factor is a property of the SHAPE and the fused-kernel factor of
    (tp>1, sp_mode) — constant across one search — so
    `search_layouts` loads the banked table ONCE and passes them
    down instead of re-reading calibration.json per candidate."""
    from apex1_tpu.core.capability import get_capability

    gen = generation or "v5e"
    cap = get_capability(gen)
    fl = step_flops(shape)
    shard = layout.dp * layout.ep * layout.cp * layout.tp
    # per-device compute: an equal stage slice of the layer stack, plus
    # the LM-head CE which rides the LAST stage (the critical one)
    flops_dev = ((fl["linear"] + fl["attn"]) / (shard * layout.pp)
                 + fl["ce"] / shard)
    bytes_dev = _hbm_bytes_per_device(shape, layout)
    sp = _sp_exposed_bytes(shape, layout, gen)
    cp = _cp_exposed_bytes(shape, layout, gen)
    dp = _dp_exposed_bytes(shape, layout)
    pp = _pp_exposed_bytes(shape, layout)
    kf = (sp_kernel if sp_kernel is not None
          else _sp_kernel_factor(layout, results_dir))
    exposed = sp * kf["slowdown"] + cp + dp + pp
    t, bound, mfu = roofline(flops_dev, bytes_dev, cap,
                             ici_exposed_bytes=exposed)
    bubble = ((layout.num_microbatches + layout.pp - 1)
              / layout.num_microbatches)
    step_ms = t * bubble * 1e3
    cal = (dict(slowdown=1.0, source="calibration disabled")
           if not use_calibration
           else calibration if calibration is not None
           else calibration_factor(shape, results_dir))
    calibrated_ms = step_ms * cal["slowdown"]
    tok_rate = (shape.tokens_per_step / (calibrated_ms * 1e-3)
                / layout.n_devices) if calibrated_ms > 0 else 0.0
    return dict(
        step_ms=step_ms, calibrated_step_ms=calibrated_ms,
        tokens_per_sec_per_chip=tok_rate,
        bound=bound, mfu=mfu, bubble_factor=bubble,
        flops_per_device=flops_dev, hbm_bytes_per_device=bytes_dev,
        ici_exposed_bytes=dict(sp_boundary=sp, cp_ring=cp,
                               dp_gradsync=dp, pp_p2p=pp),
        calibration=cal, sp_kernel=kf, generation=gen)
