"""Layout search space: model shapes, parallel layouts, legality rules.

The FIRST questions a parallel config must answer are discrete and
jax-free: does dp x pp x cp x ep x tp cover the chips, do the TP shards
divide the heads and the vocab, do the pipeline stages balance, does
the microbatch schedule feed the pipeline. Every one of these rules is
today enforced somewhere ELSE — `models.llama_3d.Llama3DConfig`
raises them one at a time at construction, `shard_map` fails opaquely
on the rest — which is exactly how hand-picked configs burn hardware
windows. This module centralizes them as a *predicate over data*
(:func:`check_layout` returns the violated rules BY NAME) so the
enumerator, the examples' argument validation, and the tests all
consult one source of truth.

Everything here is stdlib-only: legality must be checkable before jax
initializes a backend (``examples/llama_3d.py`` validates argv and
exits loudly BEFORE ``force_virtual_cpu_devices``).

The five mesh axes mirror ``core.mesh.MESH_AXES`` (dp, pp, cp, ep,
tp; fsdp is expressed as the ``zero`` flag — ZeRO-1 optimizer-state
sharding over the dp axis via
``parallel.distributed_optimizer.shard_opt_state_specs``, the
2004.13336 axis). ``sp_mode`` is the kernel-selection dimension PR 9
created: which schedule runs each Megatron-SP boundary matmul
(``overlap=`` ppermute ring vs ``fused=`` Pallas form) — a planner
dimension because the two expose different ICI residuals
(`perf_model.sp_boundary_comms`).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional, Sequence

SP_MODES = ("serial", "overlap", "fused")


@dataclasses.dataclass(frozen=True)
class ModelShape:
    """The planner's jax-free view of a transformer training job —
    every number the legality rules and the cost/memory models need,
    and nothing that requires importing a model class."""

    name: str                  # calibration key: obs.calibrate
    #                            step factors are keyed "step:<name>"
    num_layers: int
    hidden_size: int
    ffn_size: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    vocab_size: int
    seq_len: int
    global_batch: int          # sequences per optimizer step (global)
    num_experts: int = 0       # 0 = dense FFN everywhere
    moe_top_k: int = 2

    @property
    def moe(self) -> bool:
        return self.num_experts > 0

    @property
    def tokens_per_step(self) -> int:
        return self.global_batch * self.seq_len

    @classmethod
    def from_llama(cls, cfg, *, global_batch: int,
                   name: str = "llama") -> "ModelShape":
        """Duck-typed bridge from a `models.llama.LlamaConfig`-shaped
        object (reads attributes only — keeps this module jax-free)."""
        experts = (int(cfg.num_experts)
                   if getattr(cfg, "moe_every", 0) else 0)
        return cls(name=name, num_layers=cfg.num_layers,
                   hidden_size=cfg.hidden_size, ffn_size=cfg.ffn_size,
                   num_heads=cfg.num_heads,
                   num_kv_heads=cfg.num_kv_heads,
                   head_dim=cfg.hidden_size // cfg.num_heads,
                   vocab_size=cfg.vocab_size, seq_len=cfg.max_seq_len,
                   global_batch=global_batch, num_experts=experts,
                   moe_top_k=getattr(cfg, "moe_top_k", 2))


#: The banked bench shapes the acceptance contract prices (ISSUE 12 /
#: ROADMAP item 1): names match the calibration keys in
#: perf_results/calibration.json (step:gpt2 1.89x, step:llama_longctx
#: 2.79x fitted from the round-5 silicon logs), dims match the exact
#: bench.py configs (`bench_gpt2` B=16 S=1024 on v5e; `bench_llama_longctx`
#: 16-layer 0.8B at 16k) and the 8B projection matches
#: `tools/aot_check.py --flagship`'s Llama-3-8B step (dp2 pp2 tp4,
#: M=4, mb=1 -> global batch 8).
BANKED_SHAPES = {
    "gpt2": ModelShape(
        name="gpt2", num_layers=12, hidden_size=768, ffn_size=3072,
        num_heads=12, num_kv_heads=12, head_dim=64, vocab_size=50432,
        seq_len=1024, global_batch=16),
    "llama_longctx": ModelShape(
        name="llama_longctx", num_layers=16, hidden_size=2048,
        ffn_size=5632, num_heads=32, num_kv_heads=4, head_dim=64,
        vocab_size=32000, seq_len=16384, global_batch=1),
    "llama8b": ModelShape(
        name="llama8b", num_layers=32, hidden_size=4096,
        ffn_size=14336, num_heads=32, num_kv_heads=8, head_dim=128,
        vocab_size=128256, seq_len=8192, global_batch=8),
}


@dataclasses.dataclass(frozen=True)
class Layout:
    """One point of the search space: the five mesh degrees + the
    schedule/kernel knobs the cost model prices."""

    dp: int = 1
    pp: int = 1
    cp: int = 1
    ep: int = 1
    tp: int = 1
    num_microbatches: int = 1
    microbatch_size: int = 1
    zero: bool = False         # ZeRO-1: opt state sharded over dp
    sp_mode: str = "overlap"   # SP-boundary schedule (SP_MODES)
    num_chunks: int = 1
    schedule: str = "scan"

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.cp * self.ep * self.tp

    def sort_key(self):
        """Deterministic total order — the tie-break rule for equal
        prices, so the same inputs always produce the same plan."""
        return (self.tp, self.pp, self.cp, self.ep, self.dp,
                self.num_microbatches, self.zero,
                SP_MODES.index(self.sp_mode))

    def mesh_str(self) -> str:
        parts = [f"dp={self.dp}", f"pp={self.pp}", f"cp={self.cp}",
                 f"ep={self.ep}", f"tp={self.tp}"]
        knobs = [f"M={self.num_microbatches}"]
        if self.zero:
            knobs.append("zero")
        if self.tp > 1:
            knobs.append(f"sp={self.sp_mode}")
        return " ".join(parts) + " (" + " ".join(knobs) + ")"


@dataclasses.dataclass(frozen=True)
class Violation:
    """One broken legality rule — ``rule`` is the stable machine name
    the tests and the examples' error messages key on."""

    rule: str
    message: str

    def __str__(self):
        return f"{self.rule}: {self.message}"


def check_layout(shape: ModelShape, layout: Layout,
                 n_devices: Optional[int] = None) -> list[Violation]:
    """Every legality rule the repo's 3D stack enforces (or assumes),
    evaluated together. Empty list = legal. The rule names are part of
    the contract (tests pin them; examples print them)."""
    v: list[Violation] = []
    add = v.append
    lay = layout

    if n_devices is not None and lay.n_devices != n_devices:
        add(Violation(
            "device-product",
            f"dp*pp*cp*ep*tp = {lay.n_devices} != {n_devices} devices"))
    for axis in ("dp", "pp", "cp", "ep", "tp"):
        if getattr(lay, axis) < 1:
            add(Violation("axis-positive",
                          f"{axis}={getattr(lay, axis)} must be >= 1"))
    if any(getattr(lay, a) < 1 for a in ("dp", "pp", "cp", "ep",
                                         "tp")):
        # every divisibility rule below would divide by the zero
        # axis — the axis-positive violations ARE the verdict; return
        # them instead of a ZeroDivisionError traceback
        return v
    if lay.sp_mode not in SP_MODES:
        add(Violation("sp-mode",
                      f"sp_mode={lay.sp_mode!r} not in {SP_MODES}"))
    if shape.num_heads % lay.tp or shape.num_kv_heads % lay.tp:
        add(Violation(
            "tp-heads",
            f"tp={lay.tp} must divide num_heads={shape.num_heads} and "
            f"num_kv_heads={shape.num_kv_heads} (TP shards attention "
            f"heads; models.llama_3d head-divisibility rule)"))
    if shape.vocab_size % lay.tp:
        add(Violation(
            "tp-vocab",
            f"tp={lay.tp} must divide vocab_size={shape.vocab_size} "
            f"(vocab-parallel embedding + fused LM-head CE shard the "
            f"vocab over tp)"))
    if shape.seq_len % (lay.tp * lay.cp):
        add(Violation(
            "sp-seq",
            f"tp*cp = {lay.tp * lay.cp} must divide "
            f"seq_len={shape.seq_len} (Megatron-SP + ring-attention "
            f"sequence shards)"))
    if lay.pp > shape.num_layers:
        add(Violation(
            "pp-stages",
            f"pp={lay.pp} exceeds num_layers={shape.num_layers} — a "
            f"stage would hold zero layers"))
    elif shape.num_layers % (lay.pp * lay.num_chunks):
        add(Violation(
            "pp-layers",
            f"pp*num_chunks = {lay.pp * lay.num_chunks} must divide "
            f"num_layers={shape.num_layers} (equal pipeline stage "
            f"balance)"))
    # M < pp is a bubble-efficiency disaster but RUNS (the scan
    # schedule accepts it — verified against Llama3DConfig), so it is
    # NOT a legality violation here; enumerate_layouts prunes it as
    # dominated instead. What Llama3DConfig actually refuses is the
    # interleaved schedule's microbatch constraints — mirror those:
    if lay.num_chunks > 1:
        if lay.num_microbatches < lay.pp:
            add(Violation(
                "pp-microbatches",
                f"interleaved pipeline (num_chunks="
                f"{lay.num_chunks}) needs num_microbatches >= pp, "
                f"got {lay.num_microbatches} < {lay.pp}"))
        if lay.schedule == "1f1b":
            if lay.num_microbatches % lay.pp:
                add(Violation(
                    "pp-microbatches",
                    f"interleaved 1F1B requires num_microbatches % "
                    f"pp == 0, got {lay.num_microbatches} % "
                    f"{lay.pp}"))
            if lay.pp < 2:
                add(Violation(
                    "pp-microbatches",
                    "interleaved 1F1B needs pipeline size >= 2"))
    data_replicas = lay.dp * lay.ep
    if shape.global_batch % data_replicas:
        add(Violation(
            "dp-batch",
            f"dp*ep = {data_replicas} must divide "
            f"global_batch={shape.global_batch} sequences"))
    elif (lay.num_microbatches * lay.microbatch_size * data_replicas
          != shape.global_batch):
        add(Violation(
            "dp-batch",
            f"num_microbatches*microbatch_size*dp*ep = "
            f"{lay.num_microbatches * lay.microbatch_size}"
            f"*{data_replicas} != global_batch={shape.global_batch}"))
    if lay.ep > 1 and not shape.moe:
        add(Violation(
            "ep-moe", f"ep={lay.ep} > 1 requires an MoE model "
            f"(num_experts=0 here)"))
    if shape.moe and shape.num_experts % lay.ep:
        add(Violation(
            "ep-experts",
            f"ep={lay.ep} must divide num_experts={shape.num_experts}"))
    if lay.zero and lay.dp < 2:
        add(Violation(
            "zero-dp",
            f"zero (ZeRO-1 optimizer sharding) needs dp >= 2, got "
            f"dp={lay.dp}"))
    return v


def _divisors(n: int) -> list[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_layouts(shape: ModelShape, n_devices: int, *,
                      allow_cp: bool = True,
                      allow_ep: Optional[bool] = None,
                      allow_zero: bool = True,
                      require_zero: Optional[bool] = None,
                      sp_modes: Sequence[str] = ("overlap", "fused"),
                      microbatch_size: int = 1
                      ) -> Iterator[Layout]:
    """Every LEGAL layout for ``shape`` on ``n_devices`` chips, in a
    deterministic order (sorted degree tuples — same inputs, same
    sequence; the plan-determinism test rides on this).

    ``num_microbatches`` is derived, not searched: with
    ``microbatch_size`` fixed, M = global_batch / (dp * ep) is the only
    value that covers the global batch — the schedule dimension the
    planner DOES search is the (dp x pp) trade this forces (more dp =
    fewer microbatches = worse pipeline fill).

    The knob dimensions are pruned where they are degenerate: ``zero``
    only when dp >= 2, ``sp_mode`` beyond the first only when tp >= 2
    (no SP boundary exists at tp=1) — otherwise the same physical
    config would be enumerated (and priced) twice.

    ``require_zero`` (None = don't care) filters to layouts whose
    ``zero`` flag MATCHES — the elastic-resume constraint: a
    checkpoint's optimizer-state tree structure is fixed, so a re-plan
    for a changed fleet must keep the ZeRO setting, not merely be
    allowed to (`resilience.elastic_resume` passes the source plan's
    setting here).
    """
    if allow_ep is None:
        allow_ep = shape.moe
    for tp in _divisors(n_devices):
        for pp in _divisors(n_devices // tp):
            rest2 = n_devices // (tp * pp)
            for cp in (_divisors(rest2) if allow_cp else (1,)):
                if rest2 % cp:
                    continue
                rest3 = rest2 // cp
                for ep in (_divisors(rest3) if allow_ep else (1,)):
                    if rest3 % ep:
                        continue
                    dp = rest3 // ep
                    mbs = shape.global_batch // (dp * ep) \
                        if shape.global_batch % (dp * ep) == 0 else 0
                    if mbs < 1 or mbs % microbatch_size:
                        continue
                    M = mbs // microbatch_size
                    if M < pp:
                        # runnable but dominated (bubble factor
                        # (M+pp-1)/M >= 2): pruned from the SEARCH,
                        # not outlawed by check_layout — hand flags
                        # may still pick it
                        continue
                    zeros = (False, True) if (allow_zero and dp >= 2) \
                        else (False,)
                    if require_zero is not None:
                        zeros = tuple(z for z in zeros
                                      if z == require_zero)
                        if not zeros:
                            continue
                    modes = tuple(sp_modes) if tp >= 2 \
                        else tuple(sp_modes[:1])
                    for zero in zeros:
                        for mode in modes:
                            lay = Layout(
                                dp=dp, pp=pp, cp=cp, ep=ep, tp=tp,
                                num_microbatches=M,
                                microbatch_size=microbatch_size,
                                zero=zero, sp_mode=mode)
                            if not check_layout(shape, lay, n_devices):
                                yield lay
