"""CLI for the auto-parallel planner.

    python -m apex1_tpu.planner --model llama8b --devices 16 \
        [--generation v5p] [--out plan.json] [--top 5] \
        [--no-calibration] [--no-cp] [--no-zero]

    python -m apex1_tpu.planner --smoke

``--smoke`` is the check_all gate (< 30s): enumerate -> price -> emit
for the tiny shape on 8 virtual devices, pin plan determinism
(byte-identical re-plan), price the banked gpt2 shape against the
committed calibration table, then drive ``examples/llama_3d.py --plan
auto`` end-to-end on the CPU mesh — the full
search-to-training-step path with zero hardware.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from apex1_tpu.planner import (BANKED_SHAPES, ModelShape, make_plan,
                               plan_json, save_plan)

TINY = ModelShape(name="tiny", num_layers=2, hidden_size=64,
                  ffn_size=128, num_heads=4, num_kv_heads=2,
                  head_dim=16, vocab_size=256, seq_len=64,
                  global_batch=8)

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _print_plan(plan: dict) -> None:
    s = plan["search"]
    print(f"search: {s['n_enumerated']} legal layouts, "
          f"{s['n_hbm_rejected']} over HBM budget "
          f"({plan['generation']})", flush=True)
    for i, row in enumerate(s["ranked_top"]):
        tag = "-> " if i == 0 else "   "
        print(f"  {tag}{row['mesh']:44s} "
              f"calibrated {row['calibrated_step_ms']:10.3f} ms "
              f"(analytic {row['step_ms']:10.3f})", flush=True)
    p = plan["predicted"]
    print(f"pick: mesh {plan['mesh']} M="
          f"{plan['schedule']['num_microbatches']} "
          f"sp={plan['kernel_flags']['sp_boundary']} "
          f"zero={plan['zero']['enabled']}", flush=True)
    print(f"      {p['calibrated_step_ms']:.3f} ms/step calibrated "
          f"({p['calibration']['source']}); "
          f"{p['tokens_per_sec_per_chip']:,.0f} tok/s/chip; "
          f"bound {p['bound']}; mem {plan['memory']['total']:.2f} / "
          f"{plan['memory']['budget']:.2f} GiB", flush=True)


def smoke() -> int:
    print("== planner smoke: determinism ==", flush=True)
    a = plan_json(make_plan(TINY, 8))
    b = plan_json(make_plan(TINY, 8))
    if a != b:
        print("FAIL: two identical searches emitted different plans",
              flush=True)
        return 1
    print(f"  OK   tiny/8dev plan byte-stable ({len(a)} bytes)",
          flush=True)

    print("== planner smoke: banked-shape pricing ==", flush=True)
    for name in ("gpt2", "llama_longctx"):
        plan = make_plan(BANKED_SHAPES[name], 1)
        cal = plan["predicted"]["calibration"]
        print(f"  OK   {name}: "
              f"{plan['predicted']['calibrated_step_ms']:.1f} ms/step "
              f"calibrated x{cal['slowdown']:.2f} [{cal['source']}]",
              flush=True)

    print("== planner smoke: llama_3d --plan auto (CPU mesh) ==",
          flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join("examples", "llama_3d.py"),
         "--plan", "auto", "--layers", "2", "--steps", "2",
         "--microbatches", "4"],
        cwd=_REPO, env=env, capture_output=True, text=True,
        timeout=240)
    sys.stdout.write(proc.stdout)
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr)
        print(f"FAIL: llama_3d --plan auto rc={proc.returncode}",
              flush=True)
        return 1
    if "plan verified" not in proc.stdout:
        print("FAIL: example did not verify the plan's partition "
              "rules", flush=True)
        return 1
    print("planner smoke OK", flush=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0], prog="apex1_tpu.planner")
    ap.add_argument("--model", default="tiny",
                    choices=("tiny",) + tuple(sorted(BANKED_SHAPES)),
                    help="a banked shape, or the tiny smoke shape")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--generation", default="v5e")
    ap.add_argument("--global-batch", type=int, default=None,
                    help="override the shape's sequences per step")
    ap.add_argument("--out", default=None,
                    help="write the plan JSON here (atomic)")
    ap.add_argument("--top", type=int, default=5)
    ap.add_argument("--no-calibration", action="store_true",
                    help="analytic prices only (never on by default: "
                    "raw roofline optimism is what ROADMAP item 1 "
                    "exists to correct)")
    ap.add_argument("--no-cp", action="store_true")
    ap.add_argument("--no-zero", action="store_true")
    ap.add_argument("--smoke", action="store_true",
                    help="the check_all gate (see module docstring)")
    args = ap.parse_args(argv)

    if args.smoke:
        return smoke()

    shape = TINY if args.model == "tiny" else BANKED_SHAPES[args.model]
    if args.global_batch:
        import dataclasses
        shape = dataclasses.replace(shape,
                                    global_batch=args.global_batch)
    plan = make_plan(shape, args.devices, generation=args.generation,
                     use_calibration=not args.no_calibration,
                     top_k=args.top, allow_cp=not args.no_cp,
                     allow_zero=not args.no_zero)
    _print_plan(plan)
    if args.out:
        save_plan(plan, args.out)
        print(f"wrote {args.out}", flush=True)
    else:
        json.dump(plan, sys.stdout, indent=1, sort_keys=True)
        print(flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
