"""apex1_tpu — a TPU-native acceleration framework with the capabilities of
NVIDIA Apex (reference: mbrukman/apex-1).

This is NOT a port: the reference is a CUDA/C++/torch bolt-on library; this
package is a JAX/XLA/Pallas-first redesign of the same capability surface:

- ``apex1_tpu.amp``          — mixed-precision policies O0-O3, dynamic loss
                               scaling (reference: ``apex/amp``)
- ``apex1_tpu.optim``        — fused optimizers: Adam/LAMB/SGD/NovoGrad/
                               Adagrad, LARC, clip_grad (``apex/optimizers``,
                               ``apex/contrib/clip_grad``)
- ``apex1_tpu.ops``          — Pallas kernels: layer/RMS norm, scaled-masked
                               softmax, fused cross-entropy, RoPE, flash
                               attention, fused dense/MLP (``csrc/``,
                               ``apex/contrib/{fmha,multihead_attn,xentropy,
                               layer_norm}``)
- ``apex1_tpu.parallel``     — DDP-equivalent gradient sync, SyncBatchNorm,
                               ZeRO-style sharded optimizers
                               (``apex/parallel``, ``apex/contrib/optimizers``)
- ``apex1_tpu.transformer``  — tensor/pipeline/sequence parallelism over a
                               ``jax.sharding.Mesh`` (``apex/transformer``)
- ``apex1_tpu.models``       — reference model families used by the baseline
                               configs: GPT-2, BERT, Llama-3, ResNet-50
- ``apex1_tpu.runtime``      — C++ host-side runtime: pinned flat-buffer
                               packing and a prefetching data loader
                               (``csrc/flatten_unflatten.cpp``, examples'
                               loader)

Citations in docstrings use the survey convention ``path :: Symbol`` against
the upstream apex layout (see SURVEY.md §0 — the reference mount was empty at
survey time, so symbol anchors are the citation unit).
"""

__version__ = "0.1.0"


def _install_jax_compat():
    """Bridge the repo's newer-jax spellings onto an older runtime.

    The codebase targets the current `jax.shard_map(..., check_vma=)`
    API; on a jax that predates the top-level export (< 0.6, e.g. the
    0.4.x CPU verify image) the same callable lives at
    ``jax.experimental.shard_map.shard_map`` with the check kwarg named
    ``check_rep``. Install a translating alias so ONE spelling works
    everywhere (the alternative — try/except at 30+ call sites across
    src/tests/examples — rots). `ops._common.out_struct` handles the
    paired `jax.typeof`/vma gap the same way.
    """
    import jax

    if not hasattr(jax.lax, "axis_size"):
        # psum of a python literal is special-cased to the STATIC axis
        # size (an int at trace time), exactly axis_size's contract
        jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)

    if not hasattr(jax.lax, "pcast"):
        # no vma system on this jax -> re-typing a value across the
        # varying/invariant divide is the identity
        jax.lax.pcast = lambda x, axis_name=None, *, to=None: x

    if not hasattr(jax, "set_mesh"):
        # the legacy spelling of a default mesh is the Mesh context
        # manager, so only the `with jax.set_mesh(mesh):` form (the one
        # this repo uses) is bridged; the real API's statement form
        # (global install) has no legacy equivalent — the returned mesh
        # does nothing until entered
        jax.set_mesh = lambda mesh: mesh

    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f=None, **kw):
        # check_vma has no faithful translation: old check_rep is the
        # buggier predecessor (false-positives on `cond` — its own
        # error text says "as a temporary workaround pass
        # check_rep=False"), and this codebase's vma annotations
        # (pcast / out_struct vma) are identity here. Disable it; the
        # vma discipline is enforced wherever the real API exists.
        kw.pop("check_vma", None)
        kw["check_rep"] = False
        if f is None:  # partial-application form
            return lambda g: _shard_map(g, **kw)
        return _shard_map(f, **kw)

    jax.shard_map = shard_map


_install_jax_compat()

from apex1_tpu.core import mesh, policy, loss_scale  # noqa: F401,E402
from apex1_tpu.core.mesh import (MeshConfig, make_hybrid_mesh,  # noqa: F401
                                 make_mesh)
from apex1_tpu.core.policy import PrecisionPolicy, get_policy  # noqa: F401
