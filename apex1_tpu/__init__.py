"""apex1_tpu — a TPU-native acceleration framework with the capabilities of
NVIDIA Apex (reference: mbrukman/apex-1).

This is NOT a port: the reference is a CUDA/C++/torch bolt-on library; this
package is a JAX/XLA/Pallas-first redesign of the same capability surface:

- ``apex1_tpu.amp``          — mixed-precision policies O0-O3, dynamic loss
                               scaling (reference: ``apex/amp``)
- ``apex1_tpu.optim``        — fused optimizers: Adam/LAMB/SGD/NovoGrad/
                               Adagrad, LARC, clip_grad (``apex/optimizers``,
                               ``apex/contrib/clip_grad``)
- ``apex1_tpu.ops``          — Pallas kernels: layer/RMS norm, scaled-masked
                               softmax, fused cross-entropy, RoPE, flash
                               attention, fused dense/MLP (``csrc/``,
                               ``apex/contrib/{fmha,multihead_attn,xentropy,
                               layer_norm}``)
- ``apex1_tpu.parallel``     — DDP-equivalent gradient sync, SyncBatchNorm,
                               ZeRO-style sharded optimizers
                               (``apex/parallel``, ``apex/contrib/optimizers``)
- ``apex1_tpu.transformer``  — tensor/pipeline/sequence parallelism over a
                               ``jax.sharding.Mesh`` (``apex/transformer``)
- ``apex1_tpu.models``       — reference model families used by the baseline
                               configs: GPT-2, BERT, Llama-3, ResNet-50
- ``apex1_tpu.runtime``      — C++ host-side runtime: pinned flat-buffer
                               packing and a prefetching data loader
                               (``csrc/flatten_unflatten.cpp``, examples'
                               loader)

Citations in docstrings use the survey convention ``path :: Symbol`` against
the upstream apex layout (see SURVEY.md §0 — the reference mount was empty at
survey time, so symbol anchors are the citation unit).
"""

__version__ = "0.1.0"

from apex1_tpu.core import mesh, policy, loss_scale  # noqa: F401
from apex1_tpu.core.mesh import (MeshConfig, make_hybrid_mesh,  # noqa: F401
                                 make_mesh)
from apex1_tpu.core.policy import PrecisionPolicy, get_policy  # noqa: F401
