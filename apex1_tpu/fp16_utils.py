"""Legacy manual mixed-precision API — reference ``apex/fp16_utils/
{fp16_optimizer,loss_scaler,fp16util}.py`` (the pre-amp surface:
``FP16_Optimizer``, ``DynamicLossScaler``, ``network_to_half``,
``master_params_to_model_params``...).

These predate ``apex.amp`` but stayed public; users migrating from the
reference find the same names here, implemented over the same machinery
`apex1_tpu.amp` uses (`apex1_tpu.core.loss_scale`,
`apex1_tpu.core.policy`). In JAX "the model" is a param pytree, so
module-mutating helpers become pytree casts.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from apex1_tpu.core.loss_scale import (LossScaleState, all_finite,
                                       make_loss_scale, select_tree)

__all__ = [
    "tofp16", "network_to_half", "BN_convert_float", "prep_param_lists",
    "master_params_to_model_params", "model_grads_to_master_grads",
    "DynamicLossScaler", "LossScaler", "FP16_Optimizer",
]


def tofp16(tree):
    """≙ ``fp16util.tofp16`` — cast float leaves to fp16 (on TPU prefer
    bf16 via `network_to_half(dtype=jnp.bfloat16)`)."""
    return network_to_half(tree, dtype=jnp.float16)


def network_to_half(tree, *, dtype=jnp.float16, keep_norms_fp32=False):
    """≙ ``fp16util.network_to_half``: cast floating leaves. With
    ``keep_norms_fp32``, leaves whose path mentions norm/bn stay fp32
    (≙ ``BN_convert_float``'s effect on a converted network)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    treedef = jax.tree_util.tree_structure(tree)

    import re
    # norm-ish path segments only (bn1, attn_norm, ln2_scale, BatchNorm_0)
    # — NOT every "bias"/"scale": a Dense bias must go half, or the fp32
    # add would silently promote the rest of the network
    norm_pat = re.compile(r"(^|[\[\]'/_.])((layer|batch|group|sync|rms)?"
                          r"norm|bn|ln)\d*([\[\]'/_.]|$)")

    def cast(path, x):
        if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return x
        name = jax.tree_util.keystr(path).lower()
        if keep_norms_fp32 and norm_pat.search(name):
            return jnp.asarray(x, jnp.float32)
        return jnp.asarray(x, dtype)

    return jax.tree_util.tree_unflatten(
        treedef, [cast(p, x) for p, x in flat])


def BN_convert_float(tree):
    """≙ ``fp16util.BN_convert_float`` — restore norm/BN leaves to fp32
    after a wholesale half cast."""
    return network_to_half(tree, dtype=jnp.float16, keep_norms_fp32=True)


def prep_param_lists(params):
    """≙ ``fp16util.prep_param_lists(model)`` — returns (model_params,
    master_params): the half-precision view and the fp32 masters."""
    master = jax.tree.map(
        lambda x: jnp.asarray(x, jnp.float32)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
        params)
    return network_to_half(params), master


def master_params_to_model_params(master_params, *, dtype=jnp.float16):
    """≙ ``fp16util.master_params_to_model_params`` (copy direction
    master→model; functional, returns the new model params)."""
    return network_to_half(master_params, dtype=dtype)


def model_grads_to_master_grads(model_grads):
    """≙ ``fp16util.model_grads_to_master_grads`` — upcast to fp32."""
    return jax.tree.map(
        lambda g: jnp.asarray(g, jnp.float32)
        if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating) else g,
        model_grads)


class DynamicLossScaler:
    """≙ ``fp16_utils.loss_scaler.DynamicLossScaler`` — stateful facade
    over the functional `LossScaleState` (scale 2^16 init, ×2 every
    ``scale_window`` clean steps, ÷2 on overflow)."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        from apex1_tpu.core.loss_scale import DynamicLossScale
        self._impl = make_loss_scale(DynamicLossScale(
            init_scale=init_scale, growth_factor=scale_factor,
            growth_interval=scale_window))
        self.state: LossScaleState = self._impl.init()

    @property
    def loss_scale(self) -> float:
        return float(self.state.scale)

    def scale_loss(self, loss):
        return self._impl.scale(loss, self.state)

    def unscale(self, grads):
        return self._impl.unscale(grads, self.state)

    def has_overflow(self, grads) -> bool:
        return not bool(all_finite(grads))

    def update_scale(self, overflow: bool) -> None:
        self.state = self._impl.adjust(self.state,
                                       jnp.asarray(not overflow))


class LossScaler(DynamicLossScaler):
    """≙ static ``fp16_utils.loss_scaler.LossScaler``."""

    def __init__(self, scale=1.0):
        self._impl = make_loss_scale(scale)
        self.state = self._impl.init()

    def update_scale(self, overflow: bool) -> None:
        pass  # static


@dataclasses.dataclass
class FP16_Optimizer:
    """≙ ``fp16_utils.fp16_optimizer.FP16_Optimizer`` — wraps any optax
    transform with fp32 master weights + loss scaling, driven manually:

        opt = FP16_Optimizer(optax.sgd(0.1), dynamic_loss_scale=True)
        state = opt.init(half_params)
        loss, half_params, state = opt.step(loss_fn, state, batch)

    The train-loop shape (``backward(loss)`` then ``step()``) collapses
    into one functional ``step`` because grad+update are one traced
    program in JAX. Skips the update on overflow (reference semantics).
    """

    optimizer: optax.GradientTransformation
    static_loss_scale: float = 1.0
    dynamic_loss_scale: bool = False
    compute_dtype: Any = jnp.float16

    def __post_init__(self):
        self._scaler = make_loss_scale(
            "dynamic" if self.dynamic_loss_scale else self.static_loss_scale)

    def init(self, params):
        master = jax.tree.map(
            lambda x: jnp.asarray(x, jnp.float32)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            params)
        return {"master": master,
                "opt": self.optimizer.init(master),
                "scale": self._scaler.init()}

    def step(self, loss_fn: Callable, state, *batch):
        scaler = self._scaler

        def scaled(master):
            model = master_params_to_model_params(
                master, dtype=self.compute_dtype)
            loss = loss_fn(model, *batch)
            return scaler.scale(loss.astype(jnp.float32),
                                state["scale"]), loss

        grads, loss = jax.grad(scaled, has_aux=True)(state["master"])
        grads = scaler.unscale(model_grads_to_master_grads(grads),
                               state["scale"])
        finite = all_finite(grads)
        updates, new_opt = self.optimizer.update(grads, state["opt"],
                                                 state["master"])
        new_master = optax.apply_updates(state["master"], updates)
        new_state = {
            "master": select_tree(finite, new_master, state["master"]),
            "opt": select_tree(finite, new_opt, state["opt"]),
            "scale": scaler.adjust(state["scale"], finite),
        }
        model = master_params_to_model_params(new_state["master"],
                                              dtype=self.compute_dtype)
        return loss, model, new_state
