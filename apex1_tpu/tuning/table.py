"""Shape-keyed kernel tuning tables — persisted block-size winners.

The table replaces "one heuristic plus an env var" block selection with
persistent, measured state: ``tools/tune_kernels.py`` sweeps block-size
candidates **in one process** (the blocks are static kernel arguments,
so the jit cache keys on them — no fresh-process-per-candidate), writes
the winners here, and every Pallas entry point consults the table at
trace time before falling back to its analytic heuristic.

Entries are keyed on

    kernel name x TPU generation (``core.capability``) x operand dtype
    x the kernel's padded dims (``registry.KernelSpec.dims``)

so a winner swept for bf16 flash attention at head-dim 128 on v5e never
leaks to fp32, to head-dim 576, or to a v5p chip. On disk each kernel
owns one JSON file under ``perf_results/tuning/`` (override with
``APEX1_TUNING_DIR``):

    {"schema": 1, "kernel": "flash_attention",
     "entries": {"v5e|bfloat16|Dp=128":
                 {"blocks": {"block_q": 512, "block_k": 512},
                  "time_ms": 1.84, "backend": "tpu",
                  "timing": "measured"}}}

Lookup is fail-safe by construction — a missing dir, corrupt file,
unknown generation, misaligned block, or VMEM-over-budget entry (the
``registry`` cost model against the RECORDED generation's
``vmem_budget``) all degrade to a miss, and the caller's heuristic
takes over. ``timing: "interpret"`` entries (swept off-TPU, where only
the plumbing is meaningful) are served off-TPU but never on real
silicon. ``validate_tables`` re-checks every in-repo file strictly for
the ``tools/check_all.sh`` gate.
"""

from __future__ import annotations

import json
import os
from typing import Any, Mapping

import numpy as np

from apex1_tpu.core.capability import (detect_generation, get_capability,
                                       vmem_budget)
from apex1_tpu.tuning.registry import SPECS


def _on_tpu() -> bool:
    # lazy: ops._common imports the tuning package at module scope (the
    # reverse edge at import time would be a cycle)
    from apex1_tpu.ops._common import on_tpu
    return on_tpu()


_SCHEMA = 1

# process-wide cache: {"dir": str|None, "tables": {kernel: {key: entry}},
# "problems": [str]} — populated lazily on first lookup, dropped by
# clear_cache() (tests, APEX1_TUNING_DIR changes, post-sweep reloads)
_STATE: dict[str, Any] = {"dir": None, "tables": None, "problems": None}


def default_tuning_dir() -> str:
    """``APEX1_TUNING_DIR`` if set, else ``<repo>/perf_results/tuning``
    (the package's parent directory is the repo root)."""
    env = os.environ.get("APEX1_TUNING_DIR", "").strip()
    if env:
        return env
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), "perf_results", "tuning")


def clear_cache() -> None:
    """Drop the in-memory tables (next lookup reloads from disk)."""
    _STATE.update(dir=None, tables=None, problems=None)


def canonical_dtype(dtype) -> str:
    """Canonical dtype name for table keys ('bfloat16', 'float32',
    'int8', ...). Accepts strings, numpy/jax dtypes, and scalar types."""
    return np.dtype(dtype).name


def canonical_generation(generation: str | None = None) -> str:
    """Table-key generation: explicit > detected chip > 'v5e' (the same
    conservative off-TPU default ``core.capability.get_capability``
    plans blocks for, so CPU-validated lookups agree with the v5e
    planning path)."""
    return generation or detect_generation() or "v5e"


def make_key(dims: Mapping[str, int], dtype,
             generation: str | None = None) -> str:
    """Canonical entry key: ``<gen>|<dtype>|<k=v,...>`` with dims sorted
    by name. ``dims`` must be the kernel's PADDED dims (the values the
    block planner actually sees), per ``registry.KernelSpec.dims``."""
    gen = canonical_generation(generation)
    dt = canonical_dtype(dtype)
    body = ",".join(k + "=" + str(int(v)) for k, v in sorted(dims.items()))
    return gen + "|" + dt + "|" + body


def parse_key(key: str) -> tuple[str, str, dict[str, int]]:
    """Inverse of :func:`make_key`; raises ValueError on malformed keys."""
    parts = key.split("|")
    if len(parts) != 3:
        raise ValueError(f"malformed tuning key {key!r}")
    gen, dt, body = parts
    dims: dict[str, int] = {}
    for item in body.split(","):
        name, _, val = item.partition("=")
        if not name or not val:
            raise ValueError(f"malformed dims in tuning key {key!r}")
        dims[name] = int(val)
    return gen, dt, dims


def _load_file(path: str, kernel: str) -> dict[str, dict]:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != _SCHEMA:
        raise ValueError(f"unsupported schema {doc.get('schema')!r}")
    if doc.get("kernel") != kernel:
        raise ValueError(f"kernel field {doc.get('kernel')!r} != filename")
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        raise ValueError("entries must be an object")
    return entries


def _tables() -> dict[str, dict[str, dict]]:
    """Lazily load every ``<kernel>.json`` in the tuning dir. Unreadable
    files become recorded problems (see ``load_problems``), never
    exceptions — a corrupt table must not take down a training run."""
    d = default_tuning_dir()
    if _STATE["tables"] is not None and _STATE["dir"] == d:
        return _STATE["tables"]
    tables: dict[str, dict[str, dict]] = {}
    problems: list[str] = []
    if os.path.isdir(d):
        for name in sorted(os.listdir(d)):
            if not name.endswith(".json"):
                continue
            kernel = name[:-5]
            path = os.path.join(d, name)
            try:
                tables[kernel] = _load_file(path, kernel)
            except Exception as e:  # fail-safe: degrade to a miss
                problems.append(f"{path}: {type(e).__name__}: {e}")
    _STATE.update(dir=d, tables=tables, problems=problems)
    return tables


def load_problems() -> list[str]:
    """Parse problems swallowed by the lazy loader (for diagnostics)."""
    _tables()
    return list(_STATE["problems"])


def _entry_blocks(kernel: str, entry: Mapping, dims: Mapping[str, int],
                  dtype_name: str, generation: str, *,
                  serving: bool = True) -> dict[str, int] | None:
    """Validated blocks of one entry, or None if the entry is unusable:
    wrong/missing params, misaligned values, an unknown generation, or a
    VMEM estimate over the recorded generation's budget. ``serving``
    additionally rejects interpret-timed entries on real TPUs (lookup
    path); ``validate_tables`` checks structure only."""
    spec = SPECS.get(kernel)
    if spec is None:
        return None
    blocks = entry.get("blocks")
    if not isinstance(blocks, Mapping):
        return None
    out: dict[str, int] = {}
    for p in spec.params:
        v = blocks.get(p)
        if not isinstance(v, int) or isinstance(v, bool) or v <= 0 \
                or v % spec.align:
            return None
        out[p] = v
    try:
        get_capability(generation)
        es = np.dtype(dtype_name).itemsize
        ok, _est = spec.check(out, dims, es, vmem_budget(generation))
    except Exception:
        return None
    if not ok:
        return None
    # off-TPU (interpret-mode) timings order nothing on real silicon:
    # serve them only where they were measured
    if serving and _on_tpu() and entry.get("timing") != "measured":
        return None
    return out


def lookup(kernel: str, dims: Mapping[str, int], dtype,
           generation: str | None = None) -> dict[str, int] | None:
    """Validated block dict for (kernel, generation, dtype, padded dims),
    or None on miss/invalid — the caller then falls back env > heuristic
    (see the per-op precedence in docs/ops.md)."""
    try:
        key = make_key(dims, dtype, generation)
    except Exception:
        return None
    entry = _tables().get(kernel, {}).get(key)
    if entry is None:
        return None
    return _entry_blocks(kernel, entry, dims, canonical_dtype(dtype),
                         canonical_generation(generation))


def record(kernel: str, dims: Mapping[str, int], dtype,
           blocks: Mapping[str, int], *, time_ms: float | None = None,
           generation: str | None = None,
           extra: Mapping[str, Any] | None = None) -> tuple[str, dict]:
    """Install a winner in the in-memory table (visible to subsequent
    ``lookup`` calls immediately); ``save`` persists it. Records the
    backend and whether the timing was real silicon or interpret mode."""
    if kernel not in SPECS:
        raise ValueError(f"unknown tunable kernel {kernel!r}; "
                         f"known: {sorted(SPECS)}")
    spec = SPECS[kernel]
    missing = [p for p in spec.params if p not in blocks]
    if missing:
        raise ValueError(f"{kernel} entry missing block params {missing}")
    key = make_key(dims, dtype, generation)
    try:
        import jax
        backend = jax.default_backend()
    except Exception:
        backend = "unknown"
    entry: dict[str, Any] = {
        "blocks": {p: int(blocks[p]) for p in spec.params},
        "time_ms": None if time_ms is None else round(float(time_ms), 4),
        "backend": backend,
        "timing": "measured" if _on_tpu() else "interpret",
    }
    if extra:
        entry.update(extra)
    _tables().setdefault(kernel, {})[key] = entry
    return key, entry


def save(kernel: str, dir: str | None = None) -> str:
    """Write ``kernel``'s table to ``<dir>/<kernel>.json`` (merging over
    any entries already on disk that this process never loaded — two
    sweep runs for different kernels/shapes compose). Returns the path."""
    d = dir or default_tuning_dir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, kernel + ".json")
    entries: dict[str, dict] = {}
    if os.path.exists(path):
        try:
            entries = _load_file(path, kernel)
        except Exception:
            entries = {}  # unreadable file: the fresh write repairs it
    entries.update(_tables().get(kernel, {}))
    doc = {"schema": _SCHEMA, "kernel": kernel,
           "entries": {k: entries[k] for k in sorted(entries)}}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def validate_tables(dir: str | None = None) -> list[str]:
    """STRICT validation of every ``*.json`` table in ``dir`` for the
    ``check_all.sh`` gate: file parses, schema/kernel fields match, every
    key parses against a known generation, and every entry's blocks pass
    the registry VMEM model for its recorded capability. Returns the
    list of problems (empty = clean)."""
    d = dir or default_tuning_dir()
    problems: list[str] = []
    if not os.path.isdir(d):
        return problems  # no tables yet is a valid state
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(d, name)
        kernel = name[:-5]
        if kernel not in SPECS:
            problems.append(f"{path}: not a known tunable kernel "
                            f"(known: {sorted(SPECS)})")
            continue
        try:
            entries = _load_file(path, kernel)
        except Exception as e:
            problems.append(f"{path}: {type(e).__name__}: {e}")
            continue
        for key, entry in entries.items():
            try:
                gen, dt, dims = parse_key(key)
            except ValueError as e:
                problems.append(f"{path}: {e}")
                continue
            missing = [k for k in SPECS[kernel].dims if k not in dims]
            if missing:
                problems.append(f"{path}: {key}: missing dims {missing}")
                continue
            if _entry_blocks(kernel, entry, dims, dt, gen,
                             serving=False) is None:
                problems.append(
                    f"{path}: {key}: entry invalid (blocks "
                    f"{entry.get('blocks')!r} misaligned/over the "
                    f"{gen} VMEM budget, or unknown generation)")
    return problems
