"""Shape-keyed kernel autotuning — persisted tables + in-process sweeps.

The reference answers per-hardware kernel specialization with per-SM
builds (``csrc/fmha`` compiles one kernel per compute capability); the
TPU-native answer is DATA: measured block-size winners keyed on

    kernel x TPU generation x dtype x padded dims

persisted under ``perf_results/tuning/`` and consulted by every Pallas
entry point at trace time. Selection precedence at each op:

    explicit block argument            (the sweep mechanism)
    > documented env override          (``APEX1_ATTN_BLOCK_Q/K`` only)
    > tuning-table winner              (this package)
    > analytic heuristic               (``_auto_blocks`` / ``row_block``)

With no tables on disk every op reproduces the analytic heuristic's
choices bit-for-bit (pinned by ``tests/test_tuning.py``).

Because block sizes are static kernel arguments, a sweep of N candidates
runs in ONE process — the jit cache keys on the block values, so each
candidate compiles exactly one executable and candidates never
cross-contaminate (the old env-var overrides were read at trace time,
which forced a fresh process and a cold compile of everything per
candidate). ``tools/tune_kernels.py`` is the sweep driver; it measures
on the live backend, records winners here, and persists them.

Caveat for same-process consumers: a lookup resolved during an earlier
trace is baked into that executable — after recording new winners, call
``jax.clear_caches()`` (the sweep driver does) before re-tracing ops
that consult the table without explicit blocks.
"""

from __future__ import annotations

from apex1_tpu.tuning.registry import SPECS, KernelSpec  # noqa: F401
from apex1_tpu.tuning.table import (canonical_dtype,  # noqa: F401
                                    canonical_generation, clear_cache,
                                    default_tuning_dir, load_problems,
                                    lookup, make_key, parse_key, record,
                                    save, validate_tables)


def padded_lanes(lanes: int) -> int:
    """Last-dim size padded to the 128-lane multiple the kernels see."""
    return max(128, ((lanes + 127) // 128) * 128)


def seq_bucket(seq: int) -> int:
    """Power-of-two bucket (>= 128) for sequence-keyed tuning dims.
    Optimal flash blocks depend strongly on sequence length (grid size,
    causal-skip share, VMEM reuse), so winners are keyed to the bucket
    they were MEASURED at — a 1k-seq winner never silently governs a
    16k-seq program; unmeasured buckets fall through to the heuristic."""
    b = 128
    while b < seq:
        b *= 2
    return b


def tuned_row_block(kernel: str, lanes: int, *, rows: int | None = None,
                    dtype=None, requested: int | None = None) -> int:
    """Rows-per-grid-step for the row-wise kernels (softmax, layer/rms
    norm, rope, xentropy): explicit ``requested`` > tuning table
    (keyed on the PADDED lane count) > ``ops._common.row_block``.

    Tuned values get the same actual-row-count clamp as the heuristic so
    a winner swept at production scale never pads a tiny input up to
    dead work; explicit requests are honored verbatim (the sweep driver
    owns them).
    """
    # lazy: the ops modules import this one at module scope (the reverse
    # edge would be a cycle)
    from apex1_tpu.ops._common import row_block

    if requested is not None:
        return int(requested)
    tuned = lookup(kernel, {"lanes": padded_lanes(lanes)},
                   "float32" if dtype is None else dtype)
    if tuned is not None:
        br = tuned["block_rows"]
        if rows is not None:
            br = min(br, max(8, ((rows + 7) // 8) * 8))
        return max(8, br)
    return row_block(lanes, rows=rows)
