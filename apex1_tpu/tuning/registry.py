"""Tunable-kernel registry — the schema half of the tuning layer.

One :class:`KernelSpec` per Pallas entry point declares

- which **block parameters** the kernel takes as static arguments
  (``block_q``/``block_k``, ``block_rows``, ...);
- which **padded dims** key its tuning-table entries (the dims that
  actually change the block-planning problem — padded lane/head sizes,
  never raw batch counts);
- the parameter **alignment** the TPU sublane tiling demands; and
- a **VMEM cost model**: a coarse, monotone-in-blocks upper bound on the
  kernel's VMEM frame (double-buffered operand blocks + fp32 scratch +
  live score tiles). Table entries whose recorded blocks exceed the
  recorded generation's ``core.capability.vmem_budget`` under this model
  are rejected at lookup time — a stale entry swept on a bigger chip can
  never push a smaller chip into a Mosaic VMEM OOM; the analytic
  heuristics (``ops/attention._auto_blocks``, ``ops/_common.row_block``,
  ``ops/linear_xent._auto_blocks``) take over instead.

The per-kernel formulas live in ``apex1_tpu.vmem_model`` — the ONE
sizing model this registry shares with the graftlint kernel analyzer
(APX208) and ``tools/aot_check.py``; gating behavior is pinned
bit-identical to the pre-refactor in-module formulas by
``tests/test_lint_kernels.py::TestVmemModelShared``. The models are
GATING models, not performance models: generous enough that every block
shape the analytic heuristics produce passes, tight enough that the
shapes AOT analysis showed OOMing do not. Measured preference between
valid candidates comes from ``tools/tune_kernels.py``.

Adding a tunable kernel (see docs/ops.md "Block-size tuning"):

1. thread the block sizes as explicit static arguments through the op's
   public entry point (``None`` = consult the table);
2. add a :class:`KernelSpec` here with the padded-dims key and a VMEM
   model;
3. add a sweep case to ``tools/tune_kernels.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Mapping

from apex1_tpu.vmem_model import CHECKS


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """Declarative description of one tunable Pallas kernel."""

    name: str
    params: tuple[str, ...]       # block parameters, in canonical order
    dims: tuple[str, ...]         # padded dims that key table entries
    align: int                    # every block must be a multiple of this
    # (blocks, dims, esize, budget_bytes) -> (fits, estimated_bytes)
    check: Callable[[Mapping[str, int], Mapping[str, int], int, int],
                    tuple[bool, int]]


SPECS: dict[str, KernelSpec] = {spec.name: spec for spec in (
    # Sb: power-of-two seq bucket (tuning.seq_bucket) — block preference
    # varies with seq length, so winners never cross shape classes.
    # The check callables are the shared apex1_tpu.vmem_model formulas;
    # the per-formula frame accounting is documented there.
    KernelSpec("flash_attention", ("block_q", "block_k"), ("Dp", "Sb"),
               16, CHECKS["flash_attention"]),
    KernelSpec("fused_softmax", ("block_rows",), ("lanes",), 8,
               CHECKS["fused_softmax"]),
    KernelSpec("layer_norm", ("block_rows",), ("lanes",), 8,
               CHECKS["layer_norm"]),
    KernelSpec("rope", ("block_rows",), ("lanes",), 8,
               CHECKS["rope"]),
    KernelSpec("xentropy", ("block_rows",), ("lanes",), 8,
               CHECKS["xentropy"]),
    KernelSpec("bias_dropout_add", ("block_rows",), ("lanes",), 8,
               CHECKS["bias_dropout_add"]),
    KernelSpec("linear_xent", ("block_t", "block_v"), ("Hp",), 16,
               CHECKS["linear_xent"]),
    KernelSpec("fused_collective_matmul", ("block_m", "block_n"),
               ("Kp",), 16, CHECKS["fused_collective_matmul"]),
    KernelSpec("fused_ag_flash", ("block_q", "block_k"), ("Dp", "Sb"),
               16, CHECKS["fused_ag_flash"]),
    KernelSpec("int8_matmul", ("block_n", "block_k"), ("N", "K"), 128,
               CHECKS["int8_matmul"]),
    # paged decode: the tunable is the PAGE size (the K/V block the
    # grid streams per step); keyed on padded head dim and the padded
    # query-row count (GQA group x chunk width). Rq=8 is the S=1
    # decode-step class every serving engine hits.
    KernelSpec("paged_decode", ("page_p",), ("Dp", "Rq"), 8,
               CHECKS["paged_decode"]),
    # fused sampling epilogue: whole-row kernel today (block_v = padded
    # vocab); the spec pins its VMEM frame into the shared gate.
    KernelSpec("fused_sample", ("block_v",), ("Vp",), 128,
               CHECKS["fused_sample"]),
    # chunked preference/distill losses: the tunable is the VOCAB CHUNK
    # streamed per fori_loop step (the inner Pallas tiles ride the
    # linear_xent spec above); keyed on padded hidden.
    KernelSpec("chunked_loss", ("chunk_v",), ("Hp",), 128,
               CHECKS["chunked_loss"]),
    # fused SwiGLU/GeGLU MLP: token x ffn tile grid, H untiled (one MXU
    # dot per operand keeps the reduction order XLA-identical).
    KernelSpec("fused_swiglu", ("block_t", "block_f"), ("Hp",), 8,
               CHECKS["fused_swiglu"]),
    # multi-tenant LoRA decode epilogue: the tunable is the vocab tile
    # of the gathered B page; rank streams on the grid, so only the
    # padded hidden/vocab key the entries.
    KernelSpec("lora_epilogue", ("block_v",), ("Hp", "Vp"), 128,
               CHECKS["lora_epilogue"]),
)}
