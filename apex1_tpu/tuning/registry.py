"""Tunable-kernel registry — the schema half of the tuning layer.

One :class:`KernelSpec` per Pallas entry point declares

- which **block parameters** the kernel takes as static arguments
  (``block_q``/``block_k``, ``block_rows``, ...);
- which **padded dims** key its tuning-table entries (the dims that
  actually change the block-planning problem — padded lane/head sizes,
  never raw batch counts);
- the parameter **alignment** the TPU sublane tiling demands; and
- a **VMEM cost model**: a coarse, monotone-in-blocks upper bound on the
  kernel's VMEM frame (double-buffered operand blocks + fp32 scratch +
  live score tiles). Table entries whose recorded blocks exceed the
  recorded generation's ``core.capability.vmem_budget`` under this model
  are rejected at lookup time — a stale entry swept on a bigger chip can
  never push a smaller chip into a Mosaic VMEM OOM; the analytic
  heuristics (``ops/attention._auto_blocks``, ``ops/_common.row_block``,
  ``ops/linear_xent._auto_blocks``) take over instead.

The models are GATING models, not performance models: generous enough
that every block shape the analytic heuristics produce passes, tight
enough that the shapes AOT analysis showed OOMing do not. Measured
preference between valid candidates comes from ``tools/tune_kernels.py``.

Adding a tunable kernel (see docs/ops.md "Block-size tuning"):

1. thread the block sizes as explicit static arguments through the op's
   public entry point (``None`` = consult the table);
2. add a :class:`KernelSpec` here with the padded-dims key and a VMEM
   model;
3. add a sweep case to ``tools/tune_kernels.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Mapping

# fp32 scratch/statistics lanes — every row-stat scratch buffer is
# (rows, 128) fp32 regardless of input dtype
_LANES = 128
_DB = 2  # Pallas double-buffers every blocked operand


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """Declarative description of one tunable Pallas kernel."""

    name: str
    params: tuple[str, ...]       # block parameters, in canonical order
    dims: tuple[str, ...]         # padded dims that key table entries
    align: int                    # every block must be a multiple of this
    # (blocks, dims, esize, budget_bytes) -> (fits, estimated_bytes)
    check: Callable[[Mapping[str, int], Mapping[str, int], int, int],
                    tuple[bool, int]]


def _flash_check(blocks, dims, es, budget):
    """Flash attention frame: q/k/v/o blocks (double-buffered, input
    dtype), fp32 (acc, m, l) scratch, and the live fp32 score + exp
    tiles (bq, bk) the MXU step materializes in vregs/VMEM."""
    bq, bk = blocks["block_q"], blocks["block_k"]
    dp = dims["Dp"]
    est = (_DB * es * (bq * dp + 2 * bk * dp)      # q, k, v in
           + _DB * es * bq * dp                    # o out
           + 4 * (bq * dp + 2 * bq * _LANES)       # acc, m, l scratch
           + 2 * 4 * bq * bk)                      # s and e tiles
    return est <= budget, est


def _row_check(n_passes):
    """Row-wise kernels (softmax/LN/xentropy/rope): ``n_passes`` row-block
    operands of (br, lanes_p), double-buffered, priced fp32 (compute is
    fp32 even for bf16 inputs)."""
    def check(blocks, dims, _es, budget):
        br = blocks["block_rows"]
        est = n_passes * _DB * br * dims["lanes"] * 4
        return est <= budget, est
    return check


def _linear_xent_check(blocks, dims, es, budget):
    """Fused LM-head CE: the binding constraint is the AOT-established
    accumulator bound (``ops/linear_xent._auto_blocks``): the fp32
    dx (bt, Hp) + dw (bv, Hp) accumulators must fit 3/4 of a quarter of
    the VMEM budget; the double-buffered operand blocks and the live
    (bt, bv) logit tile are additionally bounded by the full budget."""
    bt, bv = blocks["block_t"], blocks["block_v"]
    hp = dims["Hp"]
    acc = 4 * (bt + bv) * hp
    est = (acc + _DB * es * (bt + bv) * hp + 2 * 4 * bt * bv)
    ok = est <= budget and acc <= (budget // 4) * 3 // 4
    return ok, est


def _cm_check(blocks, dims, es, budget):
    """Fused-collective chunk matmul (`ops.fused_collective.
    _chunk_matmul`, the tile loop of the ppermute-ring and RDMA
    reduce-scatter forms): x (bm, Kp) and w (Kp, bn) operand blocks
    (double-buffered, input dtype) + the fp32 (bm, bn) output block.
    K is untiled by design (one MXU dot per output tile, no cross-grid
    accumulation), so Kp itself bounds the frame."""
    bm, bn = blocks["block_m"], blocks["block_n"]
    kp = dims["Kp"]
    est = _DB * es * (bm * kp + kp * bn) + _DB * 4 * bm * bn
    return est <= budget, est


def _agf_check(blocks, dims, es, budget):
    """All-gather-fused flash attention (`ops.fused_collective.
    _agf_kernel`): the flash frame plus the carried fp32 (prev_out,
    prev_lse) merge operands and the fp32 merged output block the
    epilogue writes (the plain kernel's output is input-dtype)."""
    ok, est = _flash_check(blocks, dims, es, budget)
    bq, dp = blocks["block_q"], dims["Dp"]
    extra = (_DB * 4 * (bq * dp + bq * _LANES)   # prev_out, prev_lse in
             + _DB * 4 * bq * dp                 # merged fp32 out
             - _DB * es * bq * dp)               # replaces q-dtype out
    est = est + extra
    return est <= budget, est


def _int8_check(blocks, dims, _es, budget):
    """int8 decode GEMM at the kernel's worst-case row count (T <= 1024,
    ``ops/quantized._aligned_for_kernel``): bf16 x block, int8 w block
    (double-buffered), fp32 out block + scales."""
    bn, bk = blocks["block_n"], blocks["block_k"]
    t = 1024
    est = (_DB * (t * bk * 2 + bn * bk * 1 + bn * 4) + t * bn * 4)
    return est <= budget, est


SPECS: dict[str, KernelSpec] = {spec.name: spec for spec in (
    # Sb: power-of-two seq bucket (tuning.seq_bucket) — block preference
    # varies with seq length, so winners never cross shape classes
    KernelSpec("flash_attention", ("block_q", "block_k"), ("Dp", "Sb"),
               16, _flash_check),
    KernelSpec("fused_softmax", ("block_rows",), ("lanes",), 8,
               _row_check(3)),                     # y, dy, dx row blocks
    KernelSpec("layer_norm", ("block_rows",), ("lanes",), 8,
               _row_check(5)),                     # x, dy, dx + dg/db acc
    KernelSpec("rope", ("block_rows",), ("lanes",), 8,
               _row_check(6)),                     # x1, x2, cos, sin, o1, o2
    KernelSpec("xentropy", ("block_rows",), ("lanes",), 8,
               _row_check(2)),                     # x in, dx out (stats
                                                   # are (br, 1) noise)
    KernelSpec("bias_dropout_add", ("block_rows",), ("lanes",), 8,
               _row_check(4)),                     # x, residual, out (+
                                                   # dy/dx in bwd); mask
                                                   # is PRNG-recomputed,
                                                   # never stored
    KernelSpec("linear_xent", ("block_t", "block_v"), ("Hp",), 16,
               _linear_xent_check),
    KernelSpec("fused_collective_matmul", ("block_m", "block_n"),
               ("Kp",), 16, _cm_check),
    KernelSpec("fused_ag_flash", ("block_q", "block_k"), ("Dp", "Sb"),
               16, _agf_check),
    KernelSpec("int8_matmul", ("block_n", "block_k"), ("N", "K"), 128,
               _int8_check),
)}
