"""Testing harness — reference ``apex/transformer/testing/``
(``commons.py``, ``distributed_test_base.py :: DistributedTestBase``,
``standalone_gpt.py``, ``standalone_bert.py``, ``global_vars.py``).

The reference spawns N NCCL processes per test
(``NcclDistributedTestBase``); the TPU-native harness gets N devices in
ONE process: ``--xla_force_host_platform_device_count`` yields a virtual
CPU mesh where every collective (psum/all_gather/ppermute/…) runs for
real (SURVEY.md §4.2.4). ``tests/conftest.py`` applies
`force_virtual_cpu_devices` before any backend is initialized.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import re
from typing import Optional

import numpy as np


def force_virtual_cpu_devices(n: int = 8) -> None:
    """Put N virtual CPU devices under this process — MUST run before the
    first backend use (≙ ``DistributedTestBase.setUpClass`` spawning its
    process group). The container's sitecustomize pins
    ``jax_platforms=axon,cpu`` via jax.config, so the env var alone is
    not enough — we also override through jax.config. A pre-existing
    device-count flag with a different count is replaced, not kept."""
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n}"
    pat = r"--xla_force_host_platform_device_count=\d+"
    if re.search(pat, flags):
        flags = re.sub(pat, flag, flags)
    else:
        flags = f"{flags} {flag}".strip()
    os.environ["XLA_FLAGS"] = flags
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)


def _resolve_cache_dir(default_dir: str | None) -> str:
    """The one copy of the cache-dir policy: ``APEX1_JAX_CACHE_DIR``
    overrides (empty disables), else ``default_dir``, else
    ``<repo>/.jax_cache``. Returns "" when disabled."""
    if default_dir is None:
        default_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")
    return os.environ.get("APEX1_JAX_CACHE_DIR", default_dir)


def enable_persistent_compilation_cache(default_dir: str | None = None
                                        ) -> None:
    """Point JAX's persistent compilation cache at ``APEX1_JAX_CACHE_DIR``
    (or ``default_dir``, or ``<repo>/.jax_cache``). The validation gates on
    a single-core box are compile-dominated; a warm cache is what makes
    re-running them cheap. Set ``APEX1_JAX_CACHE_DIR=`` (empty) to
    disable."""
    cache = _resolve_cache_dir(default_dir)
    if not cache:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", os.path.abspath(cache))
    # 0.1, not the 1.0 JAX default or the 0.5 this first shipped with:
    # the tier-1 suite is hundreds of TINY-model programs whose XLA
    # compiles land in the 0.1-0.5s band — above the threshold they
    # were all recompiled every run, and the suite has grown to ride
    # the 870s cap (measured: the cap is compile-bound, not
    # execute-bound). Sub-0.1s programs stay uncached: for those the
    # disk round-trip costs about what it saves.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)


def child_cache_env(default_dir: str | None = None) -> dict:
    """Env-var form of :func:`enable_persistent_compilation_cache` for
    CHILD processes a test harness spawns (example smokes, multiproc
    clusters): same ``APEX1_JAX_CACHE_DIR`` resolution — empty disables —
    and an already-exported ``JAX_COMPILATION_CACHE_DIR`` wins (exported
    EMPTY counts: that is the operator disabling the cache), so an
    operator pointing everything at a shared cache — or at none — is not
    silently overridden. Merge the returned dict into the child env."""
    # always lower the min-compile-time to catch the sub-second tiny-model
    # compiles these harnesses are made of (JAX's default 1.0s skips them),
    # unless the operator pinned their own threshold (0.1 for the same
    # reason as enable_persistent_compilation_cache)
    out = {}
    if not os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"):
        out["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0.1"
    if "JAX_COMPILATION_CACHE_DIR" in os.environ:
        # presence (not truthiness): an exported-but-EMPTY dir is the
        # operator disabling the cache, mirroring APEX1_JAX_CACHE_DIR= —
        # re-enabling the repo default here would silently override them.
        # Dir (or the disable) inherited via dict(os.environ) launchers.
        return out
    cache = _resolve_cache_dir(default_dir)
    if not cache:
        return out  # cache disabled, but keep the min-compile override
    out["JAX_COMPILATION_CACHE_DIR"] = os.path.abspath(cache)
    return out


def honor_jax_platforms_env() -> None:
    """Re-assert ``JAX_PLATFORMS`` through ``jax.config``: the container's
    sitecustomize pins ``jax_platforms=axon,cpu`` via jax.config, which
    silently overrides the env var. Call before first backend use. The
    update is a silent no-op if a backend is already initialized, so the
    active backend is checked afterwards and a mismatch raises (a silent
    drop would run the wrong backend)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import jax

    jax.config.update("jax_platforms", plat)
    want = [p.strip().lower() for p in plat.split(",") if p.strip()]
    # The axon PJRT plugin is a tunnel to a real TPU: it registers under
    # platform name 'axon' but its backend/devices report as 'tpu'.
    if "axon" in want:
        want.append("tpu")
    got = jax.default_backend()  # forces init under the requested config
    if got.lower() not in want:
        raise RuntimeError(
            f"JAX_PLATFORMS={plat!r} requested but the active backend is "
            f"{got!r} — a backend was initialized before "
            "honor_jax_platforms_env() ran")


def set_random_seed(seed: int):
    """``testing/commons.py :: set_random_seed`` — numpy + a JAX key."""
    import jax

    np.random.seed(seed)
    return jax.random.key(seed)


def assert_devices(n: int):
    import jax

    devs = jax.devices()
    if len(devs) < n:
        raise RuntimeError(
            f"need {n} devices, have {len(devs)} — call "
            "force_virtual_cpu_devices() before any backend use")
    return devs[:n]


@contextlib.contextmanager
def distributed_mesh(dp: int = 1, tp: int = 1, pp: int = 1, cp: int = 1):
    """``DistributedTestBase`` analog: a mesh over virtual devices plus
    `transformer.parallel_state` initialized to match, torn down after."""
    from apex1_tpu.transformer import parallel_state

    n = dp * tp * pp * cp
    devices = assert_devices(n)
    if parallel_state.model_parallel_is_initialized():
        # never adopt leaked state: a (tp, pp) match says nothing about
        # dp/cp, and the documented postcondition (torn down on exit)
        # could not hold for state this context didn't create
        raise RuntimeError(
            "parallel_state already initialized — a previous test leaked "
            "global state; call destroy_model_parallel() first")
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp,
        context_parallel_size=cp, devices=devices)
    try:
        yield mesh
    finally:
        parallel_state.destroy_model_parallel()


@dataclasses.dataclass
class TestArgs:
    """``testing/global_vars.py`` + ``arguments.py`` analog: the knobs the
    reference's standalone models read from Megatron global args."""

    micro_batch_size: int = 2
    global_batch_size: int = 8
    seq_length: int = 32
    padded_vocab_size: int = 256
    num_layers: int = 2
    hidden_size: int = 64
    num_attention_heads: int = 4
    seed: int = 1234


_GLOBAL_ARGS: Optional[TestArgs] = None


def set_global_args(args: TestArgs) -> None:
    global _GLOBAL_ARGS
    _GLOBAL_ARGS = args


def get_args() -> TestArgs:
    """``global_vars.py :: get_args`` — defaults if unset."""
    return _GLOBAL_ARGS if _GLOBAL_ARGS is not None else TestArgs()


def standalone_gpt(args: Optional[TestArgs] = None):
    """``testing/standalone_gpt.py`` analog: (model, synthetic batch,
    params, loss_fn) at test scale."""
    import jax
    import jax.numpy as jnp

    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.gpt2 import GPT2, GPT2Config, gpt2_loss_fn

    a = args or get_args()
    cfg = GPT2Config.tiny(
        vocab_size=a.padded_vocab_size, max_seq_len=a.seq_length,
        num_layers=a.num_layers, num_heads=a.num_attention_heads,
        hidden_size=a.hidden_size, policy=get_policy("O1"))
    model = GPT2(cfg)
    rng = np.random.default_rng(a.seed)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size,
                     (a.micro_batch_size, a.seq_length)), jnp.int32)
    params = model.init(jax.random.key(a.seed), tokens)["params"]
    return model, tokens, params, gpt2_loss_fn(model)


def standalone_bert(args: Optional[TestArgs] = None):
    """``testing/standalone_bert.py`` analog."""
    import jax
    import jax.numpy as jnp

    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.bert import (BertConfig, BertPretrain,
                                       bert_pretrain_loss_fn)

    a = args or get_args()
    cfg = BertConfig.tiny(
        vocab_size=a.padded_vocab_size, max_seq_len=a.seq_length,
        num_layers=a.num_layers, num_heads=a.num_attention_heads,
        hidden_size=a.hidden_size, policy=get_policy("O1"))
    model = BertPretrain(cfg)
    rng = np.random.default_rng(a.seed)
    B, S = a.micro_batch_size, a.seq_length
    batch = {
        "tokens": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S)),
                              jnp.int32),
        "mlm_labels": jnp.asarray(
            np.where(rng.random((B, S)) < 0.15,
                     rng.integers(0, cfg.vocab_size, (B, S)), -1),
            jnp.int32),
        "nsp_labels": jnp.asarray(rng.integers(0, 2, (B,)), jnp.int32),
    }
    params = model.init(jax.random.key(a.seed), batch["tokens"])["params"]
    return model, batch, params, bert_pretrain_loss_fn(model)


def print_separator(message: str) -> None:
    """``testing/commons.py :: print_separator``."""
    print(f"{' ' + message + ' ':-^72}")
