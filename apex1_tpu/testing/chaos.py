"""Deterministic chaos-injection harness — every recovery path in
`apex1_tpu.resilience` is EXERCISED in tier-1 on CPU, not just trusted
on silicon.

All injection is seed-keyed and pure-function-of-its-inputs: two runs
with the same seed inject the same faults at the same steps, which is
what makes "SIGTERM mid-run, resume, bit-identical to uninterrupted"
an assertable property instead of a flaky one.

Fault classes (one helper per class, composable):

- **NaN/Inf poisoning** (`poison_at_steps`, traced): multiply a loss /
  grad tree by a factor that is NaN exactly at the listed steps —
  drives the sentinel's skip/rollback/abort ladder from inside jit.
- **checkpoint corruption** (`truncate_checkpoint`,
  `bitflip_checkpoint`, host): deterministic file pick + deterministic
  byte, so `find_restorable`'s backward scan is tested against real
  on-disk damage.
- **simulated preemption** (`sigterm_self_at`, host): SIGTERM delivered
  to the current process at a step boundary, exercising
  `PreemptionHandler` + the resumable-exit contract.
- **transient backend errors** (`Flaky`): a callable that raises
  `resilience.TransientError` for its first N calls — verifies
  retry/backoff policies actually retry, back off, and give up on
  schedule.
- **serving faults** (`ServingFault` family, host): hooks the
  `serving.replica.ReplicaSupervisor` calls at its submit/step
  boundaries — `ReplicaKill` (crash at an exact step), `ReplicaHang`
  (stall past the watchdog), `SlowReplica` (straggler injecting
  per-step delay), `PoisonPill` (a marked request whose ADMISSION
  kills the replica, every time, on every replica — the quarantine
  fixture). `kill_schedule` derives (replica, step) picks from a seed
  for the bench's chaos-on mode. `toy_decoder` is the matching
  fixture model: a deterministic history-dependent cached decoder that
  compiles in milliseconds, so multi-replica drills stay cheap.

``python -m apex1_tpu.testing.chaos --smoke`` runs the two headline
TRAINING recoveries end-to-end (injected-NaN rollback +
corrupt-checkpoint fallback scan) in <30 s on CPU — the
``== chaos smoke ==`` step in ``tools/check_all.sh``;
``--serve-smoke`` runs the SERVING headline (2-replica frontend,
replica killed mid-stream → every request completes token-identical
to an uninterrupted run + poison-pill quarantine) in <10 s — the
``== serving chaos smoke ==`` step.
"""

from __future__ import annotations

import os
import signal
from typing import Callable, Optional, Sequence

import numpy as np

from apex1_tpu.resilience.manifest import read_manifest
from apex1_tpu.resilience.retry import TransientError, _mix32

__all__ = [
    "poison_at_steps", "poison_tree_at_steps", "truncate_checkpoint",
    "bitflip_checkpoint", "sigterm_self_at", "Flaky", "TransientError",
    "ServingFault", "ChaosSchedule", "ReplicaKill", "ReplicaHang",
    "SlowReplica", "PoisonPill", "HandoffWindowKill",
    "HandoffCorruption", "kill_schedule", "shrink_schedule",
    "toy_decoder",
]


# -- traced-side injection --------------------------------------------------

def poison_at_steps(value, step, steps: Sequence[int], *,
                    poison: float = float("nan")):
    """Return ``value`` except at the listed ``steps``, where every
    element becomes ``poison`` (NaN default, pass ``float('inf')`` for
    Inf). ``step`` may be traced (the train state's step counter);
    ``steps`` is static. Identity (and jit-cache-identical) when
    ``steps`` is empty."""
    import jax.numpy as jnp

    if not len(steps):
        return value
    v = jnp.asarray(value)
    hits = jnp.asarray(list(steps), jnp.int32)
    hit = jnp.any(hits == jnp.asarray(step, jnp.int32))
    bad = jnp.asarray(poison, v.dtype)
    return jnp.where(hit, jnp.full_like(v, bad), v)


def poison_tree_at_steps(tree, step, steps: Sequence[int], *,
                         poison: float = float("nan")):
    """`poison_at_steps` over every floating leaf of a pytree (poisoned
    grads, not just a poisoned loss)."""
    import jax
    import jax.numpy as jnp

    def leaf(x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return x
        return poison_at_steps(x, step, steps, poison=poison)

    return jax.tree_util.tree_map(leaf, tree)


# -- on-disk corruption -----------------------------------------------------

def _pick_payload_file(ckpt_dir: str, seed: int) -> str:
    """Deterministic payload-file pick from the checkpoint's own
    manifest: the largest file (ties broken by path), rotated by seed —
    corruption always lands on bytes the integrity manifest covers."""
    m = read_manifest(ckpt_dir)
    files = sorted(m.files, key=lambda e: (-e["bytes"], e["path"]))
    if not files:
        raise ValueError(f"{ckpt_dir}: no payload files to corrupt")
    biggest = [e for e in files if e["bytes"] == files[0]["bytes"]]
    pick = biggest[_mix32(seed) % len(biggest)]
    return os.path.join(ckpt_dir, pick["path"])


def truncate_checkpoint(ckpt_dir: str | os.PathLike, *, seed: int = 0,
                        keep_fraction: float = 0.5) -> str:
    """Truncate a manifest-covered payload file to ``keep_fraction`` of
    its size (a killed writer / torn copy). Returns the damaged path."""
    path = _pick_payload_file(os.fspath(ckpt_dir), seed)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(int(size * keep_fraction))
    return path


def bitflip_checkpoint(ckpt_dir: str | os.PathLike, *, seed: int = 0
                       ) -> str:
    """XOR one deterministic byte of a payload file (cosmic-ray /
    bit-rot model). File size is unchanged — only the content digest can
    catch this. Returns the damaged path."""
    path = _pick_payload_file(os.fspath(ckpt_dir), seed)
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path}: empty file, nothing to flip")
    off = _mix32(seed ^ 0xB17F11B) % size
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))
    return path


# -- preemption + transient faults ------------------------------------------

def sigterm_self_at(step: int, at_step: Optional[int],
                    *, signum: int = signal.SIGTERM) -> bool:
    """Deliver ``signum`` to THIS process when ``step == at_step`` (the
    simulated mid-run preemption). Returns True when fired. A no-op
    (False) when ``at_step`` is None — training loops can leave the call
    in place, keyed off an env var the chaos test sets."""
    if at_step is None or int(step) != int(at_step):
        return False
    os.kill(os.getpid(), signum)
    return True


class Flaky:
    """Wrap ``fn`` to raise `TransientError` on its first ``fails``
    calls, then pass through — the backend-unreachable model. The call
    log (`attempts`, `failures`) is what retry/backoff tests assert."""

    def __init__(self, fn: Callable, *, fails: int = 2,
                 exc: type = TransientError):
        self.fn = fn
        self.fails = int(fails)
        self.exc = exc
        self.attempts = 0
        self.failures = 0

    def __call__(self, *args, **kwargs):
        self.attempts += 1
        if self.attempts <= self.fails:
            self.failures += 1
            raise self.exc(
                f"injected transient failure {self.failures}/{self.fails}")
        return self.fn(*args, **kwargs)


# -- serving faults ---------------------------------------------------------

class ServingFault:
    """Hook surface `serving.replica.ReplicaSupervisor` calls at its
    two fault boundaries. The base class is a no-op; subclasses raise
    or sleep at EXACT (replica, step) coordinates — deterministic, so
    "kill a replica mid-stream, every token bit-identical" is an
    assertable property, not a flaky one."""

    def on_step(self, replica_id: int, step: int) -> None:
        """Called once per serve iteration, before the engine step."""

    def on_submit(self, replica_id: int, sub) -> None:
        """Called just before a submission is admitted to the engine
        (``sub`` is a `serving.replica.Submission`)."""

    def on_handoff(self, replica_id: int, req_id: int, page) -> None:
        """Called by `serving.disagg.DisaggFrontend` in the handoff
        window — after a prefill replica extracted a KV page for
        ``req_id`` but BEFORE the decode pool acknowledged it
        (``page`` is a `serving.disagg.kv_transfer.KVPage`, mutable
        host copy). Raising `ReplicaKilled` here models the source
        dying mid-transfer; mutating ``page.lane`` models a torn/
        corrupt transfer the arrival re-digest must catch."""


class ChaosSchedule(ServingFault):
    """Compose several faults; each sees every hook."""

    def __init__(self, faults: Sequence[ServingFault]):
        self.faults = list(faults)

    def on_step(self, replica_id, step):
        for f in self.faults:
            f.on_step(replica_id, step)

    def on_submit(self, replica_id, sub):
        for f in self.faults:
            f.on_submit(replica_id, sub)

    def on_handoff(self, replica_id, req_id, page):
        for f in self.faults:
            f.on_handoff(replica_id, req_id, page)


class ReplicaKill(ServingFault):
    """Crash replica ``replica`` at its serve step ``at_step`` — once
    (the restarted generation starts its step count fresh but the
    fault has already fired; ``repeat=True`` kills every generation,
    the crash-loop fixture)."""

    def __init__(self, replica: int, at_step: int, *,
                 repeat: bool = False):
        self.replica = int(replica)
        self.at_step = int(at_step)
        self.repeat = bool(repeat)
        self.fired = 0

    def on_step(self, replica_id, step):
        if replica_id != self.replica or step != self.at_step:
            return
        if self.fired and not self.repeat:
            return
        self.fired += 1
        from apex1_tpu.serving.replica import ReplicaKilled
        raise ReplicaKilled(
            f"chaos: killed replica {replica_id} at step {step}")


class ReplicaHang(ServingFault):
    """Stall replica ``replica`` at step ``at_step`` for ``hang_s``
    (once) — the watchdog-path fixture: the step eventually returns,
    but past the supervision deadline, which is exactly the signature
    of a wedged-then-recovered decode the supervisor must NOT trust."""

    def __init__(self, replica: int, at_step: int, *,
                 hang_s: float = 0.2):
        self.replica = int(replica)
        self.at_step = int(at_step)
        self.hang_s = float(hang_s)
        self.fired = 0

    def on_step(self, replica_id, step):
        if (replica_id == self.replica and step == self.at_step
                and not self.fired):
            self.fired += 1
            import time
            time.sleep(self.hang_s)


class SlowReplica(ServingFault):
    """Straggler model: ``delay_s`` injected into every step of
    ``replica`` in ``[from_step, to_step)`` — below the watchdog
    threshold, so the replica stays 'healthy' while its latency blows
    hedging budgets (the hedged-dispatch fixture)."""

    def __init__(self, replica: int, *, delay_s: float = 0.02,
                 from_step: int = 0, to_step: Optional[int] = None):
        self.replica = int(replica)
        self.delay_s = float(delay_s)
        self.from_step = int(from_step)
        self.to_step = to_step

    def on_step(self, replica_id, step):
        if replica_id != self.replica or step < self.from_step:
            return
        if self.to_step is not None and step >= self.to_step:
            return
        import time
        time.sleep(self.delay_s)


class PoisonPill(ServingFault):
    """A request whose ADMISSION deterministically kills the replica —
    every admission, every replica, every restart: the fixture for the
    supervisor's quarantine ladder (resubmit -> kill again -> evicted
    as poisoned instead of crash-looping forever). Marked by a token:
    any request whose prompt contains ``poison_token`` is the pill."""

    def __init__(self, poison_token: int):
        self.poison_token = int(poison_token)
        self.fired = 0

    def on_submit(self, replica_id, sub):
        if self.poison_token in np.asarray(sub.tokens).tolist():
            self.fired += 1
            from apex1_tpu.serving.replica import PoisonedRequest
            raise PoisonedRequest(
                f"chaos: poison token {self.poison_token} in request "
                f"{sub.req_id}", req_id=sub.req_id)


class HandoffWindowKill(ServingFault):
    """Kill the SOURCE prefill replica in the handoff window — after
    its prefill completed but before the decode pool acknowledged the
    KV page (the ISSUE 16 regression fixture: the request must be
    re-routed, never stranded). Fires on the ``at_handoff``-th handoff
    overall (0 = the first); ``repeat=True`` kills every handoff from
    then on (the crash-loop form — bounded by the frontend's
    ``max_handoff_attempts``)."""

    def __init__(self, at_handoff: int = 0, *, repeat: bool = False):
        self.at_handoff = int(at_handoff)
        self.repeat = bool(repeat)
        self.seen = 0
        self.fired = 0

    def on_handoff(self, replica_id, req_id, page):
        k = self.seen
        self.seen += 1
        if k < self.at_handoff or (self.fired and not self.repeat):
            return
        self.fired += 1
        from apex1_tpu.serving.replica import ReplicaKilled
        raise ReplicaKilled(
            f"chaos: killed replica {replica_id} in the handoff window "
            f"of request {req_id} (handoff #{k})")


class HandoffCorruption(ServingFault):
    """Flip one byte of a transferred KV page AFTER its departure
    digests were taken (the torn/bit-rot transfer model) — the decode
    pool's arrival re-digest must surface a typed `HandoffError`, never
    silently garbage tokens. Fires on the ``at_handoff``-th handoff
    overall, once."""

    def __init__(self, at_handoff: int = 0):
        self.at_handoff = int(at_handoff)
        self.seen = 0
        self.fired = 0

    def on_handoff(self, replica_id, req_id, page):
        k = self.seen
        self.seen += 1
        if k != self.at_handoff or self.fired:
            return
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(page.lane)
        for i, leaf in enumerate(leaves):
            arr = np.array(leaf)         # np.asarray views of device
            #  arrays are read-only; a real copy is the writable
            #  "wire buffer" the flipped bit lands in
            flat = arr.reshape(-1).view(np.uint8)
            if flat.size:
                flat[0] ^= 0xFF
                leaves[i] = arr
                page.lane = jax.tree_util.tree_unflatten(treedef, leaves)
                self.fired += 1
                return


def shrink_schedule(seed: int, *, n_devices: int, lo: int, hi: int,
                    survivors: Optional[int] = None
                    ) -> tuple[int, int]:
    """Seed-keyed mid-run FLEET SHRINK pick for the elastic drill
    (`resilience.elastic`): ``(kill_step, n_survivors)`` — the step at
    which the training job dies, and the device count it must resume
    on. The step is avalanche-derived from the seed (same family as
    `kill_schedule`); survivors defaults to the largest proper divisor
    of ``n_devices`` (kill half an even fleet — the k-of-n drill's
    canonical k = n/2) so the planner always has a clean mesh product
    to re-plan onto. Deterministic: the drill's "kill mid-run" is an
    assertable property, not a flaky one."""
    if hi <= lo:
        raise ValueError(f"need hi > lo, got [{lo}, {hi})")
    step = lo + _mix32(seed ^ 0xE1A57C) % (hi - lo)
    if survivors is None:
        divs = [d for d in range(1, n_devices) if n_devices % d == 0]
        if not divs:
            raise ValueError(
                f"n_devices={n_devices} has no proper divisor to "
                "shrink onto")
        survivors = max(divs)
    if not 1 <= survivors < n_devices:
        raise ValueError(
            f"survivors={survivors} must be in [1, {n_devices})")
    return step, int(survivors)


def kill_schedule(seed: int, *, n_replicas: int, lo: int, hi: int
                  ) -> ReplicaKill:
    """Seed-derived `ReplicaKill`: replica and step picked by the same
    avalanche hash the rest of the chaos harness uses, so a bench's
    ``--chaos`` run is reproducible from its seed alone."""
    if hi <= lo:
        raise ValueError(f"need hi > lo, got [{lo}, {hi})")
    replica = _mix32(seed ^ 0xC0FFEE) % int(n_replicas)
    step = lo + _mix32(seed ^ 0xDEAD10C) % (hi - lo)
    return ReplicaKill(replica, step)


def toy_decoder(vocab_size: int = 61):
    """A deterministic cached toy decoder ``(apply_fn, make_cache,
    params)`` with the `models.generate` decoder contract — history-
    dependent logits (an avalanche hash of the causal prefix sum), so
    stale-cache and lost-stream bugs change tokens, but compiles in
    milliseconds: multi-replica chaos drills pay supervisor cost, not
    XLA cost. The cache stores one small integer per position, so the
    int8 ``cache_dtype`` profile is EXACT here (values < 128)."""
    import jax
    import jax.numpy as jnp

    def make_cache(batch: int, max_len: int, dtype=None):
        dt = jnp.float32 if dtype is None else dtype
        return {"toy": {"h": jnp.zeros((batch, 1, max_len, 1), dt)}}

    def apply_fn(params, tokens, cache, cache_index, positions=None,
                 chunk_decode=False):
        h = cache["toy"]["h"]                       # (B, 1, Smax, 1)
        B, S = tokens.shape
        idx = jnp.asarray(cache_index, jnp.int32)
        vals = (tokens + 1).astype(h.dtype).reshape(B, 1, S, 1)
        zero = jnp.int32(0)
        h = jax.lax.dynamic_update_slice(h, vals, (zero, zero, idx, zero))
        # causal-prefix sum per query: pos <= idx + j (the chunk-verify
        # horizon), over the UPDATED cache so each query sees itself —
        # pad/stale residue beyond the horizon never enters
        pos = jnp.arange(h.shape[2], dtype=jnp.int32)
        qpos = idx + jnp.arange(S, dtype=jnp.int32)
        mask = (pos[None, :] <= qpos[:, None]).astype(jnp.float32)
        hv = h[:, 0, :, 0].astype(jnp.float32)
        s = jnp.einsum("bp,sp->bs", hv, mask)       # (B, S)
        su = (s.astype(jnp.uint32) * params["w"].astype(jnp.uint32))
        v = jnp.arange(vocab_size, dtype=jnp.uint32)
        logits = -(((su[..., None] * jnp.uint32(2654435761)
                     + (v + 1) * jnp.uint32(40499))
                    % jnp.uint32(977)).astype(jnp.float32))
        return logits, {"toy": {"h": h}}

    params = {"w": jnp.ones((), jnp.uint32)}
    return apply_fn, make_cache, params


# -- smoke entry point (check_all.sh `== chaos smoke ==`) -------------------

def _smoke() -> int:
    """Two headline recoveries, tiny shapes, CPU, <30 s:
    (1) injected-NaN grads → device-side skip → second hit → rollback to
    last-good with a banked diagnostic; (2) newest checkpoint truncated
    AND the one before bit-flipped → `find_restorable` selects the older
    valid one and restore round-trips."""
    import tempfile

    from apex1_tpu.testing import force_virtual_cpu_devices

    force_virtual_cpu_devices(1)
    import jax
    import jax.numpy as jnp

    from apex1_tpu.amp import Amp
    from apex1_tpu.optim.fused_sgd import fused_sgd
    from apex1_tpu.resilience import (ResilientCheckpointer, Sentinel,
                                      find_restorable, sentinel_init)

    amp = Amp(tx=fused_sgd(0.1), opt_level="O0")
    state = amp.init({"w": jnp.ones((8,), jnp.float32)})

    def loss_fn(p, x, step):
        loss = jnp.sum(jnp.square(p["w"])) * x
        return poison_at_steps(loss, step, (3, 4))

    with tempfile.TemporaryDirectory() as d:
        ck = ResilientCheckpointer(d, keep=4)
        sent = Sentinel(ck, check_every=1, rollback_after=2)
        guarded = jax.jit(sent.guard(amp.make_train_step(loss_fn)))
        carry = (state, sentinel_init())
        rolled_back = False
        i = 0
        while i < 6 and not rolled_back:
            carry, _m = guarded(carry, jnp.float32(1.0),
                                carry[0].step)
            ck.save_sync(int(carry[0].step), carry[0],
                         meta={"data_step": i + 1})
            if sent.poll(carry[1]) == "rollback":
                good, manifest, s0 = sent.rollback(template=carry[0])
                carry = (good, s0)
                rolled_back = True
            i += 1
        assert rolled_back, "NaN injection never escalated to rollback"
        assert sent.records[-1]["action"] == "rollback"
        assert np.isfinite(np.asarray(carry[0].params["w"])).all()
        print(f"chaos smoke [1/2] OK: NaN@step3,4 -> skip -> rollback to "
              f"step {manifest.step}, diagnostic banked "
              f"({sent.records[-1].get('path', '<memory>')})")

        # (2) damage the two newest checkpoints two different ways
        dirs = sorted(p for p in os.listdir(d) if p.startswith("step_"))
        assert len(dirs) >= 3
        truncate_checkpoint(os.path.join(d, dirs[-1]))
        bitflip_checkpoint(os.path.join(d, dirs[-2]))
        best = find_restorable(d)
        assert best is not None and os.path.basename(best) == dirs[-3], \
            f"expected fallback to {dirs[-3]}, got {best}"
        restored, man = ck.restore(template=carry[0], path=best)
        assert int(man.step) == int(restored.step)
        ck.close()
        print(f"chaos smoke [2/2] OK: truncated {dirs[-1]} + bit-flipped "
              f"{dirs[-2]} -> find_restorable fell back to {dirs[-3]}")
    return 0


def _serve_smoke() -> int:
    """The serving headline recoveries, toy decoder, CPU, <10 s:
    (1) 2-replica frontend, replica killed mid-stream → restarted with
    a fresh engine (exactly two executables per generation), in-flight
    requests resubmitted → every request completes TOKEN-IDENTICAL to
    an uninterrupted single-engine run, at temperature > 0 (the pinned
    per-request seed, not greedy luck); (2) a poison-pill request that
    kills its replica on every admission is quarantined after the
    configured threshold instead of crash-looping."""
    from apex1_tpu.testing import (enable_persistent_compilation_cache,
                                   force_virtual_cpu_devices)

    force_virtual_cpu_devices(1)
    # every fresh engine (replica, restart, reference) re-traces the
    # same two tiny executables; the persistent cache collapses the
    # repeat XLA compiles so the drill's cost is supervision, not XLA
    enable_persistent_compilation_cache()

    from apex1_tpu.serving import (Engine, EngineConfig, FrontendConfig,
                                   ReplicaConfig, ServingFrontend)

    apply_fn, make_cache, params = toy_decoder()
    ecfg = EngineConfig(max_slots=3, max_len=48, prefill_chunk=4,
                        vocab_size=61, temperature=0.8, seed=7)

    def make_engine():
        return Engine(apply_fn, make_cache, params, ecfg)

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 61, (n,)).astype(np.int32)
               for n in (3, 7, 5, 9, 4, 6)]

    kill = kill_schedule(seed=20260804, n_replicas=2, lo=4, hi=9)
    front = ServingFrontend(
        make_engine,
        FrontendConfig(n_replicas=2, capacity_per_replica=8,
                       hedge_after_s=None,
                       replica=ReplicaConfig(watchdog_s=30.0)),
        fault=kill)
    rids = [front.submit(p, max_new_tokens=6 + i % 4)
            for i, p in enumerate(prompts)]
    front.run_until_drained(timeout_s=60.0)

    ref = make_engine()
    for i, (p, rid) in enumerate(zip(prompts, rids)):
        sub = front._subs[rid]
        assert front.poll(rid).status == "done", front.poll(rid)
        rr = ref.submit(p, max_new_tokens=sub.max_new_tokens,
                        seed=sub.seed)
        ref.run(max_steps=100)
        got, want = front.poll(rid).tokens, ref.results[rr].tokens
        assert np.array_equal(got, want), \
            f"req {rid}: {got} != uninterrupted {want}"
    restarts = front.metrics.summary()["counters"]["replica_restarts"]
    assert kill.fired == 1 and restarts == 1, (kill.fired, restarts)
    for rep in front.replicas:
        assert rep.trace_counts() == {"prefill": 1, "decode": 1}, \
            (rep.replica_id, rep.trace_counts())
    print(f"serving chaos smoke [1/2] OK: replica {kill.replica} killed "
          f"at step {kill.at_step} -> restarted (fresh 2-executable "
          f"engine), {len(rids)} streams token-identical to the "
          f"uninterrupted run at temperature 0.8")

    # (2) poison-pill quarantine: admission kills the replica every
    # time; after poison_threshold deaths the request is evicted as
    # poisoned and the replica serves on
    pill = PoisonPill(poison_token=60)
    front2 = ServingFrontend(
        make_engine,
        FrontendConfig(n_replicas=1, capacity_per_replica=8,
                       hedge_after_s=None,
                       replica=ReplicaConfig(watchdog_s=30.0,
                                             max_restarts=5,
                                             poison_threshold=1)),
        fault=pill)
    good = front2.submit(prompts[0], max_new_tokens=5)
    bad = front2.submit(np.asarray([60, 1, 2], np.int32),
                        max_new_tokens=5)
    front2.run_until_drained(timeout_s=60.0)
    assert front2.poll(good).status == "done"
    res = front2.poll(bad)
    assert res.status == "evicted" and "poisoned" in res.reason, res
    assert pill.fired == 2, pill.fired      # threshold + 1 admissions
    print(f"serving chaos smoke [2/2] OK: poison pill killed its "
          f"replica {pill.fired}x -> quarantined ('{res.reason}'), "
          f"good request still served")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="run the two headline training recovery paths "
                         "(CPU, <30s)")
    ap.add_argument("--serve-smoke", action="store_true",
                    help="run the serving recovery paths: replica-kill "
                         "token parity + poison quarantine (CPU, <10s)")
    args = ap.parse_args(argv)
    if args.smoke:
        return _smoke()
    if args.serve_smoke:
        return _serve_smoke()
    ap.print_help()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
