"""Deterministic chaos-injection harness — every recovery path in
`apex1_tpu.resilience` is EXERCISED in tier-1 on CPU, not just trusted
on silicon.

All injection is seed-keyed and pure-function-of-its-inputs: two runs
with the same seed inject the same faults at the same steps, which is
what makes "SIGTERM mid-run, resume, bit-identical to uninterrupted"
an assertable property instead of a flaky one.

Fault classes (one helper per class, composable):

- **NaN/Inf poisoning** (`poison_at_steps`, traced): multiply a loss /
  grad tree by a factor that is NaN exactly at the listed steps —
  drives the sentinel's skip/rollback/abort ladder from inside jit.
- **checkpoint corruption** (`truncate_checkpoint`,
  `bitflip_checkpoint`, host): deterministic file pick + deterministic
  byte, so `find_restorable`'s backward scan is tested against real
  on-disk damage.
- **simulated preemption** (`sigterm_self_at`, host): SIGTERM delivered
  to the current process at a step boundary, exercising
  `PreemptionHandler` + the resumable-exit contract.
- **transient backend errors** (`Flaky`): a callable that raises
  `resilience.TransientError` for its first N calls — verifies
  retry/backoff policies actually retry, back off, and give up on
  schedule.

``python -m apex1_tpu.testing.chaos --smoke`` runs the two headline
recoveries end-to-end (injected-NaN rollback + corrupt-checkpoint
fallback scan) in <30 s on CPU — the ``== chaos smoke ==`` step in
``tools/check_all.sh``.
"""

from __future__ import annotations

import os
import signal
from typing import Callable, Optional, Sequence

import numpy as np

from apex1_tpu.resilience.manifest import read_manifest
from apex1_tpu.resilience.retry import TransientError, _mix32

__all__ = [
    "poison_at_steps", "poison_tree_at_steps", "truncate_checkpoint",
    "bitflip_checkpoint", "sigterm_self_at", "Flaky", "TransientError",
]


# -- traced-side injection --------------------------------------------------

def poison_at_steps(value, step, steps: Sequence[int], *,
                    poison: float = float("nan")):
    """Return ``value`` except at the listed ``steps``, where every
    element becomes ``poison`` (NaN default, pass ``float('inf')`` for
    Inf). ``step`` may be traced (the train state's step counter);
    ``steps`` is static. Identity (and jit-cache-identical) when
    ``steps`` is empty."""
    import jax.numpy as jnp

    if not len(steps):
        return value
    v = jnp.asarray(value)
    hits = jnp.asarray(list(steps), jnp.int32)
    hit = jnp.any(hits == jnp.asarray(step, jnp.int32))
    bad = jnp.asarray(poison, v.dtype)
    return jnp.where(hit, jnp.full_like(v, bad), v)


def poison_tree_at_steps(tree, step, steps: Sequence[int], *,
                         poison: float = float("nan")):
    """`poison_at_steps` over every floating leaf of a pytree (poisoned
    grads, not just a poisoned loss)."""
    import jax
    import jax.numpy as jnp

    def leaf(x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return x
        return poison_at_steps(x, step, steps, poison=poison)

    return jax.tree_util.tree_map(leaf, tree)


# -- on-disk corruption -----------------------------------------------------

def _pick_payload_file(ckpt_dir: str, seed: int) -> str:
    """Deterministic payload-file pick from the checkpoint's own
    manifest: the largest file (ties broken by path), rotated by seed —
    corruption always lands on bytes the integrity manifest covers."""
    m = read_manifest(ckpt_dir)
    files = sorted(m.files, key=lambda e: (-e["bytes"], e["path"]))
    if not files:
        raise ValueError(f"{ckpt_dir}: no payload files to corrupt")
    biggest = [e for e in files if e["bytes"] == files[0]["bytes"]]
    pick = biggest[_mix32(seed) % len(biggest)]
    return os.path.join(ckpt_dir, pick["path"])


def truncate_checkpoint(ckpt_dir: str | os.PathLike, *, seed: int = 0,
                        keep_fraction: float = 0.5) -> str:
    """Truncate a manifest-covered payload file to ``keep_fraction`` of
    its size (a killed writer / torn copy). Returns the damaged path."""
    path = _pick_payload_file(os.fspath(ckpt_dir), seed)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(int(size * keep_fraction))
    return path


def bitflip_checkpoint(ckpt_dir: str | os.PathLike, *, seed: int = 0
                       ) -> str:
    """XOR one deterministic byte of a payload file (cosmic-ray /
    bit-rot model). File size is unchanged — only the content digest can
    catch this. Returns the damaged path."""
    path = _pick_payload_file(os.fspath(ckpt_dir), seed)
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path}: empty file, nothing to flip")
    off = _mix32(seed ^ 0xB17F11B) % size
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))
    return path


# -- preemption + transient faults ------------------------------------------

def sigterm_self_at(step: int, at_step: Optional[int],
                    *, signum: int = signal.SIGTERM) -> bool:
    """Deliver ``signum`` to THIS process when ``step == at_step`` (the
    simulated mid-run preemption). Returns True when fired. A no-op
    (False) when ``at_step`` is None — training loops can leave the call
    in place, keyed off an env var the chaos test sets."""
    if at_step is None or int(step) != int(at_step):
        return False
    os.kill(os.getpid(), signum)
    return True


class Flaky:
    """Wrap ``fn`` to raise `TransientError` on its first ``fails``
    calls, then pass through — the backend-unreachable model. The call
    log (`attempts`, `failures`) is what retry/backoff tests assert."""

    def __init__(self, fn: Callable, *, fails: int = 2,
                 exc: type = TransientError):
        self.fn = fn
        self.fails = int(fails)
        self.exc = exc
        self.attempts = 0
        self.failures = 0

    def __call__(self, *args, **kwargs):
        self.attempts += 1
        if self.attempts <= self.fails:
            self.failures += 1
            raise self.exc(
                f"injected transient failure {self.failures}/{self.fails}")
        return self.fn(*args, **kwargs)


# -- smoke entry point (check_all.sh `== chaos smoke ==`) -------------------

def _smoke() -> int:
    """Two headline recoveries, tiny shapes, CPU, <30 s:
    (1) injected-NaN grads → device-side skip → second hit → rollback to
    last-good with a banked diagnostic; (2) newest checkpoint truncated
    AND the one before bit-flipped → `find_restorable` selects the older
    valid one and restore round-trips."""
    import tempfile

    from apex1_tpu.testing import force_virtual_cpu_devices

    force_virtual_cpu_devices(1)
    import jax
    import jax.numpy as jnp

    from apex1_tpu.amp import Amp
    from apex1_tpu.optim.fused_sgd import fused_sgd
    from apex1_tpu.resilience import (ResilientCheckpointer, Sentinel,
                                      find_restorable, sentinel_init)

    amp = Amp(tx=fused_sgd(0.1), opt_level="O0")
    state = amp.init({"w": jnp.ones((8,), jnp.float32)})

    def loss_fn(p, x, step):
        loss = jnp.sum(jnp.square(p["w"])) * x
        return poison_at_steps(loss, step, (3, 4))

    with tempfile.TemporaryDirectory() as d:
        ck = ResilientCheckpointer(d, keep=4)
        sent = Sentinel(ck, check_every=1, rollback_after=2)
        guarded = jax.jit(sent.guard(amp.make_train_step(loss_fn)))
        carry = (state, sentinel_init())
        rolled_back = False
        i = 0
        while i < 6 and not rolled_back:
            carry, _m = guarded(carry, jnp.float32(1.0),
                                carry[0].step)
            ck.save_sync(int(carry[0].step), carry[0],
                         meta={"data_step": i + 1})
            if sent.poll(carry[1]) == "rollback":
                good, manifest, s0 = sent.rollback(template=carry[0])
                carry = (good, s0)
                rolled_back = True
            i += 1
        assert rolled_back, "NaN injection never escalated to rollback"
        assert sent.records[-1]["action"] == "rollback"
        assert np.isfinite(np.asarray(carry[0].params["w"])).all()
        print(f"chaos smoke [1/2] OK: NaN@step3,4 -> skip -> rollback to "
              f"step {manifest.step}, diagnostic banked "
              f"({sent.records[-1].get('path', '<memory>')})")

        # (2) damage the two newest checkpoints two different ways
        dirs = sorted(p for p in os.listdir(d) if p.startswith("step_"))
        assert len(dirs) >= 3
        truncate_checkpoint(os.path.join(d, dirs[-1]))
        bitflip_checkpoint(os.path.join(d, dirs[-2]))
        best = find_restorable(d)
        assert best is not None and os.path.basename(best) == dirs[-3], \
            f"expected fallback to {dirs[-3]}, got {best}"
        restored, man = ck.restore(template=carry[0], path=best)
        assert int(man.step) == int(restored.step)
        ck.close()
        print(f"chaos smoke [2/2] OK: truncated {dirs[-1]} + bit-flipped "
              f"{dirs[-2]} -> find_restorable fell back to {dirs[-3]}")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="run the two headline recovery paths (CPU, <30s)")
    args = ap.parse_args(argv)
    if args.smoke:
        return _smoke()
    ap.print_help()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
